"""Unified shard-leg batching plane (exec/batcher.py, ISSUE r11).

Two layers of coverage:
- StubBackend tests exercise the batcher's composition contract with no
  device (or jax) dependency: deterministic windows via window > 0,
  mixed-kind grouping (Count + Row + Sum + TopN legs drained together
  land in per-kind groups, one backend dispatch each), identical-leg
  dedupe for the synchronous kinds, per-slot query-id result scatter,
  error isolation (one bad leg fails only its submitter), and the
  occupancy/coalesce telemetry.
- Differential tests (skipped where the device backend can't import)
  prove batched results identical to the unbatched path for
  Count/Row/Sum/Min/Max/TopN under concurrent submission — the ISSUE
  r11 acceptance bar.
"""

import threading
import time

import numpy as np
import pytest

from pilosa_tpu.exec.batcher import CountBatcher, ShardLegBatcher
from pilosa_tpu.utils.stats import global_stats


class StubBackend:
    """Deterministic fake of the device backend's batched entry points.

    Count calls are ints; a count resolves to call*10 so scatter order is
    checkable. Row calls resolve to ("row", call). BSI aggregates return
    (value, count) derived from the field name; TopN returns a ranked
    list the batcher must trim per leg. Every dispatch is recorded."""

    BAD = object()  # a call whose presence fails any dispatch it rides in

    def __init__(self):
        self.count_groups = []
        self.row_groups = []
        self.bsi_calls = []
        self.topn_calls = []
        self.individual_counts = []
        self.fail_count_groups = False

    # -- count legs --------------------------------------------------------

    def count_batch_async(self, index, calls, shards):
        if self.fail_count_groups and len(calls) > 1:
            raise RuntimeError("injected group failure")
        if any(c is self.BAD for c in calls):
            if len(calls) == 1:
                self.individual_counts.append(list(calls))
            raise ValueError("bad call")
        if len(calls) == 1 and self.fail_count_groups:
            self.individual_counts.append(list(calls))
        self.count_groups.append((list(calls), tuple(shards)))
        values = [c * 10 for c in calls]
        return lambda: values

    # -- row legs ----------------------------------------------------------

    def row_batch_async(self, index, calls, shards):
        if any(c is self.BAD for c in calls):
            raise ValueError("bad row call")
        self.row_groups.append((list(calls), tuple(shards)))
        rows = [("row", c) for c in calls]
        return lambda: rows

    def bitmap_call(self, index, call, shards):
        if call is self.BAD:
            raise ValueError("bad row call")
        return ("row", call)

    # -- synchronous kinds -------------------------------------------------

    def bsi_sum(self, index, field, shards, filter_call=None):
        if field == "boom":
            raise ValueError("bad field")
        self.bsi_calls.append(("bsi_sum", field, filter_call))
        return (len(field) * 100, 7)

    def bsi_min(self, index, field, shards, filter_call=None):
        self.bsi_calls.append(("bsi_min", field, filter_call))
        return (1, 2)

    def topn_field(self, index, field, shards, n, src_call=None):
        assert n == 0, "batcher must request the full ranked vector"
        self.topn_calls.append((field, src_call))
        return [(r, 50 - r) for r in range(5)]


def _run_threads(fns):
    """Run callables concurrently; return per-fn (result | exception)."""
    out = [None] * len(fns)

    def wrap(k):
        try:
            out[k] = fns[k]()
        except Exception as e:  # noqa: BLE001 — asserted by callers
            out[k] = e

    threads = [threading.Thread(target=wrap, args=(k,)) for k in range(len(fns))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return out


class TestLegComposition:
    def test_mixed_kinds_group_per_kind(self):
        """Count + Row + Sum + TopN legs drained in one window land in
        per-kind groups: one count dispatch carrying every count call,
        one row dispatch, deduped sync calls."""
        be = StubBackend()
        b = ShardLegBatcher(be, window=0.3)
        shards = [0, 1]
        filt = object()  # shared filter tree (parse-cache identity)
        fns = [
            lambda: b.count("i", [1, 2], shards),
            lambda: b.count("i", [3], shards),
            lambda: b.row("i", "rowA", shards),
            lambda: b.row("i", "rowB", shards),
            lambda: b.bsi("bsi_sum", "i", "v", shards, None),
            lambda: b.bsi("bsi_sum", "i", "v", shards, None),  # dedupes
            lambda: b.bsi("bsi_min", "i", "v", shards, None),
            lambda: b.topn("i", "f", shards, 2, filt),
            lambda: b.topn("i", "f", shards, 0, filt),  # shares the launch
        ]
        got = _run_threads(fns)
        assert not any(isinstance(g, Exception) for g in got), got
        # One count dispatch carried all three calls (leader order may
        # interleave legs, but the group is singular and complete).
        assert len(be.count_groups) == 1
        assert sorted(be.count_groups[0][0]) == [1, 2, 3]
        assert sorted(got[0]) + got[1] == [10, 20, 30]
        # One row launch with both legs' calls; per-leg results.
        assert len(be.row_groups) == 1
        assert sorted(be.row_groups[0][0]) == ["rowA", "rowB"]
        assert got[2] == ("row", "rowA") and got[3] == ("row", "rowB")
        # Identical Sum legs deduped to ONE backend call; Min separate.
        assert be.bsi_calls.count(("bsi_sum", "v", None)) == 1
        assert be.bsi_calls.count(("bsi_min", "v", None)) == 1
        assert got[4] == got[5] == (100, 7)
        assert got[6] == (1, 2)
        # TopN shared one ranked-vector computation; n trimmed per leg.
        assert len(be.topn_calls) == 1
        assert got[7] == [(0, 50), (1, 49)]
        assert len(got[8]) == 5

    def test_count_scatter_respects_leg_boundaries(self):
        be = StubBackend()
        b = ShardLegBatcher(be, window=0.2)
        got = _run_threads([
            lambda: b.count("i", [1, 2], [0]),
            lambda: b.count("i", [7], [0]),
        ])
        assert got[0] == [10, 20]
        assert got[1] == [70]

    def test_distinct_shard_sets_do_not_share_a_group(self):
        be = StubBackend()
        b = ShardLegBatcher(be, window=0.2)
        got = _run_threads([
            lambda: b.count("i", [1], [0]),
            lambda: b.count("i", [2], [0, 1]),
        ])
        assert got[0] == [10] and got[1] == [20]
        assert len(be.count_groups) == 2
        assert {g[1] for g in be.count_groups} == {(0,), (0, 1)}

    def test_uncontended_leg_dispatches_immediately(self):
        """window=0: a lone leg pays no coalescing sleep and still works
        through every public submit method."""
        be = StubBackend()
        b = ShardLegBatcher(be, window=0.0)
        assert b.count("i", [4], [0]) == [40]
        assert b.row("i", "r", [0]) == ("row", "r")
        assert b.bsi("bsi_sum", "i", "v", [0]) == (100, 7)
        assert b.topn("i", "f", [0], 1) == [(0, 50)]

    def test_countbatcher_alias(self):
        assert CountBatcher is ShardLegBatcher


class TestErrorIsolation:
    def test_bad_count_leg_fails_only_its_submitter(self):
        be = StubBackend()
        b = ShardLegBatcher(be, window=0.25)
        got = _run_threads([
            lambda: b.count("i", [1], [0]),
            lambda: b.count("i", [StubBackend.BAD], [0]),
            lambda: b.count("i", [5], [0]),
        ])
        bads = [g for g in got if isinstance(g, ValueError)]
        goods = sorted(g[0] for g in got if isinstance(g, list))
        assert len(bads) == 1
        assert goods == [10, 50]

    def test_group_failure_retries_individually(self):
        """A whole-group dispatch failure re-dispatches each leg alone:
        every good leg still resolves, through the isolation path."""
        be = StubBackend()
        be.fail_count_groups = True
        b = ShardLegBatcher(be, window=0.25)
        got = _run_threads([
            lambda: b.count("i", [1], [0]),
            lambda: b.count("i", [2], [0]),
        ])
        assert sorted(g[0] for g in got) == [10, 20]

    def test_bad_row_leg_fails_only_its_submitter(self):
        be = StubBackend()
        b = ShardLegBatcher(be, window=0.25)
        got = _run_threads([
            lambda: b.row("i", "good", [0]),
            lambda: b.row("i", StubBackend.BAD, [0]),
        ])
        bads = [g for g in got if isinstance(g, ValueError)]
        assert len(bads) == 1
        assert ("row", "good") in got

    def test_bad_sync_leg_fails_only_its_dedupe_set(self):
        be = StubBackend()
        b = ShardLegBatcher(be, window=0.25)
        got = _run_threads([
            lambda: b.bsi("bsi_sum", "i", "v", [0]),
            lambda: b.bsi("bsi_sum", "i", "boom", [0]),
        ])
        bads = [g for g in got if isinstance(g, ValueError)]
        assert len(bads) == 1
        assert (100, 7) in got


class TestTelemetry:
    def _counters(self):
        return dict(global_stats.snapshot()["counters"])

    def test_occupancy_and_coalesce_counters(self):
        before = self._counters()
        be = StubBackend()
        b = ShardLegBatcher(be, window=0.25)
        got = _run_threads([
            lambda: b.count("i", [1], [0]),
            lambda: b.count("i", [2], [0]),
            lambda: b.count("i", [3], [0]),
        ])
        assert sorted(g[0] for g in got) == [10, 20, 30]
        after = self._counters()

        def delta(name):
            return after.get(name, 0.0) - before.get(name, 0.0)

        assert delta('batch_legs_total{kind="count"}') == 3
        # 3 legs in one launch group = 2 coalesced beyond the first.
        assert delta('batch_coalesced_total{kind="count"}') == 2
        snap = global_stats.histogram_snapshot()
        occ = snap.get('batch_occupancy{kind="count"}')
        assert occ is not None and occ["count"] >= 1

    def test_histogram_mean_helper(self):
        from pilosa_tpu.utils.stats import histogram_mean

        assert histogram_mean({"sum": 12.0, "count": 3}) == 4.0
        assert histogram_mean(
            {"sum": 12.0, "count": 4}, {"sum": 2.0, "count": 2}
        ) == 5.0
        assert histogram_mean({"sum": 0.0, "count": 0}) is None


# ---------------------------------------------------------------------------
# Differential acceptance: batched == unbatched for every routed kind,
# under concurrent submission through the real executor + device backend.
# ---------------------------------------------------------------------------


@pytest.fixture
def device_backend_available():
    """Skip (never error) where the device backend can't import — the
    stub-backend half of this module must still run on a jax without
    shard_map (the same gate tests/test_bench_smoke.py uses)."""
    pytest.importorskip(
        "pilosa_tpu.exec.tpu",
        reason="device backend unavailable (jax.shard_map)",
        exc_type=ImportError,
    )


@pytest.fixture
def holder(tmp_path, device_backend_available):
    from pilosa_tpu.core import Holder

    h = Holder(str(tmp_path / "data")).open()
    yield h
    h.close()


def _build_index(holder, rng):
    from pilosa_tpu.core.field import options_for_int
    from pilosa_tpu.shardwidth import SHARD_WIDTH

    idx = holder.create_index("i")
    for fname, rows in (("f", (1, 2)), ("g", (9,))):
        field = idx.create_field(fname)
        for row in rows:
            cols = np.unique(
                rng.integers(0, 2 * SHARD_WIDTH, 2500, dtype=np.uint64)
            )
            field.import_bits(np.full(cols.size, row, dtype=np.uint64), cols)
    v = idx.create_field("v", options_for_int(-1000, 1000))
    cols = np.unique(rng.integers(0, 2 * SHARD_WIDTH, 400, dtype=np.uint64))
    v.import_value(cols, rng.integers(-900, 901, cols.size))


DIFF_QUERIES = [
    "Count(Intersect(Row(f=1), Row(g=9)))",
    "Count(Row(f=2))",
    "Row(f=1)",
    "Union(Row(f=1), Row(g=9))",
    "Intersect(Row(f=2), Row(g=9))",
    "Sum(field=v)",
    "Min(field=v)",
    "Max(field=v)",
    "Sum(Row(f=1), field=v)",
    "TopN(f, n=1)",
    "TopN(f)",
]


class TestBatchedDifferential:
    def test_batched_equals_unbatched_under_concurrency(self, holder, rng):
        """The ISSUE r11 differential gate: every routed leg kind returns
        byte-identical JSON through the batching plane (window > 0 so
        the legs REALLY coalesce) and through the plain oracle path."""
        from pilosa_tpu.exec import Executor
        from pilosa_tpu.exec.result import result_to_json
        from pilosa_tpu.exec.tpu import TPUBackend

        _build_index(holder, rng)
        oracle = Executor(holder)
        want = {q: result_to_json(oracle.execute("i", q)[0]) for q in DIFF_QUERIES}

        be = TPUBackend(holder)
        ex = Executor(holder, backend=be)
        ex.batcher = ShardLegBatcher(be, window=0.2)
        counters0 = dict(global_stats.snapshot()["counters"])

        def run(q):
            return lambda: result_to_json(ex.execute("i", q)[0])

        got = _run_threads([run(q) for q in DIFF_QUERIES])
        for q, g in zip(DIFF_QUERIES, got):
            assert not isinstance(g, Exception), (q, g)
            assert g == want[q], q
        # The window really coalesced: at least one multi-leg group.
        after = dict(global_stats.snapshot()["counters"])
        coalesced = sum(
            after.get(k, 0.0) - counters0.get(k, 0.0)
            for k in after
            if k.startswith("batch_coalesced_total")
        )
        assert coalesced >= 1

    def test_row_batch_async_direct(self, holder, rng):
        """row_batch_async alone: slot dedupe + scatter parity with
        bitmap_call, including an unsupported call's fallback slot."""
        from pilosa_tpu.exec.tpu import TPUBackend
        from pilosa_tpu.pql import parse_string

        _build_index(holder, rng)
        be = TPUBackend(holder)
        shards = [0, 1]
        calls = [
            parse_string("Row(f=1)").calls[0],
            parse_string("Union(Row(f=1), Row(g=9))").calls[0],
            parse_string("Row(f=1)").calls[0],  # dedupes with slot 0
        ]
        rows = be.row_batch_async("i", calls, shards)()
        for c, row in zip(calls, rows):
            want = be.bitmap_call("i", c, shards)
            np.testing.assert_array_equal(
                row.columns(), want.columns()
            )
        # Distinct legs never share a Row object (downstream mutates
        # attrs/keys per query).
        assert rows[0] is not rows[2]

    def test_executor_single_query_via_batcher_matches(self, holder, rng):
        """window=0 single legs through the executor: no coalescing, no
        added latency path — results still oracle-identical."""
        from pilosa_tpu.exec import Executor
        from pilosa_tpu.exec.result import result_to_json
        from pilosa_tpu.exec.tpu import TPUBackend

        _build_index(holder, rng)
        be = TPUBackend(holder)
        ex = Executor(holder, backend=be)
        ex.batcher = ShardLegBatcher(be, window=0.0)
        oracle = Executor(holder)
        for q in DIFF_QUERIES:
            assert result_to_json(ex.execute("i", q)[0]) == result_to_json(
                oracle.execute("i", q)[0]
            ), q
