"""Roaring bitmap tests.

Differential tests against a naive Python-set reference, mirroring the
reference's roaring/naive.go differential strategy (SURVEY.md §4.6), plus
file-format round-trips and a read of the reference repo's testdata
(/root/reference/testdata/sample_view/0, written by the Go implementation).
"""

import os

import numpy as np
import pytest

from pilosa_tpu.roaring import Bitmap, deserialize, serialize
from pilosa_tpu.roaring.codec import OP_ADD_BATCH, OpWriter, apply_ops, encode_op

SAMPLE_VIEW = "/root/reference/testdata/sample_view/0"


def random_values(rng, n, spread):
    return np.unique(rng.integers(0, spread, size=n, dtype=np.uint64))


class TestBasicOps:
    def test_add_remove_contains(self):
        b = Bitmap()
        assert b.add(100)
        assert not b.add(100)
        assert b.contains(100)
        assert b.count() == 1
        assert b.remove(100)
        assert not b.remove(100)
        assert not b.contains(100)
        assert b.count() == 0

    def test_add_many_spanning_containers(self, rng):
        vals = random_values(rng, 50_000, 1 << 24)
        b = Bitmap()
        changed = b.add_many(vals)
        assert changed == vals.size
        assert b.count() == vals.size
        np.testing.assert_array_equal(b.to_array(), vals)

    def test_remove_many(self, rng):
        vals = random_values(rng, 10_000, 1 << 22)
        b = Bitmap(vals)
        half = vals[::2]
        removed = b.remove_many(half)
        assert removed == half.size
        np.testing.assert_array_equal(b.to_array(), vals[1::2])

    def test_array_to_bitmap_conversion(self):
        # Push one container past ARRAY_MAX_SIZE=4096.
        vals = np.arange(0, 10000, dtype=np.uint64) * 2
        b = Bitmap(vals)
        assert b.count() == 10000
        c = b.container(0)
        assert c.typ == "bitmap"
        np.testing.assert_array_equal(b.to_array(), vals)

    def test_min_max(self, rng):
        vals = random_values(rng, 1000, 1 << 30)
        b = Bitmap(vals)
        lo, ok = b.min()
        assert ok and lo == int(vals.min())
        assert b.max() == int(vals.max())
        empty = Bitmap()
        _, ok = empty.min()
        assert not ok

    def test_count_range(self, rng):
        vals = random_values(rng, 20_000, 1 << 21)
        b = Bitmap(vals)
        for start, end in [(0, 1 << 21), (100, 200), (65536, 65536 * 3), (1 << 20, 1 << 21), (5, 5)]:
            want = int(((vals >= start) & (vals < end)).sum())
            assert b.count_range(start, end) == want, (start, end)


class TestSetAlgebra:
    @pytest.mark.parametrize("spread", [1 << 16, 1 << 20, 1 << 24])
    @pytest.mark.parametrize("n", [100, 5000, 60_000])
    def test_differential(self, rng, n, spread):
        """AND/OR/ANDNOT/XOR vs python set, across container-type mixes."""
        a_vals = random_values(rng, n, spread)
        b_vals = random_values(rng, n, spread)
        a, b = Bitmap(a_vals), Bitmap(b_vals)
        sa, sb = set(a_vals.tolist()), set(b_vals.tolist())

        np.testing.assert_array_equal(
            a.intersect(b).to_array(), np.array(sorted(sa & sb), dtype=np.uint64)
        )
        np.testing.assert_array_equal(
            a.union(b).to_array(), np.array(sorted(sa | sb), dtype=np.uint64)
        )
        np.testing.assert_array_equal(
            a.difference(b).to_array(), np.array(sorted(sa - sb), dtype=np.uint64)
        )
        np.testing.assert_array_equal(
            a.xor(b).to_array(), np.array(sorted(sa ^ sb), dtype=np.uint64)
        )
        assert a.intersection_count(b) == len(sa & sb)

    def test_union_in_place(self, rng):
        a_vals = random_values(rng, 3000, 1 << 20)
        b_vals = random_values(rng, 3000, 1 << 20)
        a = Bitmap(a_vals)
        a.union_in_place(Bitmap(b_vals))
        want = np.union1d(a_vals, b_vals)
        np.testing.assert_array_equal(a.to_array(), want)

    def test_shift(self, rng):
        vals = random_values(rng, 5000, 1 << 20)
        vals = np.append(vals, [65535, 131071])  # container-edge carries
        b = Bitmap(np.unique(vals))
        shifted = b.shift()
        want = np.unique(vals) + 1
        np.testing.assert_array_equal(shifted.to_array(), want)

    def test_flip(self, rng):
        vals = random_values(rng, 1000, 1 << 18)
        b = Bitmap(vals)
        lo, hi = 1000, 200_000  # inclusive range
        flipped = b.flip(lo, hi)
        s = set(vals.tolist())
        want = sorted((set(range(lo, hi + 1)) - s) | {v for v in s if not lo <= v <= hi})
        np.testing.assert_array_equal(flipped.to_array(), np.array(want, dtype=np.uint64))

    def test_offset_range(self, rng):
        shard_width = 1 << 20
        vals = random_values(rng, 5000, shard_width)
        row = 7
        b = Bitmap(vals + row * shard_width)
        out = b.offset_range(3 * shard_width, row * shard_width, (row + 1) * shard_width)
        np.testing.assert_array_equal(out.to_array(), vals + 3 * shard_width)


class TestCodec:
    @pytest.mark.parametrize("kind", ["sparse", "dense", "runs", "mixed", "empty"])
    def test_roundtrip(self, rng, kind):
        if kind == "sparse":
            vals = random_values(rng, 500, 1 << 30)
        elif kind == "dense":
            vals = np.unique(rng.integers(0, 1 << 17, size=100_000, dtype=np.uint64))
        elif kind == "runs":
            vals = np.arange(1000, 90_000, dtype=np.uint64)
        elif kind == "mixed":
            vals = np.unique(
                np.concatenate(
                    [
                        np.arange(0, 70_000, dtype=np.uint64),  # run container(s)
                        random_values(rng, 100, 1 << 40),  # far sparse arrays
                        np.unique(rng.integers(1 << 20, (1 << 20) + 65536, size=30_000, dtype=np.uint64)),
                    ]
                )
            )
        else:
            vals = np.empty(0, dtype=np.uint64)
        b = Bitmap(vals)
        data = serialize(b)
        b2 = deserialize(data)
        np.testing.assert_array_equal(b2.to_array(), vals)

    def test_reads_reference_go_file(self):
        """The Go reference's own testdata must load (byte compatibility)."""
        if not os.path.exists(SAMPLE_VIEW):
            pytest.skip("reference testdata not available")
        with open(SAMPLE_VIEW, "rb") as f:
            data = f.read()
        b = deserialize(data)
        assert b.count() > 0
        # Round-trip: our serialization of it must parse again to equality.
        b2 = deserialize(serialize(b))
        np.testing.assert_array_equal(b2.to_array(), b.to_array())

    def test_op_log_replay(self, rng, tmp_path):
        vals = random_values(rng, 2000, 1 << 21)
        b = Bitmap(vals)
        path = tmp_path / "frag"
        with open(path, "wb") as f:
            f.write(serialize(b))
            b.op_writer = OpWriter(f)
            b.add(5_000_000)
            b.add_many(np.array([1, 2, 3], dtype=np.uint64))
            b.remove(int(vals[0]))
            b.remove_many(np.array([2], dtype=np.uint64))
        with open(path, "rb") as f:
            b2 = deserialize(f.read())
        np.testing.assert_array_equal(b2.to_array(), b.to_array())
        assert b2.op_n >= 4

    def test_op_checksum_detects_corruption(self):
        op = bytearray(encode_op(OP_ADD_BATCH, values=np.array([9, 10], dtype=np.uint64)))
        b = Bitmap()
        apply_ops(b, bytes(op), 0)
        assert b.count() == 2
        op[14] ^= 0xFF  # corrupt a value byte
        with pytest.raises(ValueError, match="checksum"):
            apply_ops(Bitmap(), bytes(op), 0)

    def test_import_roaring_bits(self, rng):
        a_vals = random_values(rng, 3000, 1 << 20)
        b_vals = random_values(rng, 3000, 1 << 20)
        b = Bitmap(a_vals)
        changed = b.import_roaring_bits(serialize(Bitmap(b_vals)))
        want = np.union1d(a_vals, b_vals)
        assert changed == want.size - a_vals.size
        np.testing.assert_array_equal(b.to_array(), want)
        # clear
        b.import_roaring_bits(serialize(Bitmap(b_vals)), clear=True)
        np.testing.assert_array_equal(
            b.to_array(), np.setdiff1d(a_vals, b_vals, assume_unique=True)
        )


class TestNative:
    def test_fnv_vectors(self):
        from pilosa_tpu.native import fnv32a, fnv64a

        # Known FNV-1a test vectors.
        assert fnv32a(b"") == 2166136261
        assert fnv32a(b"a") == 0xE40C292C
        assert fnv32a(b"foobar") == 0xBF9CF968
        assert fnv64a(b"") == 14695981039346656037
        assert fnv64a(b"a") == 0xAF63DC4C8601EC8C
        assert fnv64a(b"foobar") == 0x85944171F73967E8

    def test_xxhash_vectors(self):
        from pilosa_tpu.native import has_native, xxhash64

        if not has_native():
            pytest.skip("no native lib")
        # Known xxh64 vectors (seed 0).
        assert xxhash64(b"") == 0xEF46DB3751D8E999
        assert xxhash64(b"xxhash") == 0x32DD38952C4BC720


class TestRunContainers:
    """First-class in-memory RLE containers (VERDICT r3 missing #5;
    reference roaring.go:64-69,1940-1943)."""

    def _runny(self):
        b = Bitmap()
        # Full run + two fragments: 0..9999 and 20000..20004 in key 0,
        # a WHOLE container run in key 1.
        b.add_many(np.arange(0, 10_000, dtype=np.uint64), log=False)
        b.add_many(np.arange(20_000, 20_005, dtype=np.uint64), log=False)
        b.add_many(np.arange(1 << 16, 2 << 16, dtype=np.uint64), log=False)
        return b

    def test_optimize_converts_and_preserves_bits(self):
        from pilosa_tpu.roaring.bitmap import TYPE_RUN

        b = self._runny()
        before = b.to_array()
        n = b.optimize()
        assert n >= 2
        assert b.container(0).typ == TYPE_RUN
        assert b.container(1).typ == TYPE_RUN
        np.testing.assert_array_equal(b.to_array(), before)
        # Memory: the full-container run stores 1 run (4 bytes of u16
        # pairs) instead of an 8 KiB bitmap.
        assert b.container(1).data.nbytes <= 8

    def test_run_ops_differential(self, rng):
        from pilosa_tpu.roaring.bitmap import TYPE_RUN

        b = self._runny()
        b.optimize()
        plain = Bitmap(b.to_array())
        other = Bitmap(
            np.unique(rng.integers(0, 2 << 16, 5000, dtype=np.uint64))
        )
        assert b.count() == plain.count()
        for v in (0, 9_999, 10_000, 20_004, (1 << 16) + 7, (2 << 16) - 1):
            assert b.contains(v) == plain.contains(v), v
        np.testing.assert_array_equal(
            b.intersect(other).to_array(), plain.intersect(other).to_array()
        )
        np.testing.assert_array_equal(
            b.union(other).to_array(), plain.union(other).to_array()
        )
        np.testing.assert_array_equal(
            b.difference(other).to_array(), plain.difference(other).to_array()
        )
        np.testing.assert_array_equal(
            b.xor(other).to_array(), plain.xor(other).to_array()
        )
        assert b.count_range(5_000, 70_000) == plain.count_range(5_000, 70_000)
        # Mutation through a run container stays correct.
        assert b.add(123_456) == plain.add(123_456)
        assert b.remove(5) == plain.remove(5)
        np.testing.assert_array_equal(b.to_array(), plain.to_array())

    def test_serialize_roundtrip_keeps_runs_in_memory(self):
        from pilosa_tpu.roaring import deserialize, serialize
        from pilosa_tpu.roaring.bitmap import TYPE_RUN

        b = self._runny()
        data = serialize(b)
        back = deserialize(data)
        # The codec writes runs; the in-memory load must KEEP them RLE
        # (it used to inflate to array/bitmap).
        assert back.container(1).typ == TYPE_RUN
        np.testing.assert_array_equal(back.to_array(), b.to_array())
        # Re-serialize is byte-identical (same encodings chosen).
        assert serialize(back) == data

    def test_fragment_pack_with_runs(self, rng):
        from pilosa_tpu.core.fragment import Fragment
        from pilosa_tpu.ops.blocks import pack_fragment, unpack_row

        f = Fragment(None, "i", "f", "standard", 0)
        cols = np.arange(1000, 70_000, dtype=np.uint64)
        f.bulk_import(np.zeros(cols.size, dtype=np.uint64), cols)
        f.storage.optimize()
        block = pack_fragment(f)
        np.testing.assert_array_equal(unpack_row(block[0]), cols)


class TestKeysGenerationCounter:
    """keys()'s lazy sorted-key rebuild must never lose a concurrent
    writer's staleness mark (code review r5): a bool dirty flag could be
    cleared by a reader that sorted BEFORE the write landed, leaving the
    missing container invisible to every later pack retry."""

    def test_writer_during_rebuild_stays_stale(self):
        b = Bitmap([1])
        assert b.keys() == [0]
        # Simulate the interleaving: reader captured gen, then a writer
        # inserts a new container before the reader stores its result.
        g = b._keys_gen
        stale_sort = sorted(b._cs)
        b.add(5 << 16)  # new container -> gen bump
        b._keys = stale_sort
        b._keys_built = g  # reader's store of a pre-write snapshot
        # The cache must be considered stale: next keys() re-sorts.
        assert b.keys() == [0, 5]

    def test_clone_starts_stale(self):
        b = Bitmap([1, 1 << 16])
        c = b.clone()
        assert c.keys() == [0, 1]


class TestRunNativeSetAlgebra:
    """VERDICT r4 #4: run×run and run×array set algebra computes ON the
    runs (reference roaring.go:2599-2790) — differential against the
    materialized (_unrun) path for every op and operand shape, plus the
    no-bitmap-twin guarantee for run/array pairs."""

    def _containers(self, rng):
        from pilosa_tpu.roaring.bitmap import Container

        def run_c(spans):
            return Container.from_runs(np.array(spans, dtype=np.int64))

        def arr_c(pos):
            return Container.from_positions(
                np.unique(np.asarray(pos, dtype=np.uint16))
            )

        cs = {
            "empty_run": run_c(np.empty((0, 2), dtype=np.int64)),
            "one_run": run_c([[100, 60000]]),
            "runs": run_c([[0, 9], [20, 29], [100, 4999], [60000, 65535]]),
            "tight_runs": run_c([[i * 100, i * 100 + 80] for i in range(600)]),
            "edge_runs": run_c([[0, 0], [65535, 65535]]),
            "arr_sparse": arr_c(rng.integers(0, 65536, 50)),
            "arr_dense": arr_c(rng.integers(0, 65536, 3000)),
            "arr_inside": arr_c([150, 200, 4999, 60000, 65535]),
        }
        # keep only genuinely-run containers for the run side
        assert cs["one_run"].typ == "run" and cs["runs"].typ == "run"
        return cs

    def _check_equal(self, got, want_positions):
        np.testing.assert_array_equal(
            got.positions(), want_positions.astype(np.uint16)
        )
        assert got.n == want_positions.size

    def test_differential_all_pairs(self, rng):
        from pilosa_tpu.roaring.bitmap import TYPE_RUN

        cs = self._containers(rng)
        pairs = [
            (a, b)
            for a in cs
            for b in cs
            if cs[a].typ == TYPE_RUN or cs[b].typ == TYPE_RUN
        ]
        for an, bn in pairs:
            a, b = cs[an], cs[bn]
            pa = set(a.positions().tolist())
            pb = set(b.positions().tolist())
            cases = {
                "intersect": sorted(pa & pb),
                "union": sorted(pa | pb),
                "difference": sorted(pa - pb),
                "xor": sorted(pa ^ pb),
            }
            for op, want in cases.items():
                got = getattr(a, op)(b)
                self._check_equal(got, np.array(want, dtype=np.int64))
            assert a.intersection_count(b) == len(pa & pb), (an, bn)

    def test_run_pairs_allocate_no_bitmap_twin(self, rng):
        """Runny operand pairs (where the result can stay RLE) must
        never materialize a twin. Scattered operands (arr_dense) are
        DESIGNED to take the materialized kernels — the could-win gate
        keeps the run sweeps off the hot bulk paths."""
        from pilosa_tpu.roaring import bitmap as bm

        cs = self._containers(rng)
        before = bm.UNRUN_MATERIALIZATIONS[0]
        for op in ("intersect", "union", "difference", "xor",
                   "intersection_count"):
            getattr(cs["runs"], op)(cs["tight_runs"])
            getattr(cs["runs"], op)(cs["arr_inside"])
            getattr(cs["arr_sparse"], op)(cs["one_run"])
        assert bm.UNRUN_MATERIALIZATIONS[0] == before
        # intersect/intersection_count/array-minus-run are vectorized
        # mask ops: no twin even for scattered arrays.
        cs["runs"].intersect(cs["arr_dense"])
        cs["runs"].intersection_count(cs["arr_dense"])
        cs["arr_dense"].difference(cs["runs"])
        assert bm.UNRUN_MATERIALIZATIONS[0] == before

    def test_run_x_bitmap_direct_no_unrun(self, rng):
        """run x bitmap intersect verbs (ISSUE r7 satellite) AND the
        bitmap words against a cumsum coverage mask — no _unrun twin,
        bit-exact against the position-set oracle."""
        from pilosa_tpu.roaring import bitmap as bm
        from pilosa_tpu.roaring.bitmap import Container

        rc = Container.from_runs(
            np.array([[0, 10], [100, 4000], [4002, 4002], [60000, 65535]],
                     dtype=np.int64)
        )
        pos = np.unique(rng.integers(0, 65536, 9000).astype(np.uint16))
        bc = Container.from_positions(pos)
        assert rc.typ == "run" and bc.typ == "bitmap"
        want = sorted(set(rc.positions().tolist()) & set(pos.tolist()))
        before = bm.UNRUN_MATERIALIZATIONS[0]
        for a, b in ((rc, bc), (bc, rc)):
            got = a.intersect(b)
            got.validate()
            assert got.positions().tolist() == want
            assert a.intersection_count(b) == len(want)
        assert bm.UNRUN_MATERIALIZATIONS[0] == before

    def test_runs_mask_tolerates_adjacent_runs(self):
        """A foreign writer can serialize ADJACENT (non-coalesced but
        valid) runs; the boundary-delta mask must accumulate, not
        assign, or the shared boundary corrupts the whole mask (code
        review r7)."""
        from pilosa_tpu.roaring.bitmap import (
            Container, TYPE_RUN, _runs_to_bitmap_words,
        )

        adj = Container(
            TYPE_RUN, np.array([[0, 4], [5, 9]], dtype=np.uint16), 10
        )
        words = _runs_to_bitmap_words(adj.data)
        assert int(np.bitwise_count(words).sum()) == 10
        full = Container.from_positions(
            np.arange(6000, dtype=np.uint16)
        )  # bitmap (> 4096)
        assert adj.intersection_count(full) == 10
        assert adj.intersect(full).positions().tolist() == list(range(10))

    def test_with_without_many_stay_runny(self, rng):
        from pilosa_tpu.roaring import bitmap as bm

        c = self._containers(rng)["one_run"]  # [100, 60000]
        before = bm.UNRUN_MATERIALIZATIONS[0]
        # Punch a hole, then refill it: stays RLE throughout.
        holed = c.without_many(np.arange(5000, 5100, dtype=np.uint16))
        assert holed.typ == "run" and holed.n == c.n - 100
        refilled = holed.with_many(np.arange(5000, 5100, dtype=np.uint16))
        assert refilled.typ == "run" and refilled.n == c.n
        np.testing.assert_array_equal(refilled.data, c.data)
        assert bm.UNRUN_MATERIALIZATIONS[0] == before
        # Scattering many singles routes through the materialized
        # kernels (could-win gate) and flips the encoding — correct
        # either way.
        adds = np.unique(rng.integers(0, 65536, 8000).astype(np.uint16))
        scattered = c.with_many(adds)
        want = sorted(set(c.positions().tolist()) | set(adds.tolist()))
        np.testing.assert_array_equal(
            scattered.positions(), np.array(want, dtype=np.uint16)
        )

    def test_time_quantum_view_union_keeps_runs(self):
        """The workload the RLE work exists for: unioning time-quantum
        view rows whose containers are runs must not materialize
        bitmap twins."""
        from pilosa_tpu.roaring import bitmap as bm
        from pilosa_tpu.roaring.bitmap import Bitmap, Container

        b1, b2 = Bitmap(), Bitmap()
        for k in range(6):
            b1.put_container(
                k, Container.from_runs(np.array([[0, 30000]], dtype=np.int64))
            )
            b2.put_container(
                k,
                Container.from_runs(
                    np.array([[20000, 50000]], dtype=np.int64)
                ),
            )
        before = bm.UNRUN_MATERIALIZATIONS[0]
        u = b1.union(b2)
        i = b1.intersect(b2)
        d = b1.difference(b2)
        assert bm.UNRUN_MATERIALIZATIONS[0] == before
        assert u.count() == 6 * 50001
        assert i.count() == 6 * 10001
        assert d.count() == 6 * 20000
        for k in range(6):
            assert u.container(k).typ == "run"
            assert i.container(k).typ == "run"
