"""Tiled GroupBy engine tests (ISSUE 17): popcount pruning, slot-bucketed
tile launches, odometer-order streaming, and filtered-tensor caching must
stay byte-identical to the host per-shard iterator across the argument
matrix — on BOTH the maintained per-shard path and the generic tiled
sweep (forced by shrinking the per-shard byte budget to zero)."""

import numpy as np
import pytest

from pilosa_tpu.core import Holder
from pilosa_tpu.exec import Executor
from pilosa_tpu.exec.tpu import MAX_GROUP_TILE_SLOTS, TPUBackend, _slot_bucket
from pilosa_tpu.shardwidth import SHARD_WIDTH
from pilosa_tpu.utils.stats import global_stats


def counter_sum(prefix: str) -> float:
    snap = global_stats.snapshot()
    return sum(v for k, v in snap["counters"].items() if k.startswith(prefix))


@pytest.fixture
def holder(tmp_path):
    h = Holder(str(tmp_path / "data")).open()
    yield h
    h.close()


# Sparse-gap extra fields: nominal height exceeds the live row set, so
# pruning is load-bearing, not vacuous. c spans 8 nominal rows with 3
# live (1, 2, 4, 5, 6 globally empty); d spans 6 with 2 live.
LIVE = {"c": (0, 3, 7), "d": (2, 5)}
PIN_COL = 2 * SHARD_WIDTH - 9  # deterministic column for the column= arm


def build(holder, rng):
    idx = holder.create_index("i")
    for fname, nrows in (("a", 3), ("b", 2)):
        idx.create_field(fname)
        for row in range(1, nrows + 1):
            cols = np.unique(
                rng.integers(0, 2 * SHARD_WIDTH, 1500, dtype=np.uint64)
            )
            idx.field(fname).import_bits(
                np.full(cols.size, row, dtype=np.uint64), cols
            )
    for fname, rows in LIVE.items():
        idx.create_field(fname)
        for row in rows:
            cols = np.unique(
                rng.integers(0, 2 * SHARD_WIDTH, 900, dtype=np.uint64)
            )
            idx.field(fname).import_bits(
                np.full(cols.size, row, dtype=np.uint64), cols
            )
    idx.field("c").set_bit(0, PIN_COL)
    idx.field("c").set_bit(3, PIN_COL)
    return idx


def build_wide(idx, rng, nrows=70):
    """Fully-live 70-row extra field: the live product exceeds one
    64-slot bucket, so enumeration crosses a tile boundary."""
    idx.create_field("e")
    for row in range(nrows):
        cols = np.unique(rng.integers(0, 2 * SHARD_WIDTH, 40, dtype=np.uint64))
        idx.field("e").import_bits(
            np.full(cols.size, row, dtype=np.uint64), cols
        )


QUERIES = [
    "GroupBy(Rows(a), Rows(b), Rows(c))",
    "GroupBy(Rows(a), Rows(b), Rows(d))",
    "GroupBy(Rows(a), Rows(b), Rows(c), Rows(d))",
    "GroupBy(Rows(a), Rows(b), Rows(c), limit=4)",
    "GroupBy(Rows(a), Rows(b), Rows(c), limit=3, offset=2)",
    "GroupBy(Rows(a), Rows(b), Rows(c), Rows(d), limit=5, offset=1)",
    "GroupBy(Rows(a), Rows(b), Rows(c), Rows(d), limit=100, offset=3)",
    "GroupBy(Rows(a, previous=1), Rows(b), Rows(c))",
    "GroupBy(Rows(a), Rows(b), Rows(c, previous=3))",
    "GroupBy(Rows(a), Rows(b), Rows(c, limit=2))",
    f"GroupBy(Rows(a), Rows(b), Rows(c, column={PIN_COL}))",
    "GroupBy(Rows(a), Rows(b), Rows(c), filter=Row(a=1))",
    "GroupBy(Rows(a), Rows(b), Rows(c), filter=Union(Row(a=1), Row(b=2)))",
    "GroupBy(Rows(a), Rows(b), Rows(c), Rows(d), filter=Row(c=3))",
    "GroupBy(Rows(a), Rows(b), Rows(c), Rows(d), filter=Row(d=5), limit=4)",
]

WIDE_QUERIES = [
    "GroupBy(Rows(a), Rows(b), Rows(e))",
    "GroupBy(Rows(a), Rows(b), Rows(e), limit=7, offset=250)",
    "GroupBy(Rows(a), Rows(b), Rows(e), filter=Row(a=2))",
]


def _differential(holder, be):
    host = Executor(holder)
    dev = Executor(holder, backend=be)
    for q in QUERIES + WIDE_QUERIES:
        assert dev.execute("i", q) == host.execute("i", q), q


class TestTiledDifferential:
    def test_maintained_path(self, holder, rng):
        """Default routing: n>=3 unfiltered rides the maintained
        per-shard tensor (tiled pershard kernel underneath)."""
        idx = build(holder, rng)
        build_wide(idx, rng)
        _differential(holder, TPUBackend(holder))

    def test_generic_tiled_path(self, holder, rng):
        """Byte budget 1 bails the maintained per-shard tensor before
        its prewarm, so every n>=3 query takes the generic prune+tile
        sweep — the same matrix must still match the host."""
        idx = build(holder, rng)
        build_wide(idx, rng)
        be = TPUBackend(holder)
        be.MAX_PAIR_PERSHARD_BYTES = 1
        _differential(holder, be)

    def test_wide_field_spans_tiles(self, holder, rng):
        """70 live combinations > one 64-slot bucket: the sweep cuts 2
        tiles and enumeration stays exact across the boundary."""
        idx = build(holder, rng)
        build_wide(idx, rng)
        be = TPUBackend(holder)
        be.MAX_PAIR_PERSHARD_BYTES = 1
        t0 = counter_sum("groupby_tiles_total")
        host = Executor(holder)
        dev = Executor(holder, backend=be)
        q = "GroupBy(Rows(a), Rows(b), Rows(e))"
        assert dev.execute("i", q) == host.execute("i", q)
        assert _slot_bucket(min(70, MAX_GROUP_TILE_SLOTS)) == 64
        assert counter_sum("groupby_tiles_total") - t0 == 2


class TestPruning:
    def test_pruned_and_tile_counters(self, holder, rng):
        """8x8 nominal extra product (stacks pad row counts to multiples
        of 8 — pad rows prune like real empties), 3x2 live: 58 combos
        pruned before any tile is cut, one 8-slot bucket covers the 6."""
        build(holder, rng)
        be = TPUBackend(holder)
        be.MAX_PAIR_PERSHARD_BYTES = 1
        dev = Executor(holder, backend=be)
        p0 = counter_sum("groupby_pruned_groups_total")
        t0 = counter_sum("groupby_tiles_total")
        dev.execute("i", "GroupBy(Rows(a), Rows(b), Rows(c), Rows(d))")
        assert counter_sum("groupby_pruned_groups_total") - p0 == 8 * 8 - 3 * 2
        assert counter_sum("groupby_tiles_total") - t0 == 1  # bucket(6)=8
        hist = global_stats.histogram_snapshot()
        assert "groupby_tile_occupancy" in hist

    def test_empty_row_becomes_live_under_churn(self, holder, rng):
        """A write into a previously-empty row must refresh the prune
        set: the new groups appear, counts match the host exactly."""
        idx = build(holder, rng)
        be = TPUBackend(holder)
        be.MAX_PAIR_PERSHARD_BYTES = 1
        host = Executor(holder)
        dev = Executor(holder, backend=be)
        q = "GroupBy(Rows(a), Rows(b), Rows(c))"
        before = dev.execute("i", q)
        assert before == host.execute("i", q)
        # Row 4 of c was globally empty (pruned); give it a column that
        # also lives in a=1 and b=1 so a brand-new group materializes.
        col = SHARD_WIDTH + 11
        idx.field("a").set_bit(1, col)
        idx.field("b").set_bit(1, col)
        idx.field("c").set_bit(4, col)
        after = dev.execute("i", q)
        assert after == host.execute("i", q)
        assert after != before
        assert any(g.group[-1].row_id == 4 for g in after[0])

    def test_churn_differential(self, holder, rng):
        """Point-write churn across grouped fields: every epoch's tiled
        answer matches the host (stale-tile invalidation)."""
        idx = build(holder, rng)
        be = TPUBackend(holder)
        be.MAX_PAIR_PERSHARD_BYTES = 1
        host = Executor(holder)
        dev = Executor(holder, backend=be)
        qs = ["GroupBy(Rows(a), Rows(b), Rows(c))",
              "GroupBy(Rows(a), Rows(b), Rows(c), Rows(d), limit=6)"]
        for k in range(3):
            idx.field("c").set_bit(LIVE["c"][k % 3], 444_000 + k)
            idx.field("a").set_bit(1 + k % 3, 555_000 + k)
            for q in qs:
                assert dev.execute("i", q) == host.execute("i", q), (k, q)


class TestRecompilePin:
    def test_flat_across_cardinality(self, holder, rng):
        """Slot buckets + in-kernel masking keep the program set small:
        sweeping 3/2/6/70-live shapes plus churn re-sweeps must not
        recompile any signature already in the ledger."""
        idx = build(holder, rng)
        build_wide(idx, rng)
        be = TPUBackend(holder)
        be.MAX_PAIR_PERSHARD_BYTES = 1
        dev = Executor(holder, backend=be)
        r0 = counter_sum("device_recompiles_total")
        for q in ("GroupBy(Rows(a), Rows(b), Rows(c))",
                  "GroupBy(Rows(a), Rows(b), Rows(d))",
                  "GroupBy(Rows(a), Rows(b), Rows(c), Rows(d))",
                  "GroupBy(Rows(a), Rows(b), Rows(e))"):
            dev.execute("i", q)
        idx.field("c").set_bit(3, 666_000)  # churn → re-sweep, same bucket
        dev.execute("i", "GroupBy(Rows(a), Rows(b), Rows(c))")
        assert counter_sum("device_recompiles_total") - r0 == 0


class TestFilteredCache:
    Q = "GroupBy(Rows(a), Rows(b), Rows(c), filter=Union(Row(a=1), Row(b=2)))"

    def test_hit_and_churn_invalidation(self, holder, rng):
        """Filtered n>=3 tensors (previously ckey=None — recomputed
        every query) now cache on filter fingerprint + epoch vector:
        repeat queries hit, writes to grouped AND filter-referenced
        fields invalidate."""
        idx = build(holder, rng)
        be = TPUBackend(holder)
        host = Executor(holder)
        dev = Executor(holder, backend=be)
        want = host.execute("i", self.Q)
        assert dev.execute("i", self.Q) == want
        h0 = counter_sum("agg_cache_hits_total")
        assert dev.execute("i", self.Q) == want
        assert counter_sum("agg_cache_hits_total") - h0 == 1
        # Write to a GROUPED field: epoch vector moves, cache must miss.
        idx.field("c").set_bit(3, 777_000)
        h0 = counter_sum("agg_cache_hits_total")
        assert dev.execute("i", self.Q) == host.execute("i", self.Q)
        assert counter_sum("agg_cache_hits_total") - h0 == 0
        # Re-warm, then write to a FILTER-referenced field (b is only
        # in the filter tree's Union arm): fingerprint must move too.
        assert dev.execute("i", self.Q) == host.execute("i", self.Q)
        idx.field("b").set_bit(2, 777_001)
        h0 = counter_sum("agg_cache_hits_total")
        assert dev.execute("i", self.Q) == host.execute("i", self.Q)
        assert counter_sum("agg_cache_hits_total") - h0 == 0

    def test_filter_only_field_invalidates(self, holder, rng):
        """Filter references a field NOT in the grouped set: writes to
        it alone must move the fingerprint. Pins the Row(d=5) spelling,
        where the field is the arg KEY (Call.field_arg semantics), not
        a field= arg."""
        idx = build(holder, rng)
        be = TPUBackend(holder)
        host = Executor(holder)
        dev = Executor(holder, backend=be)
        q = "GroupBy(Rows(a), Rows(b), Rows(c), filter=Row(d=5))"
        assert dev.execute("i", q) == host.execute("i", q)
        h0 = counter_sum("agg_cache_hits_total")
        assert dev.execute("i", q) == host.execute("i", q)
        assert counter_sum("agg_cache_hits_total") - h0 == 1
        idx.field("d").set_bit(5, SHARD_WIDTH + 77)
        h0 = counter_sum("agg_cache_hits_total")
        assert dev.execute("i", q) == host.execute("i", q)
        assert counter_sum("agg_cache_hits_total") - h0 == 0

    def test_ledger_charge(self, holder, rng):
        """Cached groupby payloads are charged to the agg_cache_bytes
        gauge (LRU ledger satellite)."""
        build(holder, rng)
        be = TPUBackend(holder)
        dev = Executor(holder, backend=be)
        dev.execute("i", self.Q)
        snap = global_stats.snapshot()["gauges"]
        assert snap.get("agg_cache_bytes", 0) > 0
