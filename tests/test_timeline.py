"""Interference flight recorder + per-shape cost accounting tests
(ISSUE 18 tentpoles 2-3): delta math over raw cumulative samples, the
min-interval dedup and idle-cost pins, SLO-burn-triggered incident
freeze end to end, the workload table's aggregation/eviction, shape_key
structure collapse, and the satellite fix that non-explain ring entries
carry per-launch device-wait."""

import json
import threading
import time
import urllib.request

import pytest

from pilosa_tpu.core import Holder
from pilosa_tpu.exec import Executor
from pilosa_tpu.exec.tpu import TPUBackend
from pilosa_tpu.pql import parse_string
from pilosa_tpu.pql.ast import shape_key
from pilosa_tpu.server.api import API
from pilosa_tpu.server.http import Server
from pilosa_tpu.utils.monitor import (
    FlightRecorder,
    RuntimeMonitor,
    global_flight_recorder,
)
from pilosa_tpu.utils.qprofile import (
    QueryProfile,
    WorkloadTable,
    global_workload_table,
    profile_scope,
)
from pilosa_tpu.utils.stats import StatsClient, global_stats


class TestFlightRecorder:
    def test_delta_math(self):
        """Adjacent-sample deltas: counters become per-span rates,
        query_seconds (sum, count) becomes qps + busy seconds, per-site
        lock-wait sums split by the site tag."""
        stats = StatsClient()
        fr = FlightRecorder(min_interval=0.0)
        stats.count("import_bits_total", 100)
        fr.sample(stats)
        stats.count("import_bits_total", 400)
        stats.timing("query_seconds", 0.02)
        stats.timing("query_seconds", 0.04)
        stats.with_tags("site:wal_append").timing("lock_wait_seconds", 0.5)
        stats.gauge("wal_pending_ops", 7)
        # Long enough that the served spanS (rounded to 2 decimals)
        # reconstructs the deltas within tolerance.
        time.sleep(0.25)
        fr.sample(stats)
        tl = fr.timeline(60)
        assert len(tl) == 1
        ent = tl[0]
        assert ent["spanS"] > 0
        # 400 new bits over the span.
        assert ent["ingestBitsPerS"] * ent["spanS"] == pytest.approx(
            400, rel=0.05
        )
        assert ent["qps"] * ent["spanS"] == pytest.approx(2, rel=0.05)
        assert ent["queryS"] == pytest.approx(0.06, abs=1e-3)
        assert ent["lockWaitS"] == {"wal_append": 0.5}
        assert ent["walPendingOps"] == 7

    def test_min_interval_dedups_and_skips_registry_reads(self, monkeypatch):
        """Two tickers at the same instant produce ONE sample, and the
        deduped call returns before touching the stats registry — the
        recorder's idle-cost pin."""
        stats = StatsClient()
        fr = FlightRecorder(min_interval=10.0)
        assert fr.sample(stats) is True

        def boom(*a, **k):
            raise AssertionError("deduped sample read the registry")

        monkeypatch.setattr(stats, "counter_totals", boom)
        assert fr.sample(stats) is False  # gated before any read
        with fr._lock:
            assert len(fr._ring) == 1

    def test_ring_is_bounded(self):
        stats = StatsClient()
        fr = FlightRecorder(capacity=5, min_interval=0.0)
        for _ in range(20):
            fr.sample(stats)
        with fr._lock:
            assert len(fr._ring) == 5

    def test_freeze_pins_incidents_bounded(self):
        stats = StatsClient()
        fr = FlightRecorder(min_interval=0.0)
        for i in range(6):
            fr.sample(stats)
            fr.freeze(f"r{i}")
        inc = fr.incidents()
        assert len(inc) == 4  # deque(maxlen=4): newest survive
        assert [e["reason"] for e in inc] == ["r2", "r3", "r4", "r5"]
        assert "timeline" in inc[0] and "at" in inc[0]

    def test_raw_samples_survive_missed_ticks(self):
        """A gap in sampling widens spanS but never corrupts rates —
        the raw-cumulative-totals design contract."""
        stats = StatsClient()
        fr = FlightRecorder(min_interval=0.0)
        stats.count("import_bits_total", 10)
        fr.sample(stats)
        # Long enough that spanS's 2-decimal rounding (worst case
        # ±0.005 s) stays inside the 5% product tolerance even when a
        # loaded scheduler stretches the sleep.
        time.sleep(0.2)  # "missed" ticks
        stats.count("import_bits_total", 90)
        fr.sample(stats)
        ent = fr.timeline(60)[0]
        assert ent["spanS"] >= 0.2
        assert ent["ingestBitsPerS"] * ent["spanS"] == pytest.approx(
            90, rel=0.05
        )


class TestSloBurnFreeze:
    def test_burn_transition_freezes_recorder(self):
        """End to end: an objective crossing into burning (both burn
        windows > 1) on evaluate_slos pins exactly one incident; staying
        burning does not re-pin."""
        mon = RuntimeMonitor()
        # A unique tagged series so parallel tests can't pollute the
        # windowed math for this objective.
        tagged = global_stats.with_tags("call:TlBurnTest")
        mon.slo = [{
            "metric": 'query_seconds{call="TlBurnTest"}',
            "quantile": 0.5, "threshold_s": 0.01, "window_s": 300,
        }]
        # Baseline snapshot, then over-threshold observations: every
        # windowed delta is 100% violations → both windows burn.
        mon.record_histogram_snapshot(force=True)
        for _ in range(20):
            tagged.timing("query_seconds", 0.2)
        assert not any(
            "TlBurnTest" in i["reason"]
            for i in global_flight_recorder.incidents()
        )
        out = mon.evaluate_slos()
        assert out[0]["burning"] is True
        mine = [
            i["reason"] for i in global_flight_recorder.incidents()
            if "TlBurnTest" in i["reason"]
        ]
        assert mine == ['slo-burn:query_seconds{call="TlBurnTest"}']
        # Second evaluation while still burning: no new incident.
        tagged.timing("query_seconds", 0.2)
        out = mon.evaluate_slos()
        assert out[0]["burning"] is True
        again = [
            r for r in (i["reason"]
                        for i in global_flight_recorder.incidents())
            if "TlBurnTest" in r
        ]
        assert len(again) == 1


class TestWorkloadTable:
    def _profile(self, shape, device_us=1000, duration=0.01, query=""):
        p = QueryProfile(query=query)
        p.shape = shape
        p.incr("device_wait_us", device_us)
        p.incr("device_launches", 2)
        p.incr("bytes_shipped", 512)
        p.incr("bytes_returned", 64)
        p.incr("lock_wait_us", 100)
        p.finish()
        p.duration = duration
        return p

    def test_aggregates_by_shape(self):
        wt = WorkloadTable()
        wt.observe(self._profile("Count(Row(f=?))", query="Count(Row(f=1))"))
        wt.observe(self._profile("Count(Row(f=?))", device_us=3000))
        wt.observe(self._profile("Row(g=?)"))
        snap = wt.snapshot()
        assert snap["shapes"] == 2
        top = snap["entries"][0]
        # Heaviest first by cumulative device-seconds.
        assert top["shape"] == "Count(Row(f=?))"
        assert top["queries"] == 2
        assert top["deviceSeconds"] == pytest.approx(0.004)
        assert top["launches"] == 4
        assert top["bytesShipped"] == 1024
        assert top["lockWaitSeconds"] == pytest.approx(0.0002)
        assert top["example"] == "Count(Row(f=1))"

    def test_eviction_drops_cheapest_device_consumer(self):
        wt = WorkloadTable(capacity=3)
        wt.observe(self._profile("s_cheap", device_us=1))
        wt.observe(self._profile("s_mid", device_us=1000))
        wt.observe(self._profile("s_hot", device_us=100000))
        wt.observe(self._profile("s_new", device_us=500))
        snap = wt.snapshot()
        assert snap["shapes"] == 3
        assert snap["evicted"] == 1
        shapes = {e["shape"] for e in snap["entries"]}
        assert "s_cheap" not in shapes  # the safest loss
        assert {"s_hot", "s_mid", "s_new"} == shapes

    def test_profile_without_shape_is_ignored(self):
        wt = WorkloadTable()
        p = QueryProfile()
        p.finish()
        wt.observe(p)
        assert wt.snapshot()["shapes"] == 0

    def test_new_shape_emits_counter(self):
        wt = WorkloadTable()
        stats = StatsClient()
        wt.observe(self._profile("s1"), stats)
        wt.observe(self._profile("s1"), stats)
        wt.observe(self._profile("s2"), stats)
        counters = stats.snapshot()["counters"]
        assert counters["workload_shapes_total"] == 2  # distinct shapes


class TestShapeKey:
    def test_literals_collapse_structure_survives(self):
        k1 = shape_key(parse_string("Count(Row(f=3))").calls[0])
        k2 = shape_key(parse_string("Count(Row(f=99))").calls[0])
        k3 = shape_key(parse_string("Count(Row(g=3))").calls[0])
        assert k1 == k2 == "Count(Row(f=?))"
        assert k3 == "Count(Row(g=?))" != k1

    def test_difference_keeps_child_order(self):
        a = shape_key(
            parse_string("Difference(Row(f=1), Row(g=1))").calls[0]
        )
        b = shape_key(
            parse_string("Difference(Row(g=1), Row(f=1))").calls[0]
        )
        assert a != b  # A\B is not B\A: shape is ordered structure

    def test_condition_keeps_operator_drops_bound(self):
        a = shape_key(parse_string("Row(v > 5)").calls[0])
        b = shape_key(parse_string("Row(v > 99999)").calls[0])
        c = shape_key(parse_string("Row(v < 5)").calls[0])
        assert a == b
        assert a != c  # the operator IS structure

    def test_nested_call_args_recurse(self):
        k = shape_key(
            parse_string(
                'GroupBy(Rows(_field="f"), filter=Row(g=7))'
            ).calls[0]
        )
        assert "Rows(_field=f)" in k
        assert "filter=Row(g=?)" in k
        assert "7" not in k


@pytest.fixture
def tpu_server(tmp_path):
    holder = Holder(str(tmp_path / "data")).open()
    ex = Executor(holder, backend=TPUBackend(holder))
    srv = Server(API(holder, ex), host="localhost", port=0).open()
    yield srv
    srv.close()
    holder.close()


def _post(srv, path, body=b"{}", ctype="application/json"):
    r = urllib.request.Request(
        srv.uri + path, data=body, method="POST",
        headers={"Content-Type": ctype},
    )
    return json.loads(urllib.request.urlopen(r).read())


def get_json(srv, path):
    return json.loads(urllib.request.urlopen(srv.uri + path).read())


class TestEndpoints:
    def test_debug_workload_serves_shapes(self, tpu_server):
        _post(tpu_server, "/index/i")
        _post(tpu_server, "/index/i/field/f")
        _post(tpu_server, "/index/i/query", b"Set(10, f=1)", "text/plain")
        for row in (1, 1, 1):
            _post(tpu_server, "/index/i/query",
                  f"Count(Row(f={row}))".encode(), "text/plain")
        out = get_json(tpu_server, "/debug/workload")
        ent = next(
            e for e in out["entries"] if e["shape"] == "Count(Row(f=?))"
        )
        assert ent["queries"] >= 3
        assert ent["deviceSeconds"] > 0  # counted launches attributed
        assert ent["launches"] > 0
        # ?top=N is honored.
        top = get_json(tpu_server, "/debug/workload?top=1")
        assert len(top["entries"]) <= 1

    def test_debug_timeline_accrues_with_use(self, tpu_server):
        # Each scrape takes a sample; two spaced scrapes give >= 1 delta
        # (the recorder is process-global, so other tests' samples may
        # contribute more — only the floor is pinned).
        get_json(tpu_server, "/debug/timeline")
        time.sleep(0.6)  # past min_interval
        out = get_json(tpu_server, "/debug/timeline?seconds=30")
        assert out["windowS"] == 30
        assert isinstance(out["incidents"], list)
        assert len(out["timeline"]) >= 1
        ent = out["timeline"][-1]
        for key in ("qps", "lockWaitS", "hbmResidentBytes", "spanS"):
            assert key in ent
        # Garbage seconds is a structured 400.
        with pytest.raises(urllib.error.HTTPError) as ei:
            get_json(tpu_server, "/debug/timeline?seconds=abc")
        assert ei.value.code == 400

    def test_nonexplain_ring_entries_carry_device_wait(self, tpu_server):
        """Satellite fix: a plain (non-explain) query's /debug/queries
        ring entry carries the cheap scalar launch totals — before
        ISSUE 18 per-launch device-wait existed only inside explain
        plans."""
        _post(tpu_server, "/index/i")
        _post(tpu_server, "/index/i/field/f")
        _post(tpu_server, "/index/i/query", b"Set(10, f=1)", "text/plain")
        _post(tpu_server, "/index/i/query", b"Count(Row(f=1))", "text/plain")
        recent = get_json(tpu_server, "/debug/queries")["recent"]
        ent = next(
            e for e in recent
            if e["query"] == "Count(Row(f=1))" and "explain" not in e
        )
        c = ent["counters"]
        assert c["device_launches"] >= 1
        assert c["device_wait_us"] > 0
        assert c["bytes_shipped"] > 0
        assert c["bytes_returned"] > 0


class TestLockWaitAttribution:
    def test_contended_wait_lands_in_profile(self):
        """A profiled thread that loses a contended acquire charges the
        wait to its own profile's lock_wait_us counter."""
        from pilosa_tpu.utils.locks import InstrumentedLock

        lock = InstrumentedLock("test_tl_site")
        release = threading.Event()
        held = threading.Event()

        def holder_thread():
            with lock:
                held.set()
                release.wait(5)

        t = threading.Thread(target=holder_thread, daemon=True)
        t.start()
        held.wait(5)
        with profile_scope(index="i", query="q") as p:
            p.shape = "TestShape()"
            threading.Timer(0.05, release.set).start()
            with lock:
                pass
            assert p.counters.get("lock_wait_us", 0) > 0
        t.join(timeout=5)
        # And the scope export fed the workload table with it.
        ent = next(
            e for e in global_workload_table.top(0)
            if e["shape"] == "TestShape()"
        )
        assert ent["lockWaitSeconds"] > 0
