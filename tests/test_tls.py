"""TLS end-to-end (VERDICT r4 #2; reference server/tlsconfig.go:1-40,
server/config.go:120-130): HTTPS listener, https URIs, internal-client
verification, config keys, and an all-HTTPS cluster running queries,
import, resize, and anti-entropy — plus a real subprocess cluster booted
from PILOSA_TPU_TLS_* env."""

import datetime
import json
import os
import ssl
import subprocess
import sys
import tempfile
import time
import urllib.request

import pytest

from pilosa_tpu.server.config import Config, TLSConfig
from pilosa_tpu.shardwidth import SHARD_WIDTH

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _make_cert(tmpdir) -> tuple[str, str]:
    """Self-signed cert for 127.0.0.1/localhost via the cryptography lib.
    Returns (cert_path, key_path); skips cleanly on images without the
    lib (the TLS plane is optional there)."""
    import ipaddress

    x509_mod = pytest.importorskip(
        "cryptography.x509", reason="TLS tests need the cryptography lib"
    )
    del x509_mod
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import rsa
    from cryptography.x509.oid import NameOID

    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    name = x509.Name(
        [x509.NameAttribute(NameOID.COMMON_NAME, "pilosa-tpu-test")]
    )
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (
        x509.CertificateBuilder()
        .subject_name(name)
        .issuer_name(name)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=5))
        .not_valid_after(now + datetime.timedelta(days=1))
        .add_extension(
            x509.SubjectAlternativeName(
                [
                    x509.DNSName("localhost"),
                    x509.IPAddress(ipaddress.ip_address("127.0.0.1")),
                ]
            ),
            critical=False,
        )
        .sign(key, hashes.SHA256())
    )
    cert_path = os.path.join(tmpdir, "cert.pem")
    key_path = os.path.join(tmpdir, "key.pem")
    with open(cert_path, "wb") as f:
        f.write(cert.public_bytes(serialization.Encoding.PEM))
    with open(key_path, "wb") as f:
        f.write(
            key.private_bytes(
                serialization.Encoding.PEM,
                serialization.PrivateFormat.TraditionalOpenSSL,
                serialization.NoEncryption(),
            )
        )
    return cert_path, key_path


@pytest.fixture(scope="module")
def tls_files():
    with tempfile.TemporaryDirectory(prefix="pilosa-tls-") as d:
        yield _make_cert(d)


@pytest.fixture
def tls_cfg(tls_files):
    cert, key = tls_files
    return TLSConfig(certificate=cert, key=key, ca_certificate=cert)


class TestTLSConfig:
    def test_sources_and_roundtrip(self, tls_files, tmp_path):
        cert, key = tls_files
        toml = tmp_path / "c.toml"
        toml.write_text(
            f'[tls]\ncertificate = "{cert}"\nkey = "{key}"\n'
            "skip-verify = true\n"
        )
        cfg = Config.from_sources(str(toml), env={})
        assert cfg.tls.enabled and cfg.tls.skip_verify
        # Env overrides TOML.
        cfg = Config.from_sources(
            str(toml), env={"PILOSA_TPU_TLS_SKIP_VERIFY": "false",
                            "PILOSA_TPU_TLS_CA_CERTIFICATE": cert},
        )
        assert not cfg.tls.skip_verify
        assert cfg.tls.ca_certificate == cert
        # generate-config emits the keys; re-parsing them round-trips.
        text = cfg.toml_text()
        assert "[tls]" in text and "skip-verify" in text
        back = tmp_path / "back.toml"
        back.write_text(text)
        cfg2 = Config.from_sources(str(back), env={})
        assert cfg2.tls.certificate == cert and cfg2.tls.key == key

    def test_contexts(self, tls_cfg):
        assert tls_cfg.server_context() is not None
        ctx = tls_cfg.client_context()
        assert ctx.verify_mode == ssl.CERT_REQUIRED
        loose = TLSConfig(skip_verify=True).client_context()
        assert loose.verify_mode == ssl.CERT_NONE


class TestTLSServer:
    def test_https_round_trip_and_cert_verification(self, tmp_path, tls_cfg):
        from pilosa_tpu.core import Holder
        from pilosa_tpu.server.api import API
        from pilosa_tpu.server.http import Server

        holder = Holder(str(tmp_path / "d")).open()
        srv = Server(API(holder), host="127.0.0.1", port=0, tls=tls_cfg).open()
        try:
            assert srv.uri.startswith("https://")
            ctx = tls_cfg.client_context()
            req = urllib.request.Request(
                srv.uri + "/index/i", method="POST", data=b"{}"
            )
            with urllib.request.urlopen(req, timeout=10, context=ctx) as r:
                assert json.loads(r.read())["name"] == "i"
            # A verifying client WITHOUT the CA must be refused.
            strict = ssl.create_default_context()
            with pytest.raises(urllib.error.URLError):
                urllib.request.urlopen(
                    srv.uri + "/status", timeout=10, context=strict
                )
            # Plain-HTTP client against the TLS port fails cleanly.
            with pytest.raises(Exception):
                urllib.request.urlopen(
                    f"http://127.0.0.1:{srv.port}/status", timeout=10
                )
        finally:
            srv.close()
            holder.close()


class TestTLSCluster:
    def test_all_https_cluster_query_import_resize_antientropy(self, tls_cfg):
        """The VERDICT done-bar: a cluster whose every wire hop is HTTPS
        runs queries, bulk import, a resize (node add), and an
        anti-entropy pass."""
        from tests.cluster_harness import TestCluster

        with TestCluster(
            3, replica_n=2, tls=tls_cfg, client_ssl=tls_cfg.client_context()
        ) as tc:
            for cn in tc.nodes:
                assert cn.node.uri.scheme == "https"
            tc.create_index("i")
            tc.create_field("i", "f")
            # Writes through one node, reads through another (scatter +
            # replica routing all over HTTPS).
            cols = [s * SHARD_WIDTH + 3 for s in range(5)]
            tc.query(0, "i", " ".join(f"Set({c}, f=1)" for c in cols))
            out = tc.query(1, "i", "Count(Row(f=1))")
            assert out["results"][0] == len(cols)
            # Bulk import through the API (the import fan-out path).
            rows = [1] * 64
            icols = [int(c) * 7 + SHARD_WIDTH for c in range(64)]
            tc.nodes[2].api.import_bits("i", "f", rows, icols)
            out = tc.query(0, "i", "Count(Row(f=1))")
            assert out["results"][0] == len(cols) + 64
            # Resize: grow to 4 nodes over HTTPS.
            tc.add_node_via_resize()
            out = tc.query(3, "i", "Count(Row(f=1))")
            assert out["results"][0] == len(cols) + 64
            # Anti-entropy pass over HTTPS.
            tc.sync_all()
            out = tc.query(2, "i", "Count(Row(f=1))")
            assert out["results"][0] == len(cols) + 64


class TestTLSSubprocess:
    def test_three_real_processes_all_https(self, tls_files):
        """Real servers booted from PILOSA_TPU_TLS_* env + https hosts:
        the config -> CLI -> listener -> internal-client path, not just
        the in-process seams."""
        cert, key = tls_files
        import socket

        socks, ports = [], []
        for _ in range(3):
            s = socket.socket()
            s.bind(("127.0.0.1", 0))
            ports.append(s.getsockname()[1])
            socks.append(s)
        for s in socks:
            s.close()
        hosts = ",".join(f"https://127.0.0.1:{p}" for p in ports)
        tmp = tempfile.mkdtemp(prefix="pilosa-tls-proc-")
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
        ctx.load_verify_locations(cert)
        ctx.check_hostname = False  # IP SAN present, but keep it simple

        def req(port, method, path, body=None, timeout=10):
            data = body.encode() if isinstance(body, str) else (
                json.dumps(body).encode() if body is not None else None
            )
            r = urllib.request.Request(
                f"https://127.0.0.1:{port}{path}", data=data, method=method
            )
            with urllib.request.urlopen(r, timeout=timeout, context=ctx) as resp:
                raw = resp.read()
            return json.loads(raw) if raw else {}

        env = dict(
            os.environ,
            PYTHONPATH=REPO,
            JAX_PLATFORMS="cpu",
            PILOSA_TPU_CLUSTER_HOSTS=hosts,
            PILOSA_TPU_CLUSTER_REPLICAS="2",
            PILOSA_TPU_TLS_CERTIFICATE=cert,
            PILOSA_TPU_TLS_KEY=key,
            PILOSA_TPU_TLS_CA_CERTIFICATE=cert,
            PILOSA_TPU_TLS_SKIP_VERIFY="true",
        )
        procs = []
        try:
            for i in range(3):
                procs.append(
                    subprocess.Popen(
                        [sys.executable, "-m", "pilosa_tpu.cli", "server",
                         "-d", f"{tmp}/node{i}",
                         "-b", f"127.0.0.1:{ports[i]}", "--executor", "cpu"],
                        env=env, stdout=subprocess.DEVNULL,
                        stderr=subprocess.STDOUT, cwd=REPO,
                    )
                )
            for p in ports:
                deadline = time.time() + 30
                while True:
                    try:
                        req(p, "GET", "/status", timeout=2)
                        break
                    except Exception:
                        if time.time() > deadline:
                            raise TimeoutError(f"server on {p} not ready")
                        time.sleep(0.2)
            st = req(ports[0], "GET", "/status")
            assert all(n["uri"]["scheme"] == "https" for n in st["nodes"])
            req(ports[0], "POST", "/index/i", {})
            req(ports[0], "POST", "/index/i/field/f", {})
            cols = [s * SHARD_WIDTH + 9 for s in range(4)]
            req(ports[0], "POST", "/index/i/query",
                " ".join(f"Set({c}, f=2)" for c in cols))
            # Every node answers over HTTPS (cross-node scatter inside).
            for p in ports:
                out = req(p, "POST", "/index/i/query", "Count(Row(f=2))",
                          timeout=30)
                assert out["results"][0] == len(cols)
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
                    p.wait(timeout=10)


class TestTLSCtlCommands:
    def test_import_export_against_https(self, tls_files, tmp_path):
        """cli import/export must reach an HTTPS server via the
        --ca-certificate / --skip-verify trust flags (code review r5)."""
        from pilosa_tpu.cli import main as cli_main
        from pilosa_tpu.core import Holder
        from pilosa_tpu.server.api import API
        from pilosa_tpu.server.http import Server

        cert, key = tls_files
        holder = Holder(str(tmp_path / "d")).open()
        srv = Server(
            API(holder), host="127.0.0.1", port=0,
            tls=TLSConfig(certificate=cert, key=key),
        ).open()
        try:
            csv = tmp_path / "data.csv"
            csv.write_text("1,3\n1,9\n2,3\n")
            rc = cli_main([
                "import", "--host", srv.uri, "--ca-certificate", cert,
                "--create", "-i", "i", "-f", "f", str(csv),
            ])
            assert rc == 0
            from pilosa_tpu.exec import Executor

            assert Executor(holder).execute("i", "Count(Row(f=1))")[0] == 2
            import contextlib
            import io

            out = io.StringIO()
            with contextlib.redirect_stdout(out):
                rc = cli_main([
                    "export", "--host", srv.uri, "--skip-verify",
                    "-i", "i", "-f", "f",
                ])
            assert rc == 0
            lines = sorted(out.getvalue().strip().splitlines())
            assert lines == ["1,3", "1,9", "2,3"]
        finally:
            srv.close()
            holder.close()
