"""TPU backend tests: block packing, kernels, TPUBackend differential vs
the CPU oracle, and mesh execution on the 8-device virtual CPU platform
(the multi-node-without-a-cluster strategy, SURVEY.md §4.3)."""

import time

import numpy as np
import pytest

import jax

from pilosa_tpu.core import Fragment, Holder
from pilosa_tpu.core.field import options_for_int
from pilosa_tpu.exec import Executor
from pilosa_tpu.exec.result import result_to_json
from pilosa_tpu.exec.tpu import TPUBackend
from pilosa_tpu.ops.blocks import WORDS_PER_SHARD, pack_fragment, pack_row, unpack_row
from pilosa_tpu.ops.kernels import pair_stats, pair_stats_xla
from pilosa_tpu.parallel import ShardMesh
from pilosa_tpu.shardwidth import SHARD_WIDTH


@pytest.fixture
def holder(tmp_path):
    h = Holder(str(tmp_path / "data")).open()
    yield h
    h.close()


class TestBlockPacking:
    def test_pack_roundtrip(self, rng):
        f = Fragment(None, "i", "f", "standard", 0)
        cols = np.unique(rng.integers(0, SHARD_WIDTH, 5000, dtype=np.uint64))
        f.bulk_import(np.full(cols.size, 3, dtype=np.uint64), cols)
        block = pack_fragment(f)
        assert block.shape[1] == WORDS_PER_SHARD
        assert block.shape[0] % 8 == 0
        np.testing.assert_array_equal(unpack_row(block[3]), cols)
        assert block[0].sum() == 0

    def test_pack_dense_container(self):
        f = Fragment(None, "i", "f", "standard", 0)
        cols = np.arange(0, 100_000, dtype=np.uint64)  # bitmap containers
        f.bulk_import(np.zeros(cols.size, dtype=np.uint64), cols)
        block = pack_fragment(f)
        np.testing.assert_array_equal(unpack_row(block[0]), cols)

    def test_pack_row_matches_pack_fragment(self, rng):
        f = Fragment(None, "i", "f", "standard", 0)
        cols = np.unique(rng.integers(0, SHARD_WIDTH, 5000, dtype=np.uint64))
        f.bulk_import(np.full(cols.size, 2, dtype=np.uint64), cols)
        block = pack_fragment(f)
        np.testing.assert_array_equal(pack_row(f, 2), block[2])
        np.testing.assert_array_equal(pack_row(f, 0), np.zeros(WORDS_PER_SHARD, np.uint32))


class TestPairStatsKernel:
    """The batched-count Pallas kernel (interpret mode on CPU) must match
    both the fused-XLA formulation and a numpy oracle."""

    def test_pair_stats_matches_numpy(self, rng):
        S, RF, RG, W = 3, 8, 16, 512
        f = rng.integers(0, 2**32, (S, RF, W), dtype=np.uint32)
        g = rng.integers(0, 2**32, (S, RG, W), dtype=np.uint32)
        pair, cf, cg = (np.asarray(x) for x in pair_stats(f, g, interpret=True))
        want_pair = np.zeros((RF, RG), dtype=np.int64)
        for a in range(RF):
            for b in range(RG):
                want_pair[a, b] = np.bitwise_count(f[:, a] & g[:, b]).sum()
        np.testing.assert_array_equal(pair, want_pair)
        np.testing.assert_array_equal(cf, np.bitwise_count(f).sum(axis=(0, 2)))
        np.testing.assert_array_equal(cg, np.bitwise_count(g).sum(axis=(0, 2)))

    def test_pair_stats_matches_xla(self, rng):
        S, R, W = 5, 8, 256
        f = rng.integers(0, 2**32, (S, R, W), dtype=np.uint32)
        g = rng.integers(0, 2**32, (S, R, W), dtype=np.uint32)
        got = pair_stats(f, g, interpret=True)
        want = pair_stats_xla(f, g)
        for a, b in zip(got, want):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


class TestTPUBackendDifferential:
    """The TPU backend must agree with the CPU oracle on every query."""

    def _setup(self, holder, rng):
        idx = holder.create_index("i")
        idx.create_field("f")
        idx.create_field("g")
        idx.create_field("v", options_for_int(-500, 500))
        ex_cpu = Executor(holder)
        # random data across 3 shards
        for row in [1, 2, 3]:
            cols = np.unique(rng.integers(0, 3 * SHARD_WIDTH, 2000, dtype=np.uint64))
            idx.field("f").import_bits(np.full(cols.size, row, dtype=np.uint64), cols)
            ef = idx.existence_field()
            ef.import_bits(np.zeros(cols.size, dtype=np.uint64), cols)
        cols = np.unique(rng.integers(0, 3 * SHARD_WIDTH, 1500, dtype=np.uint64))
        idx.field("g").import_bits(np.full(cols.size, 7, dtype=np.uint64), cols)
        ex_tpu = Executor(holder, backend=TPUBackend(holder))
        return ex_cpu, ex_tpu

    QUERIES = [
        "Row(f=1)",
        "Count(Row(f=2))",
        "Count(Intersect(Row(f=1), Row(g=7)))",
        "Count(Union(Row(f=1), Row(f=2), Row(f=3)))",
        "Count(Difference(Row(f=1), Row(g=7)))",
        "Count(Xor(Row(f=2), Row(g=7)))",
        "Union(Row(f=1), Row(g=7))",
        "Intersect(Row(f=1), Row(f=2))",
        "Not(Row(f=1))",
        "All()",
        "Count(Not(Union(Row(f=1), Row(f=2))))",
        "TopN(f, n=2)",
        "TopN(f)",
        "TopN(f, Row(g=7), n=3)",
    ]

    @pytest.mark.parametrize("q", QUERIES)
    def test_differential(self, holder, rng, q):
        ex_cpu, ex_tpu = self._setup(holder, rng)
        want = [result_to_json(r) for r in ex_cpu.execute("i", q)]
        got = [result_to_json(r) for r in ex_tpu.execute("i", q)]
        assert got == want, q

    def test_write_invalidates_device_blocks(self, holder, rng):
        ex_cpu, ex_tpu = self._setup(holder, rng)
        before = ex_tpu.execute("i", "Count(Row(f=1))")[0]
        ex_tpu.execute("i", f"Set({SHARD_WIDTH + 123456}, f=1)")
        after = ex_tpu.execute("i", "Count(Row(f=1))")[0]
        assert after == before + 1
        # still agrees with oracle
        assert ex_cpu.execute("i", "Count(Row(f=1))")[0] == after

    BSI_QUERIES = [
        "Sum(field=v)",
        "Sum(Row(f=1), field=v)",
        "Min(field=v)",
        "Max(field=v)",
        "Min(Row(f=1), field=v)",
        "Max(Row(f=1), field=v)",
        "Row(v > 0)",
        "Row(v >= 0)",
        "Row(v < 0)",
        "Row(v <= 0)",
        "Row(v == 42)",
        "Row(v != 42)",
        "Row(v != null)",
        "Row(v > -50)",
        "Row(v < -50)",
        "Row(v >= -10)",
        "Row(v <= -10)",
        "Row(v > 1000)",  # out of range
        "Row(v < 1000)",  # encompassing -> notNull
        "Row(v >< [-20, 30])",  # mixed between
        "Row(v >< [5, 60])",  # positive between
        "Row(v >< [-60, -5])",  # negative between
        "Row(v >< [-500, 500])",  # full range -> notNull
        "Count(Intersect(Row(f=1), Row(v > 0)))",
    ]

    def _setup_bsi(self, holder, rng):
        ex_cpu, ex_tpu = self._setup(holder, rng)
        cols = np.unique(rng.integers(0, 3 * SHARD_WIDTH, 800, dtype=np.uint64))
        vals = rng.integers(-500, 501, cols.size)
        holder.index("i").field("v").import_value(cols, vals)
        ex_cpu.execute("i", "Set(5, v=42) Set(6, v=-10)")
        return ex_cpu, ex_tpu

    @pytest.mark.parametrize("q", BSI_QUERIES)
    def test_bsi_runs_on_device(self, holder, rng, q):
        ex_cpu, ex_tpu = self._setup_bsi(holder, rng)
        want = [result_to_json(r) for r in ex_cpu.execute("i", q)]
        got = [result_to_json(r) for r in ex_tpu.execute("i", q)]
        assert got == want, q

    def test_shift_on_device(self, holder, rng):
        ex_cpu, ex_tpu = self._setup(holder, rng)
        for q in ["Shift(Row(f=1), n=1)", "Shift(Row(f=2), n=40)", "Count(Shift(Row(f=1), n=3))"]:
            want = [result_to_json(r) for r in ex_cpu.execute("i", q)]
            got = [result_to_json(r) for r in ex_tpu.execute("i", q)]
            assert got == want, q

    def test_time_range_on_device(self, holder, rng):
        from pilosa_tpu.core.field import options_for_time

        ex_cpu, ex_tpu = self._setup(holder, rng)
        idx = holder.index("i")
        idx.create_field("t", options_for_time("YMDH"))
        ex_cpu.execute("i", 'Set(3, t=9, 2019-08-03T10:00)')
        ex_cpu.execute("i", 'Set(1048579, t=9, 2019-08-05T12:00)')
        q = "Row(t=9, from='2019-08-01T00:00', to='2019-08-31T00:00')"
        want = [result_to_json(r) for r in ex_cpu.execute("i", q)]
        got = [result_to_json(r) for r in ex_tpu.execute("i", q)]
        assert got == want

    def test_hbm_budget_evicts(self, holder, rng):
        ex_cpu, _ = self._setup(holder, rng)
        # Budget fits roughly one stack: queries still correct, stacks evict.
        be = TPUBackend(holder, max_bytes=3 * 8 * WORDS_PER_SHARD * 4)
        ex_tpu = Executor(holder, backend=be)
        for q in ["Count(Row(f=1))", "Count(Row(g=7))", "Count(Row(f=2))"]:
            want = [result_to_json(r) for r in ex_cpu.execute("i", q)]
            got = [result_to_json(r) for r in ex_tpu.execute("i", q)]
            assert got == want, q
        assert be.blocks.evictions > 0
        assert be.blocks.resident_bytes() <= 3 * 8 * WORDS_PER_SHARD * 4


class TestMeshExecutor:
    """Real PQL through the 8-device mesh: holder-resident fragments are
    stacked, sharded over the mesh with NamedSharding(P('shards')), and
    queried through shard_map+psum — differentially checked vs the CPU
    oracle (the VERDICT r1 top-next item)."""

    def _setup(self, holder, rng):
        idx = holder.create_index("i")
        idx.create_field("f")
        idx.create_field("g")
        idx.create_field("v", options_for_int(-500, 500))
        n_shards = 11  # not a multiple of 8: exercises shard padding
        for row in [1, 2, 3]:
            cols = np.unique(rng.integers(0, n_shards * SHARD_WIDTH, 6000, dtype=np.uint64))
            idx.field("f").import_bits(np.full(cols.size, row, dtype=np.uint64), cols)
            idx.existence_field().import_bits(np.zeros(cols.size, dtype=np.uint64), cols)
        cols = np.unique(rng.integers(0, n_shards * SHARD_WIDTH, 4000, dtype=np.uint64))
        idx.field("g").import_bits(np.full(cols.size, 7, dtype=np.uint64), cols)
        cols = np.unique(rng.integers(0, n_shards * SHARD_WIDTH, 900, dtype=np.uint64))
        vals = rng.integers(-500, 501, cols.size)
        idx.field("v").import_value(cols, vals)
        ex_cpu = Executor(holder)
        ex_mesh = Executor(holder, backend=TPUBackend(holder, mesh=ShardMesh()))
        return ex_cpu, ex_mesh

    QUERIES = [
        "Count(Intersect(Row(f=1), Row(g=7)))",
        "Count(Union(Row(f=1), Row(f=2), Row(f=3)))",
        "Count(Not(Row(f=1)))",
        "Row(f=2)",
        "TopN(f, n=2)",
        "TopN(f, Row(g=7), n=3)",
        "Sum(field=v)",
        "Min(field=v)",
        "Max(field=v)",
        "Count(Row(v > 100))",
        "Count(Row(v >< [-100, 100]))",
    ]

    @pytest.mark.parametrize("q", QUERIES)
    def test_mesh_differential(self, holder, rng, q):
        ex_cpu, ex_mesh = self._setup(holder, rng)
        want = [result_to_json(r) for r in ex_cpu.execute("i", q)]
        got = [result_to_json(r) for r in ex_mesh.execute("i", q)]
        assert got == want, q

    def test_mesh_count_batch(self, holder, rng):
        _, ex_mesh = self._setup(holder, rng)
        from pilosa_tpu.pql import parse_string

        be = ex_mesh.backend
        calls = [
            parse_string(f"Intersect(Row(f={r}), Row(g=7))").calls[0] for r in [1, 2, 3]
        ]
        shards = list(range(11))
        batch = be.count_batch("i", calls, shards)
        singles = [be.count_shards("i", c, shards) for c in calls]
        assert batch == singles


class TestShardMesh:
    def test_mesh_has_8_devices(self):
        assert len(jax.devices()) == 8


class TestCountBatch:
    def test_count_batch_matches_singles(self, holder, rng):
        idx = holder.create_index("i")
        idx.create_field("f")
        idx.create_field("g")
        for row in [1, 2, 3]:
            cols = np.unique(rng.integers(0, 2 * SHARD_WIDTH, 3000, dtype=np.uint64))
            idx.field("f").import_bits(np.full(cols.size, row, dtype=np.uint64), cols)
        cols = np.unique(rng.integers(0, 2 * SHARD_WIDTH, 3000, dtype=np.uint64))
        idx.field("g").import_bits(np.full(cols.size, 9, dtype=np.uint64), cols)
        be = TPUBackend(holder)
        from pilosa_tpu.pql import parse_string

        calls = [
            parse_string(f"Intersect(Row(f={r}), Row(g=9))").calls[0] for r in [1, 2, 3, 7]
        ]
        shards = [0, 1]
        batch = be.count_batch("i", calls, shards)
        singles = [be.count_shards("i", c, shards) for c in calls]
        assert batch == singles
        assert batch[3] == 0  # nonexistent row counts zero

    def _setup(self, holder, rng):
        idx = holder.create_index("i")
        idx.create_field("f")
        idx.create_field("g")
        idx.create_field("v", options_for_int(-100, 100))
        for row in [1, 2, 3]:
            cols = np.unique(rng.integers(0, 2 * SHARD_WIDTH, 3000, dtype=np.uint64))
            idx.field("f").import_bits(np.full(cols.size, row, dtype=np.uint64), cols)
        cols = np.unique(rng.integers(0, 2 * SHARD_WIDTH, 3000, dtype=np.uint64))
        idx.field("g").import_bits(np.full(cols.size, 9, dtype=np.uint64), cols)
        cols = np.unique(rng.integers(0, 2 * SHARD_WIDTH, 500, dtype=np.uint64))
        idx.field("v").import_value(cols, rng.integers(-100, 101, cols.size))
        return idx

    def test_mixed_verbs_pair_path(self, holder, rng):
        """All four verbs + single rows over one field pair derive from
        one pair_stats sweep; results must match per-query execution."""
        self._setup(holder, rng)
        from pilosa_tpu.pql import parse_string

        be = TPUBackend(holder)
        qs = [
            "Intersect(Row(f=1), Row(g=9))",
            "Union(Row(f=2), Row(g=9))",
            "Difference(Row(f=3), Row(g=9))",
            "Xor(Row(f=1), Row(g=9))",
            "Row(f=2)",
            "Row(g=9)",
            "Union(Row(f=99), Row(g=9))",  # missing row -> just |g|
        ]
        calls = [parse_string(q).calls[0] for q in qs]
        shards = [0, 1]
        assert be._pair_batch_plan("i", calls) is not None
        batch = be.count_batch("i", calls, shards)
        singles = [be.count_shards("i", c, shards) for c in calls]
        assert batch == singles

    def test_generic_path_groups_specs(self, holder, rng):
        """Non-pair-able batches (BSI, Not) group by spec shape and still
        match per-query execution."""
        self._setup(holder, rng)
        from pilosa_tpu.pql import parse_string

        be = TPUBackend(holder)
        qs = [
            "Row(v > 10)",
            "Row(v > -5)",
            "Not(Row(f=1))",
            "Intersect(Row(f=1), Row(v > 0))",
        ]
        calls = [parse_string(q).calls[0] for q in qs]
        assert be._pair_batch_plan("i", calls) is None
        shards = [0, 1]
        batch = be.count_batch("i", calls, shards)
        singles = [be.count_shards("i", c, shards) for c in calls]
        assert batch == singles

    def test_multi_count_query_through_executor(self, holder, rng):
        """A multi-Count PQL request is served by one batched dispatch and
        matches the CPU oracle call-for-call (the serving-batch surface)."""
        self._setup(holder, rng)
        q = (
            "Count(Intersect(Row(f=1), Row(g=9)))"
            "Count(Union(Row(f=2), Row(g=9)))"
            "Count(Row(f=3))"
            "Count(Xor(Row(f=1), Row(g=9)))"
        )
        want = Executor(holder).execute("i", q)
        got = Executor(holder, backend=TPUBackend(holder)).execute("i", q)
        assert got == want

    def test_bitmap_call_shard_subset(self, holder, rng):
        """Whole-query bitmap materialization honors shard subsets."""
        self._setup(holder, rng)
        from pilosa_tpu.pql import parse_string

        be = TPUBackend(holder)
        cpu = Executor(holder).backend
        c = parse_string("Union(Row(f=1), Row(g=9))").calls[0]
        for shards in ([0], [1], [0, 1]):
            got = be.bitmap_call("i", c, shards)
            want_cols = []
            for s in shards:
                want_cols.extend(cpu.bitmap_call_shard("i", c, s).columns().tolist())
            np.testing.assert_array_equal(got.columns(), np.array(sorted(want_cols), dtype=np.uint64))

    def test_count_batch_async_pipelines(self, holder, rng):
        """Multiple batches in flight resolve to correct results."""
        self._setup(holder, rng)
        from pilosa_tpu.pql import parse_string

        be = TPUBackend(holder)
        shards = [0, 1]
        pending = []
        for r in [1, 2, 3]:
            calls = [parse_string(f"Intersect(Row(f={r}), Row(g=9))").calls[0]]
            pending.append((r, be.count_batch_async("i", calls, shards)))
        for r, resolve in pending:
            c = parse_string(f"Intersect(Row(f={r}), Row(g=9))").calls[0]
            assert resolve() == [be.count_shards("i", c, shards)]

    def test_pair_cache_hit_and_write_invalidation(self, holder, rng):
        """Repeat batches serve from the host stats cache; a write to
        either field invalidates it (block identity = write epoch)."""
        idx = self._setup(holder, rng)
        from pilosa_tpu.pql import parse_string

        be = TPUBackend(holder)
        calls = [parse_string("Intersect(Row(f=1), Row(g=9))").calls[0]]
        shards = [0, 1]
        first = be.count_batch("i", calls, shards)
        assert len(be._pair_cache) == 1
        assert be.count_batch("i", calls, shards) == first
        # Set a column that's in g=9 but not f=1: intersect count +1.
        g_cols = set(Executor(holder).backend.bitmap_call_shard("i", parse_string("Row(g=9)").calls[0], 0).columns().tolist())
        f_cols = set(Executor(holder).backend.bitmap_call_shard("i", parse_string("Row(f=1)").calls[0], 0).columns().tolist())
        col = next(iter(g_cols - f_cols))
        idx.field("f").set_bit(1, col)
        assert be.count_batch("i", calls, shards) == [first[0] + 1]

    def test_count_batch_zero_scalar_group(self, holder, rng):
        """Calls with no traced scalars (All()) cannot scan over a query
        axis — they group into one shared program and fan out (found by
        the randomized churn differential)."""
        idx = self._setup(holder, rng)
        from pilosa_tpu.pql import parse_string

        ef = idx.existence_field()
        cols = np.unique(rng.integers(0, 2 * SHARD_WIDTH, 500, dtype=np.uint64))
        ef.import_bits(np.zeros(cols.size, dtype=np.uint64), cols)
        be = TPUBackend(holder)
        calls = [parse_string(q).calls[0]
                 for q in ("All()", "All()", "Not(Row(f=1))")]
        shards = [0, 1]
        got = be.count_batch("i", calls, shards)
        want = [be.count_shards("i", c, shards) for c in calls]
        assert got == want
        assert got[0] == got[1] == cols.size

    def test_host_slab_stats_match_pershard_kernel(self, rng):
        """The host-update helper must agree bit-for-bit with the device
        per-shard kernel — a host-refreshed table row sits next to
        device-swept rows."""
        from pilosa_tpu.exec.tpu import _host_slab_pair_flat
        from pilosa_tpu.ops.kernels import pair_stats_pershard

        S, RF, RG, W = 3, 8, 4, 512
        f = rng.integers(0, 2**32, (S, RF, W), dtype=np.uint32)
        g = rng.integers(0, 2**32, (S, RG, W), dtype=np.uint32)
        pair, cf, cg = (
            np.asarray(x) for x in pair_stats_pershard(f, g, interpret=True)
        )
        for i in range(S):
            np.testing.assert_array_equal(
                np.concatenate([pair[i].ravel(), cf[i, 0], cg[i, 0]]),
                _host_slab_pair_flat(f[i], g[i]),
            )

    def _pair_counters(self):
        from pilosa_tpu.utils.stats import global_stats

        c = global_stats._counters
        return (
            c[("pair_stats_sweeps_total", ())],
            c[("pair_stats_incremental_updates_total", ())],
        )

    def test_pair_incremental_host_update(self, holder, rng):
        """Write epochs are absorbed by the host per-shard table: after
        the one cold sweep, mutations cost zero device sweeps and every
        epoch's batch stays oracle-exact (the write-churn serving path,
        VERDICT r3 #1)."""
        idx = self._setup(holder, rng)
        from pilosa_tpu.pql import parse_string

        be = TPUBackend(holder)
        queries = [
            "Intersect(Row(f=1), Row(g=9))",
            "Union(Row(f=2), Row(g=9))",
            "Difference(Row(f=3), Row(g=9))",
            "Xor(Row(f=1), Row(g=9))",
            "Row(f=2)",
        ]
        calls = [parse_string(q).calls[0] for q in queries]
        shards = [0, 1]
        be.count_batch("i", calls, shards)
        s0, u0 = self._pair_counters()
        cpu = Executor(holder)
        wcol = 11  # fresh columns: every Set is a real mutation
        set_cols = []
        for epoch in range(4):
            for _ in range(3):
                fname = ("f", "g")[int(rng.integers(0, 2))]
                row = int(rng.integers(1, 4)) if fname == "f" else 9
                if set_cols and rng.integers(0, 3) == 0:
                    f2, r2, c2 = set_cols.pop()
                    idx.field(f2).clear_bit(r2, c2)
                else:
                    wcol += 97
                    idx.field(fname).set_bit(row, wcol % (2 * SHARD_WIDTH))
                    set_cols.append((fname, row, wcol % (2 * SHARD_WIDTH)))
            got = be.count_batch("i", calls, shards)
            want = [cpu.execute("i", f"Count({q})")[0] for q in queries]
            assert got == want, (epoch, got, want)
            s1, u1 = self._pair_counters()
            assert s1 == s0, "write epoch must not re-sweep on device"
            assert u1 == u0 + epoch + 1
        # Repeat without writes: plain identity hit, no update, no sweep.
        assert be.count_batch("i", calls, shards) == want
        assert self._pair_counters() == (s0, u0 + 4)

    def test_pair_incremental_same_field_pair(self, holder, rng):
        """Singles-only batches plan as the (f, f) self-pair; the host
        update must handle fb == fa (one slab, both sides)."""
        idx = self._setup(holder, rng)
        from pilosa_tpu.pql import parse_string

        be = TPUBackend(holder)
        calls = [parse_string(f"Row(f={r})").calls[0] for r in (1, 2, 3)]
        shards = [0, 1]
        be.count_batch("i", calls, shards)
        s0, u0 = self._pair_counters()
        idx.field("f").set_bit(2, 123457)
        cpu = Executor(holder)
        got = be.count_batch("i", calls, shards)
        want = [cpu.execute("i", f"Count(Row(f={r}))")[0] for r in (1, 2, 3)]
        assert got == want
        assert self._pair_counters() == (s0, u0 + 1)

    def test_pair_incremental_threshold_falls_back_to_sweep(self, holder, rng):
        """Epochs whose slab-tier shard count exceeds the cutoff re-sweep
        instead of paying per-shard host work. A bulk import is not
        delta-coverable (no bit-op ring entries), so with the gate shut
        it must go back to the device."""
        idx = self._setup(holder, rng)
        from pilosa_tpu.pql import parse_string

        be = TPUBackend(holder)
        be.MAX_PAIR_HOST_UPDATE_SHARDS = 0  # force the slab gate shut
        calls = [parse_string("Intersect(Row(f=1), Row(g=9))").calls[0]]
        shards = [0, 1]
        first = be.count_batch("i", calls, shards)
        s0, u0 = self._pair_counters()
        g_cols = set(Executor(holder).backend.bitmap_call_shard(
            "i", parse_string("Row(g=9)").calls[0], 0).columns().tolist())
        f_cols = set(Executor(holder).backend.bitmap_call_shard(
            "i", parse_string("Row(f=1)").calls[0], 0).columns().tolist())
        col = next(iter(g_cols - f_cols))
        idx.field("f").import_bits(
            np.array([1], dtype=np.uint64), np.array([col], dtype=np.uint64)
        )
        assert be.count_batch("i", calls, shards) == [first[0] + 1]
        s1, u1 = self._pair_counters()
        assert (s1, u1) == (s0 + 1, u0)

    def test_pair_delta_tier_applies_point_writes(self, holder, rng):
        """Point writes are absorbed by the delta tier (bit-op ring ->
        cf/pair adjustments), not slab recompute: the delta-op counter
        moves and results stay oracle-exact, including clears and writes
        to the 'other' field of the pair."""
        idx = self._setup(holder, rng)
        from pilosa_tpu.pql import parse_string
        from pilosa_tpu.utils.stats import global_stats

        be = TPUBackend(holder)
        queries = [
            "Intersect(Row(f=1), Row(g=9))",
            "Union(Row(f=2), Row(g=9))",
            "Xor(Row(f=3), Row(g=9))",
        ]
        calls = [parse_string(q).calls[0] for q in queries]
        shards = [0, 1]
        be.count_batch("i", calls, shards)
        cpu = Executor(holder)

        def dops():
            return global_stats._counters[("pair_stats_delta_ops_total", ())]

        d0 = dops()
        n_ops = 0
        for k in range(6):
            fname = ("f", "g")[k % 2]
            row = (1 + k % 3) if fname == "f" else 9
            col = 777_000 + k
            idx.field(fname).set_bit(row, col)
            n_ops += 1
            if k == 3:  # a clear in the middle of the stream
                idx.field(fname).clear_bit(row, col)
                n_ops += 1
            got = be.count_batch("i", calls, shards)
            want = [cpu.execute("i", f"Count({q})")[0] for q in queries]
            assert got == want, (k, got, want)
        assert dops() == d0 + n_ops

    def test_topn_incremental_host_update(self, holder, rng):
        """TopN's rank vector absorbs write epochs via the per-shard
        row-count table — no re-dispatch for a small epoch, results stay
        oracle-exact (including Rows(), which serves from it)."""
        idx = self._setup(holder, rng)
        from pilosa_tpu.utils.stats import global_stats

        be = TPUBackend(holder)
        ex_cpu = Executor(holder)
        ex_tpu = Executor(holder, backend=be)
        q = "TopN(f, n=0)"
        assert ex_tpu.execute("i", q) == ex_cpu.execute("i", q)

        def upds():
            return global_stats._counters[("topn_incremental_updates_total", ())]

        u0 = upds()
        wcol = 5
        for epoch in range(3):
            wcol += 131071
            idx.field("f").set_bit(int(rng.integers(1, 4)), wcol % (2 * SHARD_WIDTH))
            assert ex_tpu.execute("i", q) == ex_cpu.execute("i", q)
            assert ex_tpu.execute("i", "Rows(f)") == ex_cpu.execute("i", "Rows(f)")
            assert upds() == u0 + epoch + 1

    def test_pair_pershard_size_gate(self, holder, rng):
        """Over the per-shard-table byte gate the sweep returns summed
        totals (no resident table) and write epochs re-sweep — correct,
        just without the incremental path."""
        idx = self._setup(holder, rng)
        from pilosa_tpu.pql import parse_string

        be = TPUBackend(holder)
        be.MAX_PAIR_PERSHARD_BYTES = 0
        calls = [parse_string("Intersect(Row(f=1), Row(g=9))").calls[0]]
        shards = [0, 1]
        first = be.count_batch("i", calls, shards)
        assert be._pair_cache[("i", "f", "g")].pershard is None
        s0, u0 = self._pair_counters()
        idx.field("f").set_bit(1, 3)
        want = Executor(holder).execute("i", "Count(Intersect(Row(f=1), Row(g=9)))")
        assert be.count_batch("i", calls, shards) == want
        assert self._pair_counters() == (s0 + 1, u0)
        assert first is not None

    def test_pair_cache_concurrent_readers_and_writers(self, holder, rng):
        """The freshness protocol under real thread interleaving: batch
        readers race bit writers; every observed count must correspond
        to SOME prefix of the writes (never above the final state, never
        below the initial — staleness is allowed, corruption is not; the
        store rule is last-writer-wins, so per-reader monotonicity is
        NOT promised), and after writers finish the caches converge to
        oracle-exact."""
        import threading

        idx = self._setup(holder, rng)
        from pilosa_tpu.pql import parse_string

        be = TPUBackend(holder)
        calls = [parse_string("Intersect(Row(f=1), Row(g=9))").calls[0]]
        shards = [0, 1]
        initial = be.count_batch("i", calls, shards)[0]
        cpu = Executor(holder)
        g_cols = set(cpu.backend.bitmap_call_shard(
            "i", parse_string("Row(g=9)").calls[0], 0).columns().tolist())
        f_cols = set(cpu.backend.bitmap_call_shard(
            "i", parse_string("Row(f=1)").calls[0], 0).columns().tolist())
        # 24 columns in g=9 but not f=1: each Set(f=1) adds exactly +1.
        to_set = sorted(g_cols - f_cols)[:24]
        errors: list = []
        stop = threading.Event()

        def writer():
            for col in to_set:
                idx.field("f").set_bit(1, col)
            stop.set()

        def reader():
            while not stop.is_set():
                got = be.count_batch("i", calls, shards)[0]
                if not (initial <= got <= initial + len(to_set)):
                    errors.append(("count out of range", initial, got))
                    return

        threads = [threading.Thread(target=reader) for _ in range(3)]
        wt = threading.Thread(target=writer)
        for t in threads:
            t.start()
        wt.start()
        wt.join()
        for t in threads:
            t.join(timeout=30)
        assert not errors, errors[:3]
        want = cpu.execute("i", "Count(Intersect(Row(f=1), Row(g=9)))")
        assert be.count_batch("i", calls, shards) == want
        assert want[0] == initial + len(to_set)

    def test_topn_refresh_on_out_of_scope_write(self, holder, rng):
        """Writes to shards OUTSIDE the queried set bump the view
        generation but must not degrade TopN to a dispatch per query —
        the entry re-keys with unchanged counts."""
        idx = self._setup(holder, rng)
        from pilosa_tpu.utils.stats import global_stats

        be = TPUBackend(holder)
        want = be.topn_field("i", "f", [0], 0)
        disp0 = global_stats._counters[("topn_cache_hits_total", ())]
        for k in range(3):
            # Shard 5 is far outside the queried set [0].
            idx.field("f").set_bit(1, 5 * SHARD_WIDTH + k)
            assert be.topn_field("i", "f", [0], 0) == want
        # Second query after each write serves as a plain generation hit.
        assert be.topn_field("i", "f", [0], 0) == want
        assert global_stats._counters[("topn_cache_hits_total", ())] == disp0 + 1


class TestGroupByFromTables:
    """Unfiltered 1-/2-field GroupBy serves from the incrementally-
    maintained TopN/pair tables: exact under point-write churn with no
    device sweeps after the first."""

    def test_groupby_2field_under_churn(self, holder, rng):
        idx = holder.create_index("i")
        for fname, nrows in (("a", 3), ("b", 2)):
            idx.create_field(fname)
            for row in range(1, nrows + 1):
                cols = np.unique(
                    rng.integers(0, 2 * SHARD_WIDTH, 1500, dtype=np.uint64)
                )
                idx.field(fname).import_bits(
                    np.full(cols.size, row, dtype=np.uint64), cols
                )
        from pilosa_tpu.utils.stats import global_stats

        ex_cpu = Executor(holder)
        be = TPUBackend(holder)
        ex_tpu = Executor(holder, backend=be)
        for q in ("GroupBy(Rows(a))", "GroupBy(Rows(a), Rows(b))"):
            assert ex_tpu.execute("i", q) == ex_cpu.execute("i", q)
        s0 = global_stats._counters[("pair_stats_sweeps_total", ())]
        for k in range(4):
            idx.field("a").set_bit(1 + k % 3, 333_000 + k)
            for q in ("GroupBy(Rows(a))", "GroupBy(Rows(a), Rows(b))",
                      "GroupBy(Rows(a), Rows(b), limit=2)"):
                assert ex_tpu.execute("i", q) == ex_cpu.execute("i", q), (k, q)
        assert global_stats._counters[("pair_stats_sweeps_total", ())] == s0


class TestGroupByDevice:
    """Device GroupBy = whole-query group-count tensor (VERDICT r2 #4);
    every shape must match the host iterator call-for-call."""

    def _setup(self, holder, rng):
        idx = holder.create_index("i")
        for fname, nrows in (("a", 3), ("b", 2), ("c", 2), ("d", 2)):
            idx.create_field(fname)
            for row in range(1, nrows + 1):
                cols = np.unique(
                    rng.integers(0, 2 * SHARD_WIDTH, 1500, dtype=np.uint64)
                )
                idx.field(fname).import_bits(
                    np.full(cols.size, row, dtype=np.uint64), cols
                )
        return idx

    QUERIES = [
        "GroupBy(Rows(a))",
        "GroupBy(Rows(a), Rows(b))",
        "GroupBy(Rows(a), Rows(b), Rows(c))",
        "GroupBy(Rows(a), Rows(b), Rows(c), filter=Row(a=1))",
        "GroupBy(Rows(a), Rows(b), filter=Row(c=1))",
        "GroupBy(Rows(a), filter=Row(b=2))",
        "GroupBy(Rows(a), Rows(b), limit=3)",
        "GroupBy(Rows(a), Rows(b), limit=2, offset=1)",
        "GroupBy(Rows(a, limit=2), Rows(b))",
        "GroupBy(Rows(a, previous=1), Rows(b))",
        # 4-field shapes: the N-field odometer kernel (VERDICT r3 #4
        # removed the 3-field cliff).
        "GroupBy(Rows(a), Rows(b), Rows(c), Rows(d))",
        "GroupBy(Rows(a), Rows(b), Rows(c), Rows(d), filter=Row(a=2))",
        "GroupBy(Rows(a), Rows(b), Rows(c), Rows(d), limit=5, offset=2)",
        "GroupBy(Rows(a), Rows(b), Rows(c, limit=1), Rows(d))",
    ]

    def test_differential_vs_host(self, holder, rng):
        self._setup(holder, rng)
        host = Executor(holder)
        dev = Executor(holder, backend=TPUBackend(holder))
        for q in self.QUERIES:
            want = host.execute("i", q)
            got = dev.execute("i", q)
            assert got == want, q

    def test_device_path_taken(self, holder, rng):
        """The fast path actually runs (returns non-None) for the plain
        2-child case."""
        self._setup(holder, rng)
        be = TPUBackend(holder)
        from pilosa_tpu.pql import parse_string

        c = parse_string("GroupBy(Rows(a), Rows(b))").calls[0]
        out = be.group_by("i", c, None, [None, None], [0, 1])
        assert out is not None and len(out) > 0

    def test_write_invalidation(self, holder, rng):
        """GroupBy counts must reflect writes (stack cache freshness)."""
        idx = self._setup(holder, rng)
        dev = Executor(holder, backend=TPUBackend(holder))
        before = dev.execute("i", "GroupBy(Rows(a), Rows(b))")[0]
        # New column in both a=1 and b=1: that group's count +1.
        col = 3 * SHARD_WIDTH - 5
        idx.field("a").set_bit(1, col)
        idx.field("b").set_bit(1, col)
        after = dev.execute("i", "GroupBy(Rows(a), Rows(b))")[0]
        want = Executor(holder).execute("i", "GroupBy(Rows(a), Rows(b))")[0]
        assert after == want
        assert after != before


class TestAggCache:
    """Unfiltered Sum/Min/Max results cache against the BSI view's write
    epoch and must invalidate on writes."""

    def test_hit_and_invalidation(self, holder, rng):
        idx = holder.create_index("i")
        idx.create_field("v", options_for_int(-100, 100))
        cols = np.unique(rng.integers(0, SHARD_WIDTH, 400, dtype=np.uint64))
        vals = rng.integers(-100, 101, cols.size)
        idx.field("v").import_value(cols, vals)
        be = TPUBackend(holder)
        first = be.bsi_sum("i", "v", [0])
        assert first is not None
        assert be.bsi_sum("i", "v", [0]) == first  # cache hit
        assert len(be._agg_cache) == 1
        mn, mx = be.bsi_min("i", "v", [0]), be.bsi_max("i", "v", [0])
        # Oracle agreement.
        want_sum = Executor(holder).execute("i", "Sum(field=v)")[0]
        assert first == (want_sum.val, want_sum.count)
        # A new value invalidates: sum/min/max all change deterministically.
        free_col = int(cols.max()) + 1
        idx.field("v").set_value(free_col, -100)
        after = be.bsi_sum("i", "v", [0])
        assert after == (first[0] - 100, first[1] + 1)
        assert be.bsi_min("i", "v", [0])[0] == -100
        want_max = Executor(holder).execute("i", "Max(field=v)")[0]
        assert be.bsi_max("i", "v", [0]) == (want_max.val, want_max.count)
        assert (mn, mx) != (None, None)

    def test_sum_value_delta_tier(self, holder, rng):
        """Point value writes (set/clear/overwrite, any sign) update the
        cached Sum as exact host deltas — no plane re-sweep; bulk
        import_value is not delta-coverable and re-dispatches."""
        from pilosa_tpu.utils.stats import global_stats

        idx = holder.create_index("i")
        idx.create_field("v", options_for_int(-100, 100))
        cols = np.unique(rng.integers(0, 2 * SHARD_WIDTH, 500, dtype=np.uint64))
        idx.field("v").import_value(cols, rng.integers(-100, 101, cols.size))
        be = TPUBackend(holder)
        ex_cpu = Executor(holder)
        shards = [0, 1]
        assert be.bsi_sum("i", "v", shards) is not None

        def upds():
            return global_stats._counters[("sum_incremental_updates_total", ())]

        u0 = upds()
        taken = set(cols.tolist())
        free = next(c for c in range(SHARD_WIDTH) if c not in taken)
        free1 = next(
            c for c in range(SHARD_WIDTH, 2 * SHARD_WIDTH)
            if c not in taken and c != free
        )
        ops = [
            ("set", free, 37),        # new column
            ("set", free, -14),       # overwrite, sign flip
            ("set", int(cols[0]), 9),  # overwrite existing
            ("clear", free, None),    # removal
            ("set", free1, 50),       # the other queried shard
        ]
        for k, (verb, col, val) in enumerate(ops):
            f = idx.field("v")
            if verb == "set":
                f.set_value(col, val)
            else:
                frag = f.view(f"bsig_v").fragment(col // SHARD_WIDTH)
                frag.clear_value(col, f.bsi_group().bit_depth)
            got = be.bsi_sum("i", "v", shards)
            want = ex_cpu.execute("i", "Sum(field=v)")[0]
            assert got == (want.val, want.count), (k, got, want)
            assert upds() == u0 + k + 1
        # Bulk path: not coverable, must re-dispatch yet stay exact.
        more = np.array([free + 5, free + 6], dtype=np.uint64)
        idx.field("v").import_value(more, np.array([1, 2]))
        got = be.bsi_sum("i", "v", shards)
        want = ex_cpu.execute("i", "Sum(field=v)")[0]
        assert got == (want.val, want.count)
        assert upds() == u0 + len(ops)


class TestRowPaging:
    """HBM row paging (VERDICT r2 #8): a field too tall for the byte
    budget still answers Row/Count/TopN on device via on-demand row
    fetches and streaming page sweeps — not the CPU oracle."""

    def _tall_field(self, holder, rng, n_rows=2000):
        idx = holder.create_index("i")
        idx.create_field("tall")
        rows = np.arange(n_rows, dtype=np.uint64).repeat(3)
        cols = rng.integers(0, SHARD_WIDTH, rows.size, dtype=np.uint64)
        idx.field("tall").import_bits(rows, cols)
        return idx

    def test_row_query_pages_single_row(self, holder, rng):
        idx = self._tall_field(holder, rng)
        be = TPUBackend(holder, max_bytes=16 << 20)
        # The full stack (2000 rows x 128 KiB) exceeds the 16 MiB budget.
        assert be.blocks.get("i", idx.field("tall"), (0,))[0] is None
        from pilosa_tpu.pql import parse_string

        for rid in (0, 1500, 1999, 5000):
            c = parse_string(f"Count(Row(tall={rid}))").calls[0].children[0]
            want = Executor(holder).backend.count_shard("i", c, 0)
            assert be.count_shards("i", c, [0]) == want, rid
        # Combinations of paged rows lower too.
        c = parse_string("Union(Row(tall=3), Row(tall=1500))").calls[0]
        want = Executor(holder).backend.count_shard("i", c, 0)
        assert be.count_shards("i", c, [0]) == want

    def test_topn_paged_matches_oracle(self, holder, rng):
        self._tall_field(holder, rng)
        from pilosa_tpu.utils.stats import global_stats

        be = TPUBackend(holder, max_bytes=16 << 20)
        host = Executor(holder)
        dev = Executor(holder, backend=be)
        def uploads() -> float:
            for line in global_stats.prometheus_text().splitlines():
                if line.startswith("pilosa_hbm_page_uploads_total"):
                    return float(line.split()[1])
            return 0.0

        before = uploads()
        want = [result_to_json(r) for r in host.execute("i", "TopN(tall, n=10)")]
        got = [result_to_json(r) for r in dev.execute("i", "TopN(tall, n=10)")]
        assert got == want
        # Page traffic from THIS query is observable on /metrics.
        assert uploads() > before
        assert "hbm_page_bytes_total" in global_stats.prometheus_text()


class TestPreheat:
    def test_preheat_makes_stacks_resident_and_queries_hit(self, holder, rng):
        idx = holder.create_index("i")
        idx.create_field("f")
        idx.create_field("v", options_for_int(-100, 100))
        cols = np.unique(rng.integers(0, 2 * SHARD_WIDTH, 2000, dtype=np.uint64))
        idx.field("f").import_bits(np.full(cols.size, 1, dtype=np.uint64), cols)
        vcols = np.unique(rng.integers(0, SHARD_WIDTH, 300, dtype=np.uint64))
        idx.field("v").import_value(vcols, rng.integers(-100, 101, vcols.size))
        be = TPUBackend(holder)
        n = be.preheat()
        assert n >= 2  # f standard + v bsig (at full plane height)
        resident_before = be.blocks.resident_bytes()
        # Queries must reuse the preheated stacks (no repack/replace).
        from pilosa_tpu.pql import parse_string

        # Index-union shard lists — what the executor passes; v only has
        # data in shard 0 but must still be keyed by the union, or the
        # first query would repack and REPLACE the preheated stack.
        c = parse_string("Row(f=1)").calls[0]
        assert be.count_shards("i", c, [0, 1]) == cols.size
        assert be.bsi_sum("i", "v", [0, 1]) is not None
        assert be.blocks.resident_bytes() == resident_before


class TestCountBatcher:
    """exec/batcher.py: cross-request coalescing (VERDICT r2 #2)."""

    def _setup(self, holder, rng):
        idx = holder.create_index("i")
        idx.create_field("f")
        idx.create_field("g")
        for row in [1, 2]:
            cols = np.unique(rng.integers(0, 2 * SHARD_WIDTH, 2000, dtype=np.uint64))
            idx.field("f").import_bits(np.full(cols.size, row, dtype=np.uint64), cols)
        cols = np.unique(rng.integers(0, 2 * SHARD_WIDTH, 2000, dtype=np.uint64))
        idx.field("g").import_bits(np.full(cols.size, 9, dtype=np.uint64), cols)

    def test_concurrent_submissions_coalesce(self, holder, rng):
        import threading

        from pilosa_tpu.exec.batcher import CountBatcher
        from pilosa_tpu.pql import parse_string

        self._setup(holder, rng)
        be = TPUBackend(holder)
        batcher = CountBatcher(be, window=0.15)
        shards = [0, 1]
        queries = [f"Intersect(Row(f={r}), Row(g=9))" for r in (1, 2)] + ["Row(f=1)"]
        want = [
            be.count_shards("i", parse_string(q).calls[0], shards) for q in queries
        ]
        got = [None] * len(queries)
        errs = []

        def worker(k):
            try:
                got[k] = batcher.count(
                    "i", [parse_string(queries[k]).calls[0]], shards
                )[0]
            except BaseException as e:  # pragma: no cover
                errs.append(e)

        threads = [
            __import__("threading").Thread(target=worker, args=(k,))
            for k in range(len(queries))
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs
        assert got == want

    def test_error_isolation(self, holder, rng):
        """A bad query in the window errors only its own submitter."""
        import threading

        from pilosa_tpu.exec.batcher import CountBatcher
        from pilosa_tpu.exec.cpu import QueryError
        from pilosa_tpu.pql import parse_string

        self._setup(holder, rng)
        be = TPUBackend(holder)
        batcher = CountBatcher(be, window=0.15)
        shards = [0, 1]
        good_call = parse_string("Row(f=1)").calls[0]
        bad_call = parse_string("Row(nope=1)").calls[0]
        want = be.count_shards("i", good_call, shards)
        results = {}

        def run(name, call):
            try:
                results[name] = batcher.count("i", [call], shards)[0]
            except QueryError as e:
                results[name] = e

        t1 = threading.Thread(target=run, args=("good", good_call))
        t2 = threading.Thread(target=run, args=("bad", bad_call))
        t1.start(), t2.start()
        t1.join(), t2.join()
        assert results["good"] == want
        assert isinstance(results["bad"], QueryError)

    def test_executor_rides_batcher(self, holder, rng):
        """Executor with a batcher returns oracle-identical results, even
        for a single-Count query."""
        from pilosa_tpu.exec.batcher import CountBatcher

        self._setup(holder, rng)
        be = TPUBackend(holder)
        ex = Executor(holder, backend=be)
        ex.batcher = CountBatcher(be, window=0.0)
        for q in (
            "Count(Intersect(Row(f=1), Row(g=9)))",
            "Count(Row(f=2))Count(Union(Row(f=1), Row(g=9)))",
        ):
            assert ex.execute("i", q) == Executor(holder).execute("i", q)


class TestTriStatsKernel:
    def test_tri_matches_premasked_pairs(self, rng):
        """tri_stats[k] must equal pair_stats(F & H_k [& filt], G)."""
        from pilosa_tpu.ops.kernels import pair_stats, tri_stats

        S, RF, RG, RH, W = 3, 8, 8, 4, 512
        f = rng.integers(0, 1 << 32, (S, RF, W), dtype=np.uint32)
        g = rng.integers(0, 1 << 32, (S, RG, W), dtype=np.uint32)
        h = rng.integers(0, 1 << 32, (S, RH, W), dtype=np.uint32)
        filt = rng.integers(0, 1 << 32, (S, W), dtype=np.uint32)
        tri = np.asarray(tri_stats(f, g, h, interpret=True))
        tri_f = np.asarray(tri_stats(f, g, h, filt, interpret=True))
        for k in range(RH):
            m = h[:, k, :]
            want = np.asarray(pair_stats((f & m[:, None, :]), g, interpret=True)[0])
            np.testing.assert_array_equal(tri[k], want)
            want_f = np.asarray(
                pair_stats((f & (m & filt)[:, None, :]), g, interpret=True)[0]
            )
            np.testing.assert_array_equal(tri_f[k], want_f)


class TestIncrementalStackUpdate:
    """VERDICT r3 #1: a write touching one shard must refresh the
    resident stack by splicing that shard's slab, not repacking the
    whole stack."""

    def _build(self, holder, rng, n_shards=4):
        idx = holder.create_index("i")
        f = idx.create_field("f")
        for shard in range(n_shards):
            base = shard * SHARD_WIDTH
            cols = np.unique(
                rng.integers(0, SHARD_WIDTH, 3000, dtype=np.uint64)
            ) + base
            f.import_bits(np.full(cols.size, 1, dtype=np.uint64), cols)
        return idx

    def test_single_shard_write_is_incremental_and_correct(self, holder, rng):
        from pilosa_tpu.pql import parse_string
        from pilosa_tpu.utils.stats import global_stats

        idx = self._build(holder, rng, n_shards=16)
        be = TPUBackend(holder)
        shards = list(range(16))
        call = parse_string("Count(Row(f=1))").calls[0].children[0]
        before_total = be.count_shards("i", call, shards)
        old_arr = be.blocks._entries[("i", "f", "standard")][1]

        def updates():
            return global_stats._counters.get(
                ("stack_incremental_updates_total", ()), 0
            )

        n0 = updates()
        # One write in shard 3 (a fresh column: count must grow by 1).
        idx.field("f").set_bit(1, 3 * SHARD_WIDTH + 777_777)
        after_total = be.count_shards("i", call, shards)
        assert after_total == before_total + 1
        assert updates() == n0 + 1
        new_arr = be.blocks._entries[("i", "f", "standard")][1]
        # New array object: identity-keyed caches see a fresh epoch.
        assert new_arr is not old_arr
        # And a repeat query is a pure fingerprint hit (no new update).
        assert be.count_shards("i", call, shards) == after_total
        assert updates() == n0 + 1

    def test_many_dirty_shards_full_rebuild(self, holder, rng):
        from pilosa_tpu.pql import parse_string
        from pilosa_tpu.utils.stats import global_stats

        idx = self._build(holder, rng, n_shards=4)
        be = TPUBackend(holder)
        shards = list(range(4))
        call = parse_string("Count(Row(f=1))").calls[0].children[0]
        base = be.count_shards("i", call, shards)

        def updates():
            return global_stats._counters.get(
                ("stack_incremental_updates_total", ()), 0
            )

        n0 = updates()
        # Dirty 3 of 4 shards: over the 1/8 cutoff -> full rebuild.
        for s in range(3):
            idx.field("f").set_bit(1, s * SHARD_WIDTH + 999_999)
        assert be.count_shards("i", call, shards) == base + 3
        assert updates() == n0

    def test_row_growth_forces_rebuild(self, holder, rng):
        """A write that adds a new max row changes the stack height —
        never incrementally spliceable."""
        from pilosa_tpu.pql import parse_string

        idx = self._build(holder, rng, n_shards=16)
        be = TPUBackend(holder)
        shards = list(range(16))
        call = parse_string("Count(Row(f=63))").calls[0].children[0]
        assert be.count_shards("i", call, shards) == 0
        idx.field("f").set_bit(63, 5 * SHARD_WIDTH + 42)
        assert be.count_shards("i", call, shards) == 1


class TestRowsDevice:
    """Rows() served from the counts vector (VERDICT r3 #5) must match
    the host fragment walk in every shape."""

    def _setup(self, holder, rng):
        idx = holder.create_index("i")
        f = idx.create_field("f")
        for row in (0, 2, 5):
            cols = np.unique(rng.integers(0, 3 * SHARD_WIDTH, 2500, dtype=np.uint64))
            f.import_bits(np.full(cols.size, row, dtype=np.uint64), cols)
        return idx

    QUERIES = [
        "Rows(f)",
        "Rows(f, previous=1)",
        "Rows(f, previous=2)",
        "Rows(f, limit=2)",
        "Rows(f, previous=0, limit=1)",
        f"Rows(f, column={SHARD_WIDTH + 17})",
    ]

    def test_differential_vs_host(self, holder, rng):
        self._setup(holder, rng)
        host = Executor(holder)
        dev = Executor(holder, backend=TPUBackend(holder))
        for q in self.QUERIES:
            assert dev.execute("i", q) == host.execute("i", q), q

    def test_device_path_taken_and_row_clear(self, holder, rng):
        idx = self._setup(holder, rng)
        be = TPUBackend(holder)
        shards = [0, 1, 2]
        assert be.rows_field("i", "f", shards) == [0, 2, 5]
        assert be.rows_field("i", "f", shards, start=1) == [2, 5]
        # Clearing every bit of a row removes it (empty containers drop).
        Executor(holder).execute("i", "ClearRow(f=2)")
        assert be.rows_field("i", "f", shards) == [0, 5]
        assert Executor(holder, backend=be).execute("i", "Rows(f)") == Executor(
            holder
        ).execute("i", "Rows(f)")


class TestVersionCaptureRace:
    """ADVICE r4 (high): writers mutate storage BEFORE bumping version,
    both inside fr.lock (fragment.py set_bit). A version capture that
    does not serialize with that critical section can record a
    pre-write version for post-write content, and the non-idempotent
    delta replay then double-applies the op. These tests pin the fix:
    every capture/confirm read of (uid, version) holds fr.lock."""

    def _mid_write(self, fr, row, col):
        """Start a writer parked inside its critical section: storage
        mutated, version NOT yet bumped. Returns (thread, release)."""
        import threading

        from pilosa_tpu.core.fragment import pos

        entered = threading.Event()
        release = threading.Event()

        def writer():
            with fr.lock:
                fr.storage.add(pos(row, col))  # content lands first...
                entered.set()
                release.wait(5)
                fr.version += 1  # ...version bumps before unlock

        t = threading.Thread(target=writer, daemon=True)
        t.start()
        assert entered.wait(5)
        return t, release

    def test_pack_confirmed_blocks_on_mid_write(self):
        import threading

        from pilosa_tpu.exec.tpu import _pack_confirmed

        fr = Fragment(None, "i", "f", "standard", 0)
        fr.set_bit(0, 1)
        t, release = self._mid_write(fr, 1, 5)
        done = {}

        def packer():
            done["res"] = _pack_confirmed(fr, 2)

        p = threading.Thread(target=packer, daemon=True)
        p.start()
        p.join(0.3)
        # Must be parked on fr.lock — capturing now would pair the
        # pre-write version with who-knows-which content.
        assert "res" not in done
        release.set()
        t.join(5)
        p.join(5)
        slab, v = done["res"]
        # The recorded version describes exactly the returned content:
        # the mid-flight write is in BOTH the slab and the version.
        assert v == (fr.uid, fr.version)
        assert slab[1][0] & (1 << 5)

    def test_live_versions_serialize_with_writer(self, holder):
        import threading

        idx = holder.create_index("i")
        f = idx.create_field("f")
        f.set_bit(0, 1)
        be = TPUBackend(holder)
        fr = f.view("standard").fragment(0)
        v_before = fr.version
        t, release = self._mid_write(fr, 1, 5)
        got = {}

        def reader():
            got["v"] = be._live_versions(f, (0,))

        r = threading.Thread(target=reader, daemon=True)
        r.start()
        r.join(0.3)
        assert "v" not in got  # parked on fr.lock, not reading mid-write
        release.set()
        t.join(5)
        r.join(5)
        assert got["v"][0] == (fr.uid, v_before + 1)


class TestGroupNMaintainedTensor:
    """VERDICT r4 #1b: unfiltered N>=3 GroupBy must absorb write churn
    through the maintained per-shard tensor (host delta/slab tiers), not
    re-dispatch the nary sweep every epoch — and stay exact vs the
    oracle through every tier."""

    def _build(self, holder, rng, n_shards=4):
        idx = holder.create_index("i")
        for fn, nrows in (("f", 4), ("g", 4), ("h", 3)):
            f = idx.create_field(fn)
            for s in range(n_shards):
                cols = np.unique(
                    rng.integers(0, SHARD_WIDTH, 2500, dtype=np.uint64)
                ) + s * SHARD_WIDTH
                f.import_bits(
                    rng.integers(0, nrows, cols.size, dtype=np.uint64), cols
                )
        return idx

    def _updates(self):
        from pilosa_tpu.utils.stats import global_stats

        return global_stats._counters.get(
            ("groupn_incremental_updates_total", ()), 0
        )

    Q = "GroupBy(Rows(f), Rows(g), Rows(h))"

    def test_host_slab_matches_pershard_kernel(self, rng):
        from pilosa_tpu.exec.tpu import _host_slab_groupn
        from pilosa_tpu.ops.kernels import nary_stats_pershard

        rf, rg, rh, w = 8, 8, 4, 512
        fs = rng.integers(0, 2**32, (2, rf, w), dtype=np.uint32)
        gs = rng.integers(0, 2**32, (2, rg, w), dtype=np.uint32)
        hs = rng.integers(0, 2**32, (2, rh, w), dtype=np.uint32)
        per = np.asarray(
            nary_stats_pershard(fs, gs, (hs,), interpret=True)
        )  # [K, S, rf, rg]
        for s in range(2):
            host = _host_slab_groupn([fs[s], gs[s], hs[s]], [rf, rg, rh])
            np.testing.assert_array_equal(
                host, per[:, s].reshape(-1).astype(np.int32)
            )

    def test_point_write_delta_tier(self, holder, rng):
        idx = self._build(holder, rng)
        be = TPUBackend(holder)
        dev = Executor(holder, backend=be)
        host = Executor(holder)
        assert dev.execute("i", self.Q) == host.execute("i", self.Q)
        n0 = self._updates()
        # Point writes on each field in turn: every epoch must resolve
        # through the incremental tier, exactly.
        for j, fn in enumerate(("f", "g", "h", "f")):
            idx.field(fn).set_bit(j % 3, (j % 4) * SHARD_WIDTH + 12345 + j)
            assert dev.execute("i", self.Q) == host.execute("i", self.Q), fn
        assert self._updates() == n0 + 4
        # Clears too (negative deltas).
        idx.field("f").clear_bit(0, 12345)
        assert dev.execute("i", self.Q) == host.execute("i", self.Q)
        assert self._updates() == n0 + 5

    def test_bulk_write_slab_tier(self, holder, rng):
        idx = self._build(holder, rng)
        be = TPUBackend(holder)
        dev = Executor(holder, backend=be)
        host = Executor(holder)
        dev.execute("i", self.Q)
        n0 = self._updates()
        # Bulk import into one shard: the op ring can't explain it ->
        # that shard's row re-derives from _pack_confirmed slabs.
        cols = np.unique(
            rng.integers(0, SHARD_WIDTH, 3000, dtype=np.uint64)
        ) + 2 * SHARD_WIDTH
        idx.field("g").import_bits(
            rng.integers(0, 4, cols.size, dtype=np.uint64), cols
        )
        assert dev.execute("i", self.Q) == host.execute("i", self.Q)
        assert self._updates() == n0 + 1

    def test_row_growth_redispatches(self, holder, rng):
        idx = self._build(holder, rng)
        be = TPUBackend(holder)
        dev = Executor(holder, backend=be)
        host = Executor(holder)
        dev.execute("i", self.Q)
        # New max row on h changes the tensor K axis: must re-dispatch
        # (stack heights are padded to 8, so grow past the pad).
        idx.field("h").set_bit(9, SHARD_WIDTH + 7)
        assert dev.execute("i", self.Q) == host.execute("i", self.Q)

    def test_mixed_churn_stays_exact(self, holder, rng):
        idx = self._build(holder, rng)
        be = TPUBackend(holder)
        dev = Executor(holder, backend=be)
        host = Executor(holder)
        dev.execute("i", self.Q)
        w = np.random.default_rng(5)
        for step in range(12):
            fn = ("f", "g", "h")[step % 3]
            if step % 5 == 4:
                cols = np.unique(
                    w.integers(0, SHARD_WIDTH, 500, dtype=np.uint64)
                ) + int(w.integers(0, 4)) * SHARD_WIDTH
                idx.field(fn).import_bits(
                    w.integers(0, 3, cols.size, dtype=np.uint64), cols
                )
            else:
                idx.field(fn).set_bit(
                    int(w.integers(0, 3)),
                    int(w.integers(0, 4 * SHARD_WIDTH)),
                )
            assert dev.execute("i", self.Q) == host.execute("i", self.Q), step

    def test_four_fields(self, holder, rng):
        idx = self._build(holder, rng)
        f = idx.create_field("e")
        for s in range(4):
            cols = np.unique(
                rng.integers(0, SHARD_WIDTH, 1500, dtype=np.uint64)
            ) + s * SHARD_WIDTH
            f.import_bits(np.zeros(cols.size, dtype=np.uint64) + rng.integers(0, 2), cols)
        be = TPUBackend(holder)
        dev = Executor(holder, backend=be)
        host = Executor(holder)
        q = "GroupBy(Rows(f), Rows(g), Rows(h), Rows(e))"
        assert dev.execute("i", q) == host.execute("i", q)
        idx.field("e").set_bit(1, 3 * SHARD_WIDTH + 99)
        assert dev.execute("i", q) == host.execute("i", q)


class TestMinMaxChurnAbsorption:
    """VERDICT r4 #7: Min/Max must absorb point-value churn through the
    per-shard extremum table — O(1) for monotone writes, host re-derive
    (no device dispatch) only for shards whose incumbent was cleared —
    and stay exact vs the oracle through every tier."""

    def _build(self, holder, rng, shards=3):
        idx = holder.create_index("i")
        idx.create_field("v", options_for_int(-1000, 1000))
        cols = np.unique(
            rng.integers(0, shards * SHARD_WIDTH, 600, dtype=np.uint64)
        )
        idx.field("v").import_value(cols, rng.integers(-900, 901, cols.size))
        return idx, cols

    def _upd(self, name):
        from pilosa_tpu.utils.stats import global_stats

        return global_stats._counters.get((name, ()), 0)

    def _check(self, holder, be, shards):
        ex = Executor(holder)
        for kind, q in (("min", "Min(field=v)"), ("max", "Max(field=v)")):
            want = ex.execute("i", q)[0]
            got = getattr(be, f"bsi_{kind}")("i", "v", shards)
            assert got == (want.val, want.count), (kind, got, want)

    def test_monotone_writes_are_o1(self, holder, rng):
        idx, cols = self._build(holder, rng)
        shards = [0, 1, 2]
        be = TPUBackend(holder)
        self._check(holder, be, shards)
        n0 = self._upd("minmax_incremental_updates_total")
        r0 = self._upd("minmax_shard_rederives_total")
        # A middling value: beats neither extremum -> pure O(1) update.
        free = int(cols.max()) + 10
        idx.field("v").set_value(free, 5)
        self._check(holder, be, shards)
        assert self._upd("minmax_incremental_updates_total") == n0 + 2
        assert self._upd("minmax_shard_rederives_total") == r0
        # New global min and max: still O(1) (better value replaces).
        idx.field("v").set_value(free + 1, -999)
        idx.field("v").set_value(free + 2, 999)
        self._check(holder, be, shards)
        assert self._upd("minmax_shard_rederives_total") == r0

    def test_cleared_incumbent_rederives_one_shard(self, holder, rng):
        idx, cols = self._build(holder, rng)
        shards = [0, 1, 2]
        be = TPUBackend(holder)
        # Plant a unique global minimum, warm the table.
        free = int(cols.max()) + 10
        idx.field("v").set_value(free, -999)
        self._check(holder, be, shards)
        r0 = self._upd("minmax_shard_rederives_total")
        # Overwrite the incumbent minimum with a middling value: its
        # shard's extremum is cleared -> exactly that shard re-derives
        # on the host.
        idx.field("v").set_value(free, 17)
        self._check(holder, be, shards)
        assert self._upd("minmax_shard_rederives_total") == r0 + 1
        # Max table for the same epoch should NOT have re-derived
        # (the old -999 and new 17 both lose to the max incumbent)...
        # already covered by the +1 (min) instead of +2.

    @staticmethod
    def _clear(f, col):
        f._bsi_fragment(col // SHARD_WIDTH).clear_value(
            col, f.bsi_group().bit_depth
        )

    def test_clear_value_and_ties(self, holder, rng):
        idx = holder.create_index("i")
        idx.create_field("v", options_for_int(-100, 100))
        f = idx.field("v")
        # Tie: two columns in different shards share the minimum.
        f.set_value(5, -50)
        f.set_value(SHARD_WIDTH + 7, -50)
        f.set_value(20, 30)
        be = TPUBackend(holder)
        shards = [0, 1]
        self._check(holder, be, shards)
        assert be.bsi_min("i", "v", shards) == (-50, 2)
        # Clearing one of the tied pair: count drops, value holds.
        self._clear(f, 5)
        self._check(holder, be, shards)
        assert be.bsi_min("i", "v", shards) == (-50, 1)
        # Clearing the last: shard 1's incumbent clears -> re-derive.
        self._clear(f, SHARD_WIDTH + 7)
        self._check(holder, be, shards)
        assert be.bsi_min("i", "v", shards) == (30, 1)

    def test_bulk_import_rederives_not_redispatches(self, holder, rng):
        idx, cols = self._build(holder, rng)
        shards = [0, 1, 2]
        be = TPUBackend(holder)
        self._check(holder, be, shards)
        n0 = self._upd("minmax_incremental_updates_total")
        # Bulk import into shard 1: ring can't explain -> host re-derive
        # of that shard (still the incremental tier, no dispatch).
        newc = np.unique(
            rng.integers(SHARD_WIDTH, 2 * SHARD_WIDTH, 300, dtype=np.uint64)
        )
        idx.field("v").import_value(newc, rng.integers(-900, 901, newc.size))
        self._check(holder, be, shards)
        assert self._upd("minmax_incremental_updates_total") == n0 + 2

    def test_churn_stays_exact(self, holder, rng):
        idx, cols = self._build(holder, rng)
        shards = [0, 1, 2]
        be = TPUBackend(holder)
        self._check(holder, be, shards)
        w = np.random.default_rng(9)
        for step in range(25):
            col = int(w.integers(0, 3 * SHARD_WIDTH))
            if step % 7 == 6:
                self._clear(idx.field("v"), col)
            else:
                idx.field("v").set_value(col, int(w.integers(-1000, 1001)))
            self._check(holder, be, shards)


class TestWindowedRefresh:
    """Windowed device-refresh coalescing (ISSUE r19 tentpole 2):
    answers under churn stay byte-identical to unwindowed execution, a
    read landing mid-window forces the flush barrier, a window flush
    goes through the incremental splice (full rebuilds flat), and the
    background flusher actually refreshes stale stacks."""

    def _setup(self, holder, rng):
        idx = holder.create_index("i")
        idx.create_field("f")
        idx.create_field("g")
        for row in [1, 2, 3]:
            cols = np.unique(
                rng.integers(0, 2 * SHARD_WIDTH, 3000, dtype=np.uint64)
            )
            idx.field("f").import_bits(
                np.full(cols.size, row, dtype=np.uint64), cols
            )
        cols = np.unique(rng.integers(0, 2 * SHARD_WIDTH, 2000, dtype=np.uint64))
        idx.field("g").import_bits(np.full(cols.size, 9, dtype=np.uint64), cols)
        return idx

    @staticmethod
    def _counter(name):
        from pilosa_tpu.utils.stats import global_stats

        return global_stats.snapshot()["counters"].get(name, 0.0)

    def test_differential_with_mid_window_barrier(self, holder, rng):
        """Interleave background window flushes (refresh_stale) with
        mid-window stack reads across import churn: the windowed
        backend's device tensor must stay byte-identical to an
        UNWINDOWED backend's, query answers must match the CPU oracle,
        the mid-window reads must show up as forced barriers, the
        flushes as windowed refreshes — and stack_full_rebuilds_total
        must not move (the splice stays on the incremental path)."""
        from pilosa_tpu.pql import parse_string

        idx = self._setup(holder, rng)
        be_w = TPUBackend(holder)   # windowed
        be_u = TPUBackend(holder)   # unwindowed reference
        cpu = Executor(holder)
        queries = [
            "Intersect(Row(f=1), Row(g=9))",
            "Union(Row(f=2), Row(g=9))",
            "Row(f=3)",
        ]
        calls = [parse_string(q).calls[0] for q in queries]
        fobj = idx.field("f")
        shards = (0, 1)
        be_w.blocks.get("i", fobj, shards)  # resident
        rebuilds0 = self._counter("stack_full_rebuilds_total")
        forced0 = self._counter("stack_refresh_forced_total")
        windowed0 = self._counter("stack_windowed_refresh_total")
        # Windowing on, no flusher thread: the window boundary is
        # driven manually (refresh_stale) so the test is deterministic.
        be_w.blocks.refresh_window_ms = 60_000
        forced = windowed = 0
        for k in range(8):
            fobj.set_bit(1 + k % 3, 555_000 + 97 * k)
            if k % 2 == 0:
                # Mid-window read: the flush-on-demand barrier splices
                # inline rather than serving stale device bits.
                forced += 1
            else:
                # The window boundary: dirty shards flush as one
                # incremental round per stale stack.
                n = be_w.blocks.refresh_stale()
                assert n >= 1, "write must have staled the stack"
                windowed += n
            block_w, _ = be_w.blocks.get("i", fobj, shards)
            block_u, _ = be_u.blocks.get("i", fobj, shards)
            np.testing.assert_array_equal(
                np.asarray(block_w), np.asarray(block_u)
            )
            got = be_w.count_batch("i", calls, list(shards))
            want = [cpu.execute("i", f"Count({q})")[0] for q in queries]
            assert got == want, (k, got, want)
        assert self._counter("stack_refresh_forced_total") - forced0 == forced
        assert (
            self._counter("stack_windowed_refresh_total") - windowed0
            == windowed
        )
        assert self._counter("stack_full_rebuilds_total") == rebuilds0
        # A read right after a window flush is a plain hit: no barrier.
        assert be_w.blocks.refresh_stale() == 0
        f1 = self._counter("stack_refresh_forced_total")
        be_w.blocks.get("i", fobj, shards)
        assert self._counter("stack_refresh_forced_total") == f1

    def test_background_flusher_thread_refreshes(self, holder, rng):
        """start_refresher: the stack-refresh daemon picks up a write
        within a few windows with no read in between."""
        from pilosa_tpu.pql import parse_string

        idx = self._setup(holder, rng)
        be = TPUBackend(holder)
        calls = [parse_string("Row(f=1)").calls[0]]
        shards = [0, 1]
        first = be.count_batch("i", calls, shards)
        be.start_refresher(10)
        try:
            w0 = self._counter("stack_windowed_refresh_total")
            idx.field("f").set_bit(1, 777_777)
            deadline = time.monotonic() + 10
            while self._counter("stack_windowed_refresh_total") == w0:
                assert time.monotonic() < deadline, "flusher never refreshed"
                time.sleep(0.01)
            # The flushed stack serves the new bit as a plain hit.
            f0 = self._counter("stack_refresh_forced_total")
            assert be.count_batch("i", calls, shards) == [first[0] + 1]
            assert self._counter("stack_refresh_forced_total") == f0
        finally:
            be.stop_refresher()
        assert be.blocks.refresh_window_ms == 0
