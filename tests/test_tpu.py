"""TPU backend tests: block packing, kernels, TPUBackend differential vs
the CPU oracle, and mesh execution on the 8-device virtual CPU platform
(the multi-node-without-a-cluster strategy, SURVEY.md §4.3)."""

import numpy as np
import pytest

import jax

from pilosa_tpu.core import Fragment, Holder
from pilosa_tpu.core.field import options_for_int
from pilosa_tpu.exec import Executor
from pilosa_tpu.exec.result import result_to_json
from pilosa_tpu.exec.tpu import TPUBackend
from pilosa_tpu.ops.blocks import WORDS_PER_SHARD, BlockCache, pack_fragment, unpack_row
from pilosa_tpu.ops.kernels import and_popcount, popcount_rows
from pilosa_tpu.parallel import ShardMesh
from pilosa_tpu.shardwidth import SHARD_WIDTH


@pytest.fixture
def holder(tmp_path):
    h = Holder(str(tmp_path / "data")).open()
    yield h
    h.close()


class TestBlockPacking:
    def test_pack_roundtrip(self, rng):
        f = Fragment(None, "i", "f", "standard", 0)
        cols = np.unique(rng.integers(0, SHARD_WIDTH, 5000, dtype=np.uint64))
        f.bulk_import(np.full(cols.size, 3, dtype=np.uint64), cols)
        block = pack_fragment(f)
        assert block.shape[1] == WORDS_PER_SHARD
        assert block.shape[0] % 8 == 0
        np.testing.assert_array_equal(unpack_row(block[3]), cols)
        assert block[0].sum() == 0

    def test_pack_dense_container(self):
        f = Fragment(None, "i", "f", "standard", 0)
        cols = np.arange(0, 100_000, dtype=np.uint64)  # bitmap containers
        f.bulk_import(np.zeros(cols.size, dtype=np.uint64), cols)
        block = pack_fragment(f)
        np.testing.assert_array_equal(unpack_row(block[0]), cols)

    def test_cache_invalidation(self):
        f = Fragment(None, "i", "f", "standard", 0)
        f.set_bit(0, 1)
        cache = BlockCache()
        b1 = cache.block(f)
        assert np.asarray(b1)[0, 0] == 2  # bit 1
        f.set_bit(0, 2)  # version bump
        b2 = cache.block(f)
        assert np.asarray(b2)[0, 0] == 6  # bits 1,2
        assert cache.resident_bytes() > 0


class TestKernels:
    def test_and_popcount_matches_numpy(self, rng):
        a = rng.integers(0, 2**32, WORDS_PER_SHARD, dtype=np.uint32)
        b = rng.integers(0, 2**32, WORDS_PER_SHARD, dtype=np.uint32)
        got = int(and_popcount(a, b))
        want = int(np.bitwise_count(a & b).sum())
        assert got == want

    def test_popcount_rows(self, rng):
        block = rng.integers(0, 2**32, (8, WORDS_PER_SHARD), dtype=np.uint32)
        got = np.asarray(popcount_rows(block))
        want = np.bitwise_count(block).sum(axis=1)
        np.testing.assert_array_equal(got, want)


class TestTPUBackendDifferential:
    """The TPU backend must agree with the CPU oracle on every query."""

    def _setup(self, holder, rng):
        idx = holder.create_index("i")
        idx.create_field("f")
        idx.create_field("g")
        idx.create_field("v", options_for_int(-500, 500))
        ex_cpu = Executor(holder)
        # random data across 3 shards
        for row in [1, 2, 3]:
            cols = np.unique(rng.integers(0, 3 * SHARD_WIDTH, 2000, dtype=np.uint64))
            idx.field("f").import_bits(np.full(cols.size, row, dtype=np.uint64), cols)
            ef = idx.existence_field()
            ef.import_bits(np.zeros(cols.size, dtype=np.uint64), cols)
        cols = np.unique(rng.integers(0, 3 * SHARD_WIDTH, 1500, dtype=np.uint64))
        idx.field("g").import_bits(np.full(cols.size, 7, dtype=np.uint64), cols)
        ex_tpu = Executor(holder, backend=TPUBackend(holder))
        return ex_cpu, ex_tpu

    QUERIES = [
        "Row(f=1)",
        "Count(Row(f=2))",
        "Count(Intersect(Row(f=1), Row(g=7)))",
        "Count(Union(Row(f=1), Row(f=2), Row(f=3)))",
        "Count(Difference(Row(f=1), Row(g=7)))",
        "Count(Xor(Row(f=2), Row(g=7)))",
        "Union(Row(f=1), Row(g=7))",
        "Intersect(Row(f=1), Row(f=2))",
        "Not(Row(f=1))",
        "All()",
        "Count(Not(Union(Row(f=1), Row(f=2))))",
        "TopN(f, n=2)",
        "TopN(f)",
        "TopN(f, Row(g=7), n=3)",
    ]

    @pytest.mark.parametrize("q", QUERIES)
    def test_differential(self, holder, rng, q):
        ex_cpu, ex_tpu = self._setup(holder, rng)
        want = [result_to_json(r) for r in ex_cpu.execute("i", q)]
        got = [result_to_json(r) for r in ex_tpu.execute("i", q)]
        assert got == want, q

    def test_write_invalidates_device_blocks(self, holder, rng):
        ex_cpu, ex_tpu = self._setup(holder, rng)
        before = ex_tpu.execute("i", "Count(Row(f=1))")[0]
        ex_tpu.execute("i", f"Set({SHARD_WIDTH + 123456}, f=1)")
        after = ex_tpu.execute("i", "Count(Row(f=1))")[0]
        assert after == before + 1
        # still agrees with oracle
        assert ex_cpu.execute("i", "Count(Row(f=1))")[0] == after

    BSI_QUERIES = [
        "Sum(field=v)",
        "Sum(Row(f=1), field=v)",
        "Min(field=v)",
        "Max(field=v)",
        "Min(Row(f=1), field=v)",
        "Max(Row(f=1), field=v)",
        "Row(v > 0)",
        "Row(v >= 0)",
        "Row(v < 0)",
        "Row(v <= 0)",
        "Row(v == 42)",
        "Row(v != 42)",
        "Row(v != null)",
        "Row(v > -50)",
        "Row(v < -50)",
        "Row(v >= -10)",
        "Row(v <= -10)",
        "Row(v > 1000)",  # out of range
        "Row(v < 1000)",  # encompassing -> notNull
        "Row(v >< [-20, 30])",  # mixed between
        "Row(v >< [5, 60])",  # positive between
        "Row(v >< [-60, -5])",  # negative between
        "Row(v >< [-500, 500])",  # full range -> notNull
        "Count(Intersect(Row(f=1), Row(v > 0)))",
    ]

    def _setup_bsi(self, holder, rng):
        ex_cpu, ex_tpu = self._setup(holder, rng)
        cols = np.unique(rng.integers(0, 3 * SHARD_WIDTH, 800, dtype=np.uint64))
        vals = rng.integers(-500, 501, cols.size)
        holder.index("i").field("v").import_value(cols, vals)
        ex_cpu.execute("i", "Set(5, v=42) Set(6, v=-10)")
        return ex_cpu, ex_tpu

    @pytest.mark.parametrize("q", BSI_QUERIES)
    def test_bsi_runs_on_device(self, holder, rng, q):
        ex_cpu, ex_tpu = self._setup_bsi(holder, rng)
        want = [result_to_json(r) for r in ex_cpu.execute("i", q)]
        got = [result_to_json(r) for r in ex_tpu.execute("i", q)]
        assert got == want, q

    def test_shift_on_device(self, holder, rng):
        ex_cpu, ex_tpu = self._setup(holder, rng)
        for q in ["Shift(Row(f=1), n=1)", "Shift(Row(f=2), n=40)", "Count(Shift(Row(f=1), n=3))"]:
            want = [result_to_json(r) for r in ex_cpu.execute("i", q)]
            got = [result_to_json(r) for r in ex_tpu.execute("i", q)]
            assert got == want, q

    def test_time_range_on_device(self, holder, rng):
        from pilosa_tpu.core.field import options_for_time

        ex_cpu, ex_tpu = self._setup(holder, rng)
        idx = holder.index("i")
        idx.create_field("t", options_for_time("YMDH"))
        ex_cpu.execute("i", 'Set(3, t=9, 2019-08-03T10:00)')
        ex_cpu.execute("i", 'Set(1048579, t=9, 2019-08-05T12:00)')
        q = "Row(t=9, from='2019-08-01T00:00', to='2019-08-31T00:00')"
        want = [result_to_json(r) for r in ex_cpu.execute("i", q)]
        got = [result_to_json(r) for r in ex_tpu.execute("i", q)]
        assert got == want

    def test_hbm_budget_evicts(self, holder, rng):
        ex_cpu, _ = self._setup(holder, rng)
        # Budget fits roughly one stack: queries still correct, stacks evict.
        be = TPUBackend(holder, max_bytes=3 * 8 * WORDS_PER_SHARD * 4)
        ex_tpu = Executor(holder, backend=be)
        for q in ["Count(Row(f=1))", "Count(Row(g=7))", "Count(Row(f=2))"]:
            want = [result_to_json(r) for r in ex_cpu.execute("i", q)]
            got = [result_to_json(r) for r in ex_tpu.execute("i", q)]
            assert got == want, q
        assert be.blocks.evictions > 0
        assert be.blocks.resident_bytes() <= 3 * 8 * WORDS_PER_SHARD * 4


class TestMeshExecutor:
    """Real PQL through the 8-device mesh: holder-resident fragments are
    stacked, sharded over the mesh with NamedSharding(P('shards')), and
    queried through shard_map+psum — differentially checked vs the CPU
    oracle (the VERDICT r1 top-next item)."""

    def _setup(self, holder, rng):
        idx = holder.create_index("i")
        idx.create_field("f")
        idx.create_field("g")
        idx.create_field("v", options_for_int(-500, 500))
        n_shards = 11  # not a multiple of 8: exercises shard padding
        for row in [1, 2, 3]:
            cols = np.unique(rng.integers(0, n_shards * SHARD_WIDTH, 6000, dtype=np.uint64))
            idx.field("f").import_bits(np.full(cols.size, row, dtype=np.uint64), cols)
            idx.existence_field().import_bits(np.zeros(cols.size, dtype=np.uint64), cols)
        cols = np.unique(rng.integers(0, n_shards * SHARD_WIDTH, 4000, dtype=np.uint64))
        idx.field("g").import_bits(np.full(cols.size, 7, dtype=np.uint64), cols)
        cols = np.unique(rng.integers(0, n_shards * SHARD_WIDTH, 900, dtype=np.uint64))
        vals = rng.integers(-500, 501, cols.size)
        idx.field("v").import_value(cols, vals)
        ex_cpu = Executor(holder)
        ex_mesh = Executor(holder, backend=TPUBackend(holder, mesh=ShardMesh()))
        return ex_cpu, ex_mesh

    QUERIES = [
        "Count(Intersect(Row(f=1), Row(g=7)))",
        "Count(Union(Row(f=1), Row(f=2), Row(f=3)))",
        "Count(Not(Row(f=1)))",
        "Row(f=2)",
        "TopN(f, n=2)",
        "TopN(f, Row(g=7), n=3)",
        "Sum(field=v)",
        "Min(field=v)",
        "Max(field=v)",
        "Count(Row(v > 100))",
        "Count(Row(v >< [-100, 100]))",
    ]

    @pytest.mark.parametrize("q", QUERIES)
    def test_mesh_differential(self, holder, rng, q):
        ex_cpu, ex_mesh = self._setup(holder, rng)
        want = [result_to_json(r) for r in ex_cpu.execute("i", q)]
        got = [result_to_json(r) for r in ex_mesh.execute("i", q)]
        assert got == want, q

    def test_mesh_count_batch(self, holder, rng):
        _, ex_mesh = self._setup(holder, rng)
        from pilosa_tpu.pql import parse_string

        be = ex_mesh.backend
        calls = [
            parse_string(f"Intersect(Row(f={r}), Row(g=7))").calls[0] for r in [1, 2, 3]
        ]
        shards = list(range(11))
        batch = be.count_batch("i", calls, shards)
        singles = [be.count_shards("i", c, shards) for c in calls]
        assert batch == singles


class TestShardMesh:
    """Multi-chip execution on the virtual 8-device CPU mesh."""

    def test_mesh_has_8_devices(self):
        assert len(jax.devices()) == 8

    def test_count_intersect_psum(self, rng):
        mesh = ShardMesh()
        S = mesh.n
        a = rng.integers(0, 2**32, (S, WORDS_PER_SHARD), dtype=np.uint32)
        b = rng.integers(0, 2**32, (S, WORDS_PER_SHARD), dtype=np.uint32)
        da, db = mesh.put(a), mesh.put(b)
        got = mesh.count_intersect(da, db)
        want = int(np.bitwise_count(a & b).sum())
        assert got == want

    def test_topn_counts(self, rng):
        mesh = ShardMesh()
        S, R = mesh.n, 8
        blocks = rng.integers(0, 2**32, (S, R, WORDS_PER_SHARD // 16), dtype=np.uint32)
        got = mesh.topn_counts(mesh.put(blocks))
        want = np.bitwise_count(blocks).sum(axis=(0, 2))
        np.testing.assert_array_equal(got, want)

    def test_bsi_sum(self, rng):
        mesh = ShardMesh()
        S, D, W = mesh.n, 4, WORDS_PER_SHARD // 64
        planes = rng.integers(0, 2**32, (S, D, W), dtype=np.uint32)
        exists = np.full((S, W), 0xFFFFFFFF, dtype=np.uint32)
        sign = np.zeros((S, W), dtype=np.uint32)
        total, cnt = mesh.bsi_sum(mesh.put(planes), mesh.put(exists), mesh.put(sign))
        want = sum(int(np.bitwise_count(planes[:, i, :]).sum()) << i for i in range(D))
        assert total == want
        assert cnt == S * W * 32


class TestCountBatch:
    def test_count_batch_matches_singles(self, holder, rng):
        idx = holder.create_index("i")
        idx.create_field("f")
        idx.create_field("g")
        for row in [1, 2, 3]:
            cols = np.unique(rng.integers(0, 2 * SHARD_WIDTH, 3000, dtype=np.uint64))
            idx.field("f").import_bits(np.full(cols.size, row, dtype=np.uint64), cols)
        cols = np.unique(rng.integers(0, 2 * SHARD_WIDTH, 3000, dtype=np.uint64))
        idx.field("g").import_bits(np.full(cols.size, 9, dtype=np.uint64), cols)
        be = TPUBackend(holder)
        from pilosa_tpu.pql import parse_string

        calls = [
            parse_string(f"Intersect(Row(f={r}), Row(g=9))").calls[0] for r in [1, 2, 3, 7]
        ]
        shards = [0, 1]
        batch = be.count_batch("i", calls, shards)
        singles = [be.count_shards("i", c, shards) for c in calls]
        assert batch == singles
        assert batch[3] == 0  # nonexistent row counts zero
