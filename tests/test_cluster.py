"""Cluster layer tests: topology math, distributed queries, replication,
node-failure retry (reference cluster_internal_test.go + executor_test.go
cluster cases via test.MustRunCluster)."""

import pytest

from pilosa_tpu.cluster import (
    Cluster,
    InternalClient,
    JmpHasher,
    ModHasher,
    Node,
    Topology,
    URI,
)
from pilosa_tpu.shardwidth import SHARD_WIDTH

from tests.cluster_harness import TestCluster


# ---------------------------------------------------------------------------
# topology
# ---------------------------------------------------------------------------


def _nodes(n):
    return [Node(f"node{i}", URI(port=10101 + i)) for i in range(n)]


class TestURI:
    def test_parse_full(self):
        u = URI.parse("http://example.com:8080")
        assert (u.scheme, u.host, u.port) == ("http", "example.com", 8080)

    def test_parse_defaults(self):
        assert URI.parse("example.com").port == 10101
        assert URI.parse("example.com:81").scheme == "http"
        assert str(URI.parse("https://h:1")) == "https://h:1"

    def test_parse_invalid(self):
        with pytest.raises(ValueError):
            URI.parse("http://host:port:extra")


class TestJumpHash:
    def test_spread_and_stability(self):
        # Jump hash: adding a bucket moves only ~1/n of keys.
        h = JmpHasher()
        before = [h.hash(k, 4) for k in range(1000)]
        after = [h.hash(k, 5) for k in range(1000)]
        moved = sum(1 for a, b in zip(before, after) if a != b)
        assert 100 < moved < 350  # ~1/5 of keys
        # Every moved key moved to the NEW bucket (jump hash property).
        assert all(b == 4 for a, b in zip(before, after) if a != b)

    def test_range(self):
        h = JmpHasher()
        for k in range(100):
            assert 0 <= h.hash(k, 3) < 3


class TestTopology:
    def test_partition_deterministic(self):
        t = Topology(_nodes(3))
        assert t.partition("i", 0) == t.partition("i", 0)
        assert 0 <= t.partition("i", 12345) < 256
        # different index -> different partition for at least some shards
        assert any(t.partition("i", s) != t.partition("j", s) for s in range(32))

    def test_replica_ring(self):
        t = Topology(_nodes(4), replica_n=3)
        nodes = t.partition_nodes(7)
        assert len(nodes) == 3
        assert len({n.id for n in nodes}) == 3
        # consecutive on the ID-sorted ring
        ids = [n.id for n in t.nodes]
        i0 = ids.index(nodes[0].id)
        assert [n.id for n in nodes] == [ids[(i0 + k) % 4] for k in range(3)]

    def test_replica_clamped_to_cluster_size(self):
        t = Topology(_nodes(2), replica_n=5)
        assert len(t.partition_nodes(0)) == 2

    def test_mod_hasher_placement(self):
        t = Topology(_nodes(3), hasher=ModHasher())
        p = t.partition("i", 9)
        assert t.partition_nodes(p)[0].id == f"node{p % 3}"

    def test_owns_shard_covers_all_nodes(self):
        t = Topology(_nodes(3))
        owners = {t.primary_for_shard("i", s).id for s in range(64)}
        assert owners == {"node0", "node1", "node2"}  # jump hash spreads

    def test_add_remove_node(self):
        t = Topology(_nodes(2))
        t.add_node(Node("node9", URI(port=1)))
        assert [n.id for n in t.nodes] == ["node0", "node1", "node9"]
        assert t.remove_node("node9")
        assert not t.remove_node("node9")


# ---------------------------------------------------------------------------
# distributed execution
# ---------------------------------------------------------------------------

N_SHARDS = 6


def _populate(tc: TestCluster, index="i", field="f"):
    """Bits spread over N_SHARDS shards, writes routed through different
    nodes round-robin to exercise replication + forwarding."""
    tc.create_index(index)
    tc.create_field(index, field)
    expected_cols = []
    for s in range(N_SHARDS):
        col = s * SHARD_WIDTH + s + 1
        expected_cols.append(col)
        tc.query(s % len(tc), index, f"Set({col}, {field}=1)")
    # row 2: only even shards
    for s in range(0, N_SHARDS, 2):
        tc.query(0, index, f"Set({s * SHARD_WIDTH + 7}, {field}=2)")
    tc.await_shard_convergence(index)
    return expected_cols


class TestDistributedQueries:
    def test_count_and_row_from_every_node(self):
        with TestCluster(3) as tc:
            cols = _populate(tc)
            for i in range(3):
                out = tc.query(i, "i", "Count(Row(f=1))")
                assert out["results"][0] == N_SHARDS, f"node {i}"
                out = tc.query(i, "i", "Row(f=1)")
                assert out["results"][0]["columns"] == sorted(cols)

    def test_intersect_count_across_nodes(self):
        with TestCluster(3) as tc:
            _populate(tc)
            # Row 3 = same columns as row 1 on shards 0..2
            for s in range(3):
                tc.query(1, "i", f"Set({s * SHARD_WIDTH + s + 1}, f=3)")
            tc.await_shard_convergence("i")
            out = tc.query(2, "i", "Count(Intersect(Row(f=1), Row(f=3)))")
            assert out["results"][0] == 3

    def test_topn_distributed(self):
        with TestCluster(3) as tc:
            _populate(tc)
            out = tc.query(1, "i", "TopN(f, n=2)")
            pairs = out["results"][0]
            assert pairs[0] == {"id": 1, "count": N_SHARDS}
            assert pairs[1] == {"id": 2, "count": N_SHARDS // 2}

    def test_sum_bsi_distributed(self):
        with TestCluster(3) as tc:
            tc.create_index("i")
            tc.create_field("i", "f")
            tc.create_field("i", "v", {"type": "int", "min": 0, "max": 1000})
            total = 0
            for s in range(N_SHARDS):
                col = s * SHARD_WIDTH + 3
                val = 10 * (s + 1)
                total += val
                tc.query(s % 3, "i", f"Set({col}, v={val})")
                tc.query(s % 3, "i", f"Set({col}, f=1)")
            tc.await_shard_convergence("i")
            out = tc.query(2, "i", "Sum(field=v)")
            assert out["results"][0] == {"value": total, "count": N_SHARDS}
            out = tc.query(1, "i", "Max(field=v)")
            assert out["results"][0] == {"value": 10 * N_SHARDS, "count": 1}

    def test_rows_and_groupby_distributed(self):
        with TestCluster(3) as tc:
            _populate(tc)
            out = tc.query(0, "i", "Rows(f)")
            assert out["results"][0] == {"rows": [1, 2]}
            out = tc.query(1, "i", "GroupBy(Rows(f))")
            groups = out["results"][0]
            assert {g["group"][0]["rowID"]: g["count"] for g in groups} == {
                1: N_SHARDS,
                2: N_SHARDS // 2,
            }

    def test_clear_and_clearrow_distributed(self):
        with TestCluster(3) as tc:
            cols = _populate(tc)
            out = tc.query(1, "i", f"Clear({cols[0]}, f=1)")
            assert out["results"][0] is True
            assert tc.query(2, "i", "Count(Row(f=1))")["results"][0] == N_SHARDS - 1
            tc.query(0, "i", "ClearRow(f=2)")
            assert tc.query(1, "i", "Count(Row(f=2))")["results"][0] == 0


class TestReplication:
    def test_writes_reach_all_replicas(self):
        with TestCluster(3, replica_n=2) as tc:
            tc.create_index("i")
            tc.create_field("i", "f")
            col = 5
            tc.query(0, "i", f"Set({col}, f=1)")
            owners = tc[0].cluster.topology.shard_nodes("i", 0)
            assert len(owners) == 2
            for owner in owners:
                cn = next(n for n in tc.nodes if n.node.id == owner.id)
                f = cn.holder.index("i").field("f")
                assert f.row(1, 0).includes_column(col), owner.id

    def test_clearrow_replicated_survives_primary_down(self):
        with TestCluster(3, replica_n=2) as tc:
            _populate(tc)
            tc.query(0, "i", "ClearRow(f=2)")
            # Kill each node in turn conceptually: clearing must have hit
            # every replica, so any single-node outage can't resurrect row 2.
            tc[1].server.close()
            assert tc.query(0, "i", "Count(Row(f=2))")["results"][0] == 0

    def test_import_routed_to_owners(self):
        with TestCluster(3, replica_n=2) as tc:
            tc.create_index("i")
            tc.create_field("i", "f")
            cols = [s * SHARD_WIDTH + 11 for s in range(N_SHARDS)]
            # Import through node 0 regardless of ownership.
            tc[0].api.import_bits("i", "f", [1] * len(cols), cols)
            tc.await_shard_convergence("i")
            # Visible cluster-wide from every node.
            for i in range(3):
                assert tc.query(i, "i", "Count(Row(f=1))")["results"][0] == len(cols)
            # And present on BOTH replicas of each shard locally.
            for s in range(N_SHARDS):
                for owner in tc[0].cluster.topology.shard_nodes("i", s):
                    cn = next(n for n in tc.nodes if n.node.id == owner.id)
                    f = cn.holder.index("i").field("f")
                    assert f.row(1, s).includes_column(cols[s]), (s, owner.id)

    def test_import_values_routed(self):
        with TestCluster(3) as tc:
            tc.create_index("i")
            tc.create_field("i", "v", {"type": "int", "min": 0, "max": 10**6})
            cols = [s * SHARD_WIDTH + 1 for s in range(N_SHARDS)]
            vals = [100 * (s + 1) for s in range(N_SHARDS)]
            tc[1].api.import_values("i", "v", cols, vals)
            tc.await_shard_convergence("i")
            out = tc.query(2, "i", "Sum(field=v)")
            assert out["results"][0] == {"value": sum(vals), "count": len(vals)}

    def test_count_survives_node_down(self):
        with TestCluster(3, replica_n=2) as tc:
            cols = _populate(tc)
            # Kill a non-coordinator node's server; query through node 0.
            tc[2].server.close()
            out = tc.query(0, "i", "Count(Row(f=1))")
            assert out["results"][0] == len(cols)

    def test_unreplicated_shard_unavailable_raises(self):
        with TestCluster(3, replica_n=1) as tc:
            _populate(tc)
            tc[2].server.close()
            # Some shard owned solely by node2 -> error (reference
            # errShardUnavailable path) unless node 0/1 own everything.
            owned_by_2 = [
                s for s in range(N_SHARDS)
                if tc[0].cluster.topology.primary_for_shard("i", s).id == "node2"
            ]
            if owned_by_2:
                from pilosa_tpu.server.api import APIError

                # Must surface as a clean APIError (503/502), not a 500
                # PANIC traceback.
                with pytest.raises(APIError):
                    tc.query(0, "i", "Count(Row(f=1))")


class TestSchemaPropagation:
    def test_ddl_broadcast(self):
        with TestCluster(3) as tc:
            tc.create_index("idx1")
            tc.create_field("idx1", "fld1")
            for cn in tc.nodes:
                idx = cn.holder.index("idx1")
                assert idx is not None
                assert idx.field("fld1") is not None

    def test_attrs_replicated(self):
        with TestCluster(3) as tc:
            tc.create_index("i")
            tc.create_field("i", "f")
            tc.query(0, "i", 'SetRowAttrs(f, 1, color="red")')
            for cn in tc.nodes:
                f = cn.holder.index("i").field("f")
                assert f.row_attr_store.attrs(1) == {"color": "red"}


class TestInternalClientHTTP:
    def test_query_node_over_http(self):
        with TestCluster(2) as tc:
            _populate(tc)
            client = InternalClient()
            out = client.query_node(
                tc[1].node, "i", "Count(Row(f=1))", shards=[0], remote=False
            )
            # Non-remote query through node1 fans out cluster-wide for
            # shard 0 only.
            assert out["results"][0] == 1

    def test_status_and_nodes(self):
        with TestCluster(2) as tc:
            client = InternalClient()
            st = client.status(tc[0].node)
            assert st["state"] == "NORMAL"
            assert len(st["nodes"]) == 2


class TestRejoin:
    def test_restarted_join_node_rejoins(self):
        """ADVICE r3 medium: a member that restarts and re-announces must
        receive the current cluster status + schema instead of staying
        standalone while the cluster routes shards to it."""
        with TestCluster(2) as tc:
            tc.create_index("i")
            tc.create_field("i", "f")
            tc.query(0, "i", "Set(3, f=1)")
            # Simulate node1 restarting: it boots single-node (sees only
            # itself, believes itself coordinator) with empty schema.
            import shutil as _shutil

            n1 = tc[1]
            n1.holder.close()
            _shutil.rmtree(n1.data_dir, ignore_errors=True)
            from pilosa_tpu.core.holder import Holder

            n1.holder = Holder(n1.data_dir).open()
            n1.api.holder = n1.holder
            n1.executor.holder = n1.holder
            n1.node.is_coordinator = True
            tc._wire(n1, [n1.node])
            assert len(n1.cluster.topology.nodes) == 1
            # Re-announce to the coordinator: handle_join sees an existing
            # member and re-sends schema + cluster status directly.
            ok = n1.cluster.join_cluster(tc[0].node.uri, timeout=10.0)
            assert ok
            assert len(n1.cluster.topology.nodes) == 2
            assert not n1.cluster.local_node.is_coordinator
            assert n1.holder.index("i") is not None
            f = n1.holder.index("i").field("f")
            assert f is not None
            # Available shards ship with the rejoin status: queries
            # routed through the rejoined node fan out over every shard
            # immediately (code review r4).
            assert 0 in f.available_shards().to_array().tolist()


class TestWireFallback:
    def test_sender_falls_back_to_json_per_peer(self):
        """ADVICE r3: a JSON-only peer rejecting a binary control frame
        gets ONE JSON retry and is pinned to JSON for later sends."""
        import json as _json

        from pilosa_tpu.cluster.broadcast import HTTPBroadcaster, Message
        from pilosa_tpu.cluster.client import ClientError

        class JSONOnlyPeer:
            def __init__(self):
                self.binary_rejects = 0
                self.accepted = []

            def send_message(self, node, payload):
                try:
                    _json.loads(payload)
                except Exception:
                    # A legacy build surfaces the decode error through
                    # its panic trap; a current build answers the
                    # structured bad-frame code — cover the legacy shape.
                    self.binary_rejects += 1
                    raise ClientError(
                        "PANIC: json.decoder.JSONDecodeError: ...",
                        status=500,
                    )
                self.accepted.append(payload)

        class _Stub:
            pass

        cluster = _Stub()
        cluster.local_node = Node("n0", URI(port=1), True)
        cluster.topology = Topology(nodes=[cluster.local_node])
        fake = JSONOnlyPeer()
        b = HTTPBroadcaster(cluster, client=fake)
        peer = Node("n1", URI(port=2), False)
        msg = Message.make("cluster-status", state="NORMAL")
        binary = msg.to_bytes()
        b.send_to(peer, msg)
        b.send_to(peer, msg)
        assert len(fake.accepted) == 2
        if binary != _json.dumps(msg).encode():
            # Binary default: exactly one rejected attempt, then pinned.
            assert fake.binary_rejects == 1
            assert "n1" in b._json_peers

    def test_wire_pins_safe_under_concurrent_reset(self):
        """Regression for the shared-state finding fixed in ISSUE r13:
        fan-out send threads read/pin `_json_peers` concurrently with
        the membership-change clear — all now serialized by
        `_wire_lock`, so a negotiate/reset storm neither corrupts the
        set nor drops the pin invariant (a peer is either pinned or
        re-negotiates; never a torn state)."""
        import threading as _threading

        from pilosa_tpu.cluster.broadcast import HTTPBroadcaster, Message

        class AcceptAllPeer:
            timeout = 1.0

            def send_message(self, node, payload):
                pass

        class _Stub:
            pass

        cluster = _Stub()
        cluster.local_node = Node("n0", URI(port=1), True)
        cluster.topology = Topology(nodes=[cluster.local_node])
        b = HTTPBroadcaster(cluster, client=AcceptAllPeer())
        peers = [Node(f"n{i}", URI(port=2 + i), False) for i in range(1, 5)]
        msg = Message.make("cluster-status", state="NORMAL")
        stop = _threading.Event()
        errors: list = []

        def sender(p):
            while not stop.is_set():
                try:
                    b.send_to(p, msg)
                    with b._wire_lock:
                        b._json_peers.add(p.id)
                except Exception as e:  # noqa: BLE001 — fail the test loudly
                    errors.append(e)
                    return

        def resetter():
            while not stop.is_set():
                b.reset_wire_negotiation()

        threads = [_threading.Thread(target=sender, args=(p,)) for p in peers]
        threads.append(_threading.Thread(target=resetter))
        for t in threads:
            t.start()
        import time as _time

        _time.sleep(0.2)
        stop.set()
        for t in threads:
            t.join(timeout=5)
        assert not errors, errors
        with b._wire_lock:
            assert b._json_peers <= {p.id for p in peers}

    def test_transport_failure_not_retried_as_json(self):
        from pilosa_tpu.cluster.broadcast import HTTPBroadcaster, Message
        from pilosa_tpu.cluster.client import ClientError

        attempts = []

        class DeadPeer:
            def send_message(self, node, payload):
                attempts.append(payload)
                raise ClientError("connection refused")  # status 0

        class _Stub:
            pass

        cluster = _Stub()
        cluster.local_node = Node("n0", URI(port=1), True)
        cluster.topology = Topology(nodes=[cluster.local_node])
        b = HTTPBroadcaster(cluster, client=DeadPeer())
        peer = Node("n1", URI(port=2), False)
        try:
            b.send_to(peer, Message.make("cluster-status", state="NORMAL"))
            raise AssertionError("expected ClientError")
        except ClientError:
            pass
        assert len(attempts) == 1

    def test_handler_error_not_retried_as_json(self):
        """A post-parse handler error (generic PANIC, no decode marker)
        must not be re-sent — the peer may have partially applied it."""
        from pilosa_tpu.cluster.broadcast import HTTPBroadcaster, Message
        from pilosa_tpu.cluster.client import ClientError

        attempts = []

        class AngryPeer:
            def send_message(self, node, payload):
                attempts.append(payload)
                raise ClientError("PANIC: KeyError: 'nodes'", status=500)

        class _Stub:
            pass

        cluster = _Stub()
        cluster.local_node = Node("n0", URI(port=1), True)
        cluster.topology = Topology(nodes=[cluster.local_node])
        b = HTTPBroadcaster(cluster, client=AngryPeer())
        peer = Node("n1", URI(port=2), False)
        try:
            b.send_to(peer, Message.make("cluster-status", state="NORMAL"))
            raise AssertionError("expected ClientError")
        except ClientError:
            pass
        assert len(attempts) == 1


class TestClusterExport:
    def test_whole_field_export_across_nodes(self):
        """VERDICT r3 missing #6: export must cover every shard whichever
        node holds it (reference ctl/export.go, api.go:591)."""
        with TestCluster(3) as tc:
            cols = _populate(tc)
            csv0 = tc[0].api.export_csv("i", "f")
            got = sorted(
                tuple(map(int, ln.split(",")))
                for ln in csv0.strip().splitlines()
                if ln
            )
            want_r1 = [(1, c) for c in cols]
            want_r2 = [
                (2, s * SHARD_WIDTH + 7) for s in range(0, N_SHARDS, 2)
            ]
            assert got == sorted(want_r1 + want_r2)
            # Same result whichever node serves the export.
            assert tc[1].api.export_csv("i", "f") is not None
            got1 = sorted(
                tuple(map(int, ln.split(",")))
                for ln in tc[1].api.export_csv("i", "f").strip().splitlines()
                if ln
            )
            assert got1 == got

    def test_keyed_export_emits_keys(self):
        with TestCluster(2) as tc:
            tc.create_index("ki", {"keys": True})
            tc.create_field("ki", "kf", {"keys": True})
            tc.query(0, "ki", 'Set("colA", kf="rowX")')
            tc.query(1, "ki", 'Set("colB", kf="rowX")')
            tc.await_shard_convergence("ki")
            csv = tc[0].api.export_csv("ki", "kf")
            lines = sorted(ln for ln in csv.strip().splitlines() if ln)
            assert lines == ["rowX,colA", "rowX,colB"]
