"""Execution introspection plane tests (ISSUE 16): EXPLAIN is free when
off and faithful when on (its plan agrees with the embedded counter
families), the device-program ledger detects a forced recompile, and a
two-thread WAL convoy lands in the lock-stall plane with an exemplar
that resolves to the waiter's trace."""

import json
import threading
import time
import urllib.request

import pytest

from pilosa_tpu.core import Holder
from pilosa_tpu.core.fragment import _WalFile
from pilosa_tpu.exec import Executor
from pilosa_tpu.exec.tpu import TPUBackend
from pilosa_tpu.server.api import API
from pilosa_tpu.server.http import Server
from pilosa_tpu.utils.locks import global_stall_ledger
from pilosa_tpu.utils.qprofile import ExplainPlan, profile_scope
from pilosa_tpu.utils.stats import global_stats
from pilosa_tpu.utils.tracing import global_tracer


@pytest.fixture
def server(tmp_path):
    holder = Holder(str(tmp_path / "data")).open()
    srv = Server(API(holder, Executor(holder)), host="localhost", port=0).open()
    yield srv
    srv.close()
    holder.close()


def _post(srv, path, body=b"{}", ctype="application/json", headers=None):
    hdrs = {"Content-Type": ctype}
    hdrs.update(headers or {})
    r = urllib.request.Request(srv.uri + path, data=body, method="POST", headers=hdrs)
    return json.loads(urllib.request.urlopen(r).read())


def post_query(srv, pql, suffix="", headers=None):
    return _post(
        srv, "/index/i/query" + suffix, pql.encode(), "text/plain", headers
    )


def get_json(srv, path):
    return json.loads(urllib.request.urlopen(srv.uri + path).read())


def setup_index(srv):
    _post(srv, "/index/i")
    _post(srv, "/index/i/field/f")
    post_query(srv, "Set(10, f=1) Set(100, f=1)")


class TestExplainOptIn:
    def test_off_allocates_no_plan(self, server, monkeypatch):
        """The alloc pin: with the flag off, no ExplainPlan is ever
        constructed anywhere on the serving path — the deep hooks are
        getattr checks against a None slot, not plan-node builders."""
        setup_index(server)
        made = []
        orig = ExplainPlan.__init__

        def counting(plan):
            made.append(1)
            orig(plan)

        monkeypatch.setattr(ExplainPlan, "__init__", counting)
        out = post_query(server, "Count(Row(f=1))")
        assert out == {"results": [2]}
        assert made == []
        out = post_query(server, "Count(Row(f=1))", suffix="?explain=1")
        assert out["results"] == [2]
        assert made == [1]

    def test_flag_attaches_plan(self, server):
        setup_index(server)
        out = post_query(server, "Count(Row(f=1))", suffix="?explain=1")
        assert out["results"] == [2]
        calls = out["explain"]["calls"]
        assert calls and calls[0]["call"] == "Count"
        assert "route" in calls[0]
        # Header spelling of the same opt-in.
        out = post_query(server, "Row(f=1)", headers={"X-Pilosa-Explain": "1"})
        assert "explain" in out
        assert out["explain"]["calls"][0]["call"] == "Row"

    def test_ring_carries_shards_and_plan(self, server):
        """Satellite: every ring entry (explain or not) carries the
        resolved shard count; explain entries carry the plan too."""
        setup_index(server)
        post_query(server, "Row(f=1)")
        post_query(server, "Count(Row(f=1))", suffix="?explain=1")
        recent = get_json(server, "/debug/queries")["recent"]
        # The ring is process-global and newest-first: keep the newest
        # entry per query so earlier tests' entries don't shadow ours.
        by_query = {}
        for e in recent:
            if e.get("query") and e["query"] not in by_query:
                by_query[e["query"]] = e
        assert by_query["Row(f=1)"]["shards"] >= 1
        assert "explain" not in by_query["Row(f=1)"]
        assert "calls" in by_query["Count(Row(f=1))"]["explain"]

    def test_debug_stalls_and_programs_routes(self, server):
        stalls = get_json(server, "/debug/stalls?n=5")
        assert "worst" in stalls and "sites" in stalls
        programs = get_json(server, "/debug/programs")
        assert {"programs", "compiles", "recompiles", "launches", "entries"} <= set(
            programs
        )


@pytest.fixture
def tpu_ex(tmp_path):
    holder = Holder(str(tmp_path / "data")).open()
    idx = holder.create_index("i")
    idx.create_field("f")
    Executor(holder).execute("i", "Set(10, f=1) Set(100, f=1) Set(7, f=2)")
    be = TPUBackend(holder)
    yield Executor(holder, backend=be), be
    holder.close()


def _device_counters():
    snap = global_stats.snapshot()["counters"]
    return {
        k: v
        for k, v in snap.items()
        if k.startswith(("device_launches_total", "device_recompiles_total"))
    }


class TestExplainDifferential:
    def test_plan_matches_leg_counter_deltas(self, tpu_ex):
        """The plan must agree with the embedded counter families
        (bench.py LEG_COUNTER_FAMILIES): one launch record per
        device_launches_total increment, and the recompile family stays
        flat on a first-compile run."""
        from bench import LEG_COUNTER_FAMILIES

        assert "device_recompiles_total" in LEG_COUNTER_FAMILIES
        assert "snapshot_stall_seconds_total" in LEG_COUNTER_FAMILIES
        ex, _ = tpu_ex
        before = _device_counters()
        with profile_scope(index="i", query="Count(Row(f=1))") as prof:
            prof.explain = ExplainPlan()
            assert ex.execute("i", "Count(Row(f=1))") == [2]
        after = _device_counters()
        launched = sum(
            len(c.get("launches", [])) for c in prof.explain.calls
        )
        delta = sum(
            v - before.get(k, 0.0)
            for k, v in after.items()
            if k.startswith("device_launches_total")
        )
        assert launched == delta
        # Each launch record names its program and carries the byte
        # accounting the ledger aggregates.
        for call in prof.explain.calls:
            for rec in call.get("launches", []):
                assert rec["kind"] and rec["program"]
                assert rec["bytesShipped"] > 0
        recompiled = sum(
            v - before.get(k, 0.0)
            for k, v in after.items()
            if k.startswith("device_recompiles_total")
        )
        assert recompiled == 0

    def test_forced_recompile_detected(self, tpu_ex):
        """Dropping the jit-fn cache and re-running the same shape is a
        same-signature second compile: the ledger must count it as a
        recompile (the /debug/programs regression signal)."""
        ex, be = tpu_ex
        ex.execute("i", "Count(Row(f=1))")
        base = be.programs.counts()
        ex.execute("i", "Count(Row(f=1))")
        steady = be.programs.counts()
        assert steady["recompiles"] == base["recompiles"]
        be._fns.clear()
        ex.execute("i", "Count(Row(f=1))")
        forced = be.programs.counts()
        assert forced["recompiles"] > base["recompiles"]
        assert any(
            k.startswith("device_recompiles_total")
            for k in global_stats.snapshot()["counters"]
        )
        # The ledger row for the recompiled program shows both compiles.
        assert any(e["compiles"] >= 2 for e in be.programs.ledger())


class TestLockStallAttribution:
    def test_wal_convoy_attributed_with_exemplar(self, tmp_path):
        """Two-thread WAL convoy: the writer that waits must land in
        lock_wait_seconds{site=wal_append} and the stall ledger, with a
        trace id that resolves to the waiter's span."""
        wal = _WalFile(str(tmp_path / "f.wal"))
        holding = threading.Event()
        release = threading.Event()

        def holder_thread():
            with wal._lock:
                holding.set()
                release.wait(5.0)

        trace_id = []

        def writer_thread():
            with global_tracer.start_span("wal-convoy-writer") as span:
                trace_id.append(span.trace_id)
                wal.write(b"x" * 64)

        t_hold = threading.Thread(target=holder_thread)
        t_hold.start()
        assert holding.wait(5.0)
        t_write = threading.Thread(target=writer_thread)
        t_write.start()
        time.sleep(0.05)  # let the writer block on the held lock
        release.set()
        t_write.join(5.0)
        t_hold.join(5.0)
        wal.release()

        entries = [
            e for e in global_stall_ledger.worst(256)
            if e["site"] == "wal_append" and e["traceId"] == trace_id[0]
        ]
        assert entries, "convoyed WAL write missing from the stall ledger"
        assert entries[0]["waitMs"] > 0
        # The exemplar resolves: the tracer can serve the waiter's span.
        assert global_tracer.spans_for(trace_id[0])
        # Site aggregates and the histogram family both saw the wait.
        assert global_stall_ledger.sites()["wal_append"]["waits"] >= 1
        timings = global_stats.snapshot()["timings"]
        assert any(
            name.startswith("lock_wait_seconds") and 'site="wal_append"' in name
            for name in timings
        )
        hist = global_stats.histogram_snapshot()
        waits = [
            ent for name, ent in hist.items()
            if name.startswith("lock_wait_seconds") and 'site="wal_append"' in name
        ]
        assert waits and waits[0]["count"] >= 1
        assert any(
            ex_rec["trace_id"] == trace_id[0]
            for ent in waits
            for ex_rec in ent.get("exemplars", [])
        )
