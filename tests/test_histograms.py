"""Round-10 latency-distribution plane (ISSUE r10): fixed-boundary
mergeable histograms, cluster-merged quantiles from /metrics/cluster,
SLO burn rates at /debug/slo, and trace exemplars linking a burning
bucket to /debug/traces/<id>."""

import json
import random
import re
import urllib.error
import urllib.request

import pytest

from pilosa_tpu.shardwidth import SHARD_WIDTH
from pilosa_tpu.utils.stats import (
    BUCKET_BOUNDS,
    BUCKET_RATIO,
    StatsClient,
    bucket_fraction_le,
    bucket_index,
    bucket_quantile,
    global_stats,
    merge_buckets,
)
from pilosa_tpu.utils.tracing import Tracer, global_tracer
from tests.cluster_harness import FaultProxy, RewriteClient, TestCluster


def _get_json(uri: str, path: str) -> dict:
    with urllib.request.urlopen(uri + path, timeout=10) as resp:
        return json.loads(resp.read())


def _get_text(uri: str, path: str) -> str:
    with urllib.request.urlopen(uri + path, timeout=10) as resp:
        return resp.read().decode()


def _exact_quantile(samples: list, q: float) -> float:
    s = sorted(samples)
    return s[min(len(s) - 1, int(len(s) * q))]


def _within_one_bucket(estimated: float, exact: float) -> bool:
    """The histogram promise: an interpolated quantile lands in the
    exact value's bucket or an adjacent one."""
    return abs(bucket_index(estimated) - bucket_index(exact)) <= 1


class TestHistogramCore:
    def test_buckets_cumulative_monotonic_and_exact_count_sum(self):
        s = StatsClient()
        random.seed(7)
        samples = [random.lognormvariate(-5, 1.5) for _ in range(500)]
        for v in samples:
            s.timing("probe_seconds", v)
        text = s.prometheus_text()
        assert "# TYPE pilosa_probe_seconds histogram" in text
        assert "# HELP pilosa_probe_seconds" in text
        cums = []
        for line in text.splitlines():
            if line.startswith("pilosa_probe_seconds_bucket"):
                cums.append(float(line.partition(" # ")[0].rsplit(" ", 1)[1]))
        assert len(cums) == len(BUCKET_BOUNDS) + 1  # 31 finite + +Inf
        assert cums == sorted(cums), "bucket counts must be cumulative"
        assert cums[-1] == len(samples)
        snap = s.snapshot()["timings"]["probe_seconds"]
        assert snap["count"] == len(samples)
        assert snap["sum"] == pytest.approx(sum(samples))

    def test_series_never_vanishes_under_heavy_traffic(self):
        """Ring-trim regression (ISSUE r10 satellite): the old 1024-ring
        trimmed half its samples mid-stream; a series that drained
        vanished from export and broke rate() continuity. Buckets are
        cumulative: 5000 observations stay 5000."""
        s = StatsClient()
        for _ in range(5000):
            s.timing("busy_seconds", 0.002)
        assert "pilosa_busy_seconds_count 5000" in s.prometheus_text()
        assert s.snapshot()["timings"]["busy_seconds"]["count"] == 5000

    def test_quantiles_unbiased_by_recency(self):
        """The old ring kept only the newest 1024 samples, so a burst of
        slow queries owned the p50 regardless of the day's traffic. The
        cumulative histogram weighs every observation once."""
        s = StatsClient()
        for _ in range(2000):
            s.timing("mixed_seconds", 0.001)
        for _ in range(20):
            s.timing("mixed_seconds", 1.0)
        snap = s.snapshot()["timings"]["mixed_seconds"]
        assert snap["p50"] < 0.01  # 2000/2020 observations are ~1 ms
        assert snap["p999"] > 0.1  # but the slow tail is still visible

    def test_quantile_interpolation_vs_exact_known_samples(self):
        s = StatsClient()
        random.seed(42)
        samples = [random.lognormvariate(-4, 1.0) for _ in range(4000)]
        for v in samples:
            s.timing("known_seconds", v)
        snap = s.snapshot()["timings"]["known_seconds"]
        for label, q in (("p50", 0.5), ("p95", 0.95), ("p99", 0.99),
                         ("p999", 0.999)):
            exact = _exact_quantile(samples, q)
            est = snap[label]
            assert _within_one_bucket(est, exact), (label, est, exact)
            # And never off by more than one bucket's multiplicative
            # width squared (adjacent-bucket worst case).
            assert exact / BUCKET_RATIO**2 <= est <= exact * BUCKET_RATIO**2

    def test_merge_commutative_and_associative(self):
        random.seed(3)
        n = len(BUCKET_BOUNDS) + 1
        a = [random.randrange(50) for _ in range(n)]
        b = [random.randrange(50) for _ in range(n)]
        c = [random.randrange(50) for _ in range(n)]
        assert merge_buckets(a, b) == merge_buckets(b, a)
        assert merge_buckets(merge_buckets(a, b), c) == merge_buckets(
            a, merge_buckets(b, c)
        )
        # Quantiles of a merge are quantiles of the pooled population.
        pooled = merge_buckets(a, b)
        assert sum(pooled) == sum(a) + sum(b)

    def test_exposition_merge_matches_pooled_quantile(self):
        """The exposition-level merge (/metrics/cluster's helper) must
        agree with the pooled sample set within one bucket width, and be
        order-independent."""
        from pilosa_tpu.server.http import _merge_member_histograms

        na, nb = StatsClient(), StatsClient()
        random.seed(9)
        # Unequal counts so no tested rank lands exactly on the empty
        # gap between the modes (there the CDF is flat and any value
        # across the gap is an equally valid quantile).
        sa = [random.uniform(0.0005, 0.005) for _ in range(700)]
        sb = [random.uniform(0.02, 0.4) for _ in range(900)]
        for v in sa:
            na.timing("pool_seconds", v)
        for v in sb:
            nb.timing("pool_seconds", v)
        ta, tb = na.prometheus_text(), nb.prometheus_text()
        merged = _merge_member_histograms([ta, tb])
        assert merged == _merge_member_histograms([tb, ta])
        counts = _bucket_counts(merged, "pilosa_pool_seconds")
        assert sum(counts) == len(sa) + len(sb)
        for q in (0.5, 0.99):
            est = bucket_quantile(counts, q)
            exact = _exact_quantile(sa + sb, q)
            assert _within_one_bucket(est, exact), (q, est, exact)

    def test_fraction_le_interpolation(self):
        counts = [0] * (len(BUCKET_BOUNDS) + 1)
        # 100 observations uniform inside the bucket that contains 0.01
        i = bucket_index(0.01)
        counts[i] = 100
        lo = BUCKET_BOUNDS[i - 1]
        hi = BUCKET_BOUNDS[i]
        mid = (lo + hi) / 2
        frac = bucket_fraction_le(counts, mid)
        assert frac == pytest.approx(0.5, abs=0.01)
        assert bucket_fraction_le(counts, BUCKET_BOUNDS[-1]) == 1.0
        assert bucket_fraction_le([0] * len(counts), 1.0) is None

    def test_remote_leg_excluded_from_query_seconds(self):
        """A coordinator-dispatched peer leg (?remote=true) must not
        feed the whole-query latency series: one distributed query is
        ONE observation in the cluster-merged distribution, not one per
        participating node."""
        from pilosa_tpu.utils.qprofile import profile_scope

        def count_for(call):
            snap = global_stats.histogram_snapshot()
            ent = snap.get(f'query_seconds{{call="{call}"}}')
            return ent["count"] if ent else 0

        with profile_scope(index="i", call="RemoteLeg") as prof:
            prof.remote = True
        assert count_for("RemoteLeg") == 0
        with profile_scope(index="i", call="LocalQuery"):
            pass
        assert count_for("LocalQuery") == 1

    def test_exemplar_recorded_under_active_trace_only(self):
        s = StatsClient()
        s.timing("exm_seconds", 0.003)  # no active span: no exemplar
        assert "trace_id" not in s.prometheus_text()
        span = global_tracer.start_span("exemplar-test")
        s.timing("exm_seconds", 0.004)
        span.finish()
        text = s.prometheus_text()
        m = re.search(r'# \{trace_id="([0-9a-f]+)"\} 0\.004', text)
        assert m, text
        assert m.group(1) == span.trace_id


def _bucket_counts(lines, family_prefix: str) -> list:
    """Per-bucket (non-cumulative) counts from exposition _bucket lines."""
    cums = []
    for line in lines:
        if line.startswith(family_prefix + "_bucket"):
            cums.append(float(line.partition(" # ")[0].rsplit(" ", 1)[1]))
    return [cums[0]] + [cums[i] - cums[i - 1] for i in range(1, len(cums))]


class TestSloEvaluation:
    def _monitor(self, slo):
        from pilosa_tpu.utils.monitor import RuntimeMonitor

        mon = RuntimeMonitor()
        mon.slo = slo
        mon.record_histogram_snapshot(force=True)  # leg-start baseline
        return mon

    def test_burning_objective_reports_multi_window_burn(self):
        mon = self._monitor(
            [{"metric": "slo_burn_seconds", "quantile": 0.9,
              "threshold_s": 0.01, "window_s": 60.0}]
        )
        for _ in range(40):
            global_stats.timing("slo_burn_seconds", 0.2)  # all violations
        (o,) = mon.evaluate_slos()
        assert o["compliant"] is False
        assert o["observations"] == 40
        # 100% violations against a 10% budget: burn rate 10x.
        assert o["burnRate_fast"] == pytest.approx(10.0, rel=0.01)
        assert o["burnRate_slow"] == pytest.approx(10.0, rel=0.01)
        assert o["burning"] is True

    def test_compliant_objective_not_burning(self):
        mon = self._monitor(
            [{"metric": "slo_ok_seconds", "quantile": 0.99,
              "threshold_s": 0.5, "window_s": 60.0}]
        )
        for _ in range(40):
            global_stats.timing("slo_ok_seconds", 0.001)
        (o,) = mon.evaluate_slos()
        assert o["compliant"] is True
        assert o["burnRate_fast"] == pytest.approx(0.0, abs=1e-6)
        assert o["burning"] is False

    def test_no_observations_is_compliant_not_crash(self):
        mon = self._monitor(
            [{"metric": "slo_absent_seconds", "quantile": 0.99,
              "threshold_s": 0.1, "window_s": 60.0}]
        )
        (o,) = mon.evaluate_slos()
        assert o["compliant"] is True
        assert o["currentQuantileS"] is None
        assert o["observations"] == 0

    def test_windowed_delta_excludes_pre_window_traffic(self):
        """The burn calculation must diff against the baseline snapshot,
        not read the cumulative series — yesterday's outage is not
        today's burn."""
        for _ in range(100):
            global_stats.timing("slo_hist_seconds", 0.5)  # "yesterday"
        mon = self._monitor(
            [{"metric": "slo_hist_seconds", "quantile": 0.9,
              "threshold_s": 0.01, "window_s": 60.0}]
        )
        for _ in range(10):
            global_stats.timing("slo_hist_seconds", 0.001)  # healthy now
        (o,) = mon.evaluate_slos()
        assert o["observations"] == 10
        assert o["compliant"] is True
        assert o["burnRate_fast"] == pytest.approx(0.0, abs=1e-6)


class TestSloConfigValidation:
    def test_normalize_rejects_out_of_range_objectives(self):
        """`quantile = 99` (the percent-vs-fraction typo) must fail
        config load, not page forever with a ~1e9 burn rate."""
        pytest.importorskip("tomllib")
        from pilosa_tpu.server.config import Config

        ok = Config._normalize_slo(
            [{"metric": "query_seconds", "quantile": 0.99,
              "threshold": 0.5, "window": 600}]
        )
        assert ok == [{"metric": "query_seconds", "quantile": 0.99,
                       "threshold_s": 0.5, "window_s": 600.0}]
        for bad in (
            [{"metric": "m", "quantile": 99}],
            [{"metric": "m", "quantile": 0.0}],
            [{"metric": "m", "threshold": 0}],
            # Past the top finite bucket bound the CDF reads every +Inf
            # observation as compliant: the objective could never page.
            [{"metric": "m", "threshold": BUCKET_BOUNDS[-1] * 2}],
            [{"metric": "m", "window": -1}],
            [{"quantile": 0.99}],
        ):
            with pytest.raises(ValueError):
                Config._normalize_slo(bad)


class TestHttpSurfaces:
    @pytest.fixture()
    def cluster1(self):
        with TestCluster(1) as tc:
            yield tc

    def test_metrics_exposes_histogram_families(self, cluster1):
        uri = str(cluster1[0].node.uri)
        cluster1.create_index("h1")
        cluster1.create_field("h1", "f")
        cluster1.query(0, "h1", "Set(1, f=0)")
        cluster1.query(0, "h1", "Count(Row(f=0))")
        _get_json(uri, "/status")
        text = _get_text(uri, "/metrics")
        for family in (
            "pilosa_query_phase_seconds",
            "pilosa_http_request_duration_seconds",
        ):
            assert f"# TYPE {family} histogram" in text
            assert f"# HELP {family}" in text
            assert f'{family}_bucket{{' in text
            assert re.search(rf'{family}_bucket{{[^}}]*le="\+Inf"}}', text)
            assert f"{family}_sum{{" in text
            assert f"{family}_count{{" in text

    def test_debug_queries_latency_block(self, cluster1):
        uri = str(cluster1[0].node.uri)
        cluster1.create_index("h2")
        cluster1.create_field("h2", "f")
        # Through the HTTP surface so the profile opens at ingress.
        req = urllib.request.Request(
            uri + "/index/h2/query", data=b"Count(Row(f=0))", method="POST"
        )
        with urllib.request.urlopen(req, timeout=10) as resp:
            resp.read()
        out = _get_json(uri, "/debug/queries")
        assert "latency" in out
        assert "Count" in out["latency"], out["latency"]
        row = out["latency"]["Count"]
        assert row["count"] >= 1
        assert row["p50Ms"] is not None
        assert set(row) >= {"count", "p50Ms", "p95Ms", "p99Ms", "p999Ms"}

    def test_pprof_seconds_validated_and_capped(self, cluster1):
        uri = str(cluster1[0].node.uri)
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get_json(uri, "/debug/pprof/profile?seconds=abc")
        assert ei.value.code == 400
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get_json(uri, "/debug/pprof/profile?top=xyz&seconds=0.1")
        assert ei.value.code == 400
        # The clamp itself: the handler must floor/cap BEFORE profiling;
        # a 0-second request still returns a report instantly.
        out = _get_json(uri, "/debug/pprof/profile?seconds=0&top=3")
        assert "frames" in out or "samples" in out or isinstance(out, dict)

    def test_debug_slo_empty_without_objectives(self, cluster1):
        uri = str(cluster1[0].node.uri)
        out = _get_json(uri, "/debug/slo")
        assert out["objectives"] == []
        assert out["fastWindowS"] == 300.0
        assert out["slowWindowS"] == 3600.0


class TestClusterAcceptance:
    def test_cluster_merged_p99_matches_pooled_observations(self):
        """ISSUE r10 acceptance: /metrics/cluster's merged buckets'
        interpolated p99 matches the pooled two-node observation
        quantile within one bucket width."""
        random.seed(11)
        samples = [random.lognormvariate(-4, 1.3) for _ in range(1500)]
        with TestCluster(2) as tc:
            for v in samples:
                global_stats.timing("pooled_acc_seconds", v)
            text = _get_text(str(tc[0].node.uri), "/metrics/cluster")
            merged_lines = [
                l for l in text.splitlines() if 'node="_cluster"' in l
            ]
            assert merged_lines, "no merged cluster histograms emitted"
            counts = _bucket_counts(merged_lines, "pilosa_pooled_acc_seconds")
            # In-process harness nodes share one registry, so the merge
            # pools two identical member vectors — quantiles unchanged.
            assert sum(counts) == 2 * len(samples)
            est99 = bucket_quantile(counts, 0.99)
            exact99 = _exact_quantile(samples + samples, 0.99)
            assert _within_one_bucket(est99, exact99), (est99, exact99)
            # Per-node series survive next to the merged ones.
            assert re.search(
                r'pilosa_pooled_acc_seconds_bucket\{node="node0"', text
            )

    @pytest.mark.chaos
    def test_slo_flags_injected_latency_burn_with_resolvable_exemplar(self):
        """ISSUE r10 acceptance: a FaultProxy-injected peer latency burn
        shows up at /debug/slo as a burning objective whose exemplar
        trace id resolves through /debug/traces/<id>."""
        from pilosa_tpu.utils.monitor import RuntimeMonitor

        with TestCluster(2) as tc:
            tc.create_index("slo")
            tc.create_field("slo", "f")
            topo = tc[0].cluster.topology
            remote_shards = [
                s for s in range(32)
                if topo.shard_nodes("slo", s)[0].id == "node1"
            ][:2]
            assert remote_shards, "need a shard primaried on node1"
            stmts = " ".join(
                f"Set({s * SHARD_WIDTH + 3}, f=1)" for s in remote_shards
            )
            tc.query(0, "slo", stmts)
            tc.await_shard_convergence("slo")

            target = tc[0].cluster.topology.node_by_id("node1").uri
            proxy = FaultProxy(target.host, target.port)
            proxy.mode = "latency"
            proxy.latency_s = 0.25
            rc = RewriteClient(
                {f"{target.host}:{target.port}": f"127.0.0.1:{proxy.port}"},
                timeout=5.0,
            )
            tc[0].cluster.client = rc

            mon = RuntimeMonitor(tc[0].holder)
            mon.slo = [
                {"metric": "peer_rpc_seconds", "quantile": 0.5,
                 "threshold_s": 0.05, "window_s": 300.0}
            ]
            mon.record_histogram_snapshot(force=True)
            tc[0].api.monitor = mon
            uri = str(tc[0].node.uri)
            try:
                for _ in range(3):
                    req = urllib.request.Request(
                        uri + "/index/slo/query",
                        data=b"Count(Row(f=1))",
                        method="POST",
                    )
                    with urllib.request.urlopen(req, timeout=10) as resp:
                        out = json.loads(resp.read())
                    assert out["results"][0] == len(remote_shards)
                slo = _get_json(uri, "/debug/slo")
            finally:
                proxy.close()
            (o,) = slo["objectives"]
            assert o["compliant"] is False, o
            assert o["burnRate_fast"] > 1.0, o
            assert o["burning"] is True, o
            assert o["exemplars"], "latency burn recorded no trace exemplar"
            trace_id = o["exemplars"][0]["traceID"]
            tree = _get_json(uri, f"/debug/traces/{trace_id}")
            assert tree["traceID"] == trace_id
            assert tree["spanCount"] >= 1, tree
