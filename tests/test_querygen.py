"""Randomized differential stress: generated PQL through the device
backend vs the CPU oracle (reference internal/test/querygenerator.go;
VERDICT r2 missing #6). result_to_json normalizes both sides so Row
columns, TopN pairs, and ValCounts compare exactly."""

import numpy as np
import pytest

from pilosa_tpu.exec import Executor
from pilosa_tpu.exec.result import result_to_json
from pilosa_tpu.exec.tpu import TPUBackend

from tests.querygen import QueryGenerator, build_schema


@pytest.fixture
def holder(tmp_path):
    from pilosa_tpu.core import Holder

    h = Holder(str(tmp_path / "holder")).open()
    yield h
    h.close()


@pytest.mark.parametrize("seed", [1, 2, 3])
def test_generated_queries_differential(holder, seed):
    rng = np.random.default_rng(1000 + seed)
    build_schema(holder, rng, shards=2)
    host = Executor(holder)
    dev = Executor(holder, backend=TPUBackend(holder))
    gen = QueryGenerator(seed)
    for k in range(25):
        q = gen.query()
        want = [result_to_json(r) for r in host.execute("qg", q)]
        got = [result_to_json(r) for r in dev.execute("qg", q)]
        assert got == want, f"seed={seed} q#{k}: {q}"


def test_generated_multi_count_batches(holder):
    """Batched serving path: whole multi-Count requests of generated
    bitmaps must match the oracle call-for-call (exercises the pair-plan
    detection + generic scan grouping under arbitrary shapes)."""
    rng = np.random.default_rng(77)
    build_schema(holder, rng, shards=2)
    host = Executor(holder)
    dev = Executor(holder, backend=TPUBackend(holder))
    gen = QueryGenerator(7)
    for _ in range(4):
        q = "".join(f"Count({gen.bitmap()})" for _ in range(8))
        assert dev.execute("qg", q) == host.execute("qg", q), q
