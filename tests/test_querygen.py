"""Randomized differential stress: generated PQL through the device
backend vs the CPU oracle (reference internal/test/querygenerator.go;
VERDICT r2 missing #6). result_to_json normalizes both sides so Row
columns, TopN pairs, and ValCounts compare exactly."""

import numpy as np
import pytest

from pilosa_tpu.exec import Executor
from pilosa_tpu.exec.result import result_to_json
from pilosa_tpu.exec.tpu import TPUBackend

from tests.querygen import QueryGenerator, build_schema


@pytest.fixture
def holder(tmp_path):
    from pilosa_tpu.core import Holder

    h = Holder(str(tmp_path / "holder")).open()
    yield h
    h.close()


@pytest.mark.parametrize("seed", [1, 2, 3])
def test_generated_queries_differential(holder, seed):
    rng = np.random.default_rng(1000 + seed)
    build_schema(holder, rng, shards=2)
    host = Executor(holder)
    dev = Executor(holder, backend=TPUBackend(holder))
    gen = QueryGenerator(seed)
    for k in range(25):
        q = gen.query()
        want = [result_to_json(r) for r in host.execute("qg", q)]
        got = [result_to_json(r) for r in dev.execute("qg", q)]
        assert got == want, f"seed={seed} q#{k}: {q}"


@pytest.mark.parametrize("seed", [11, 12])
def test_generated_queries_under_write_churn(holder, seed):
    """The write-churn serving protocol, randomized: interleave point
    writes, clears, and occasional bulk imports (delta-uncoverable
    epochs) with generated queries and batched Counts — every answer
    must stay oracle-exact through the delta/slab/sweep tiers."""
    from pilosa_tpu.shardwidth import SHARD_WIDTH

    rng = np.random.default_rng(2000 + seed)
    build_schema(holder, rng, shards=2)
    host = Executor(holder)
    dev = Executor(holder, backend=TPUBackend(holder))
    gen = QueryGenerator(seed)
    idx = holder.index("qg")
    fields = [f for f in idx.fields if not f.startswith("_")]
    set_cols: list = []
    for k in range(40):
        # 1-3 random mutations per step.
        for _ in range(int(rng.integers(1, 4))):
            fname = fields[int(rng.integers(0, len(fields)))]
            fld = idx.field(fname)
            if fld.options.type == "int":
                fld.set_value(int(rng.integers(0, 2 * SHARD_WIDTH)),
                              int(rng.integers(-50, 50)))
                continue
            row = int(rng.integers(0, 5))
            roll = rng.integers(0, 10)
            if roll < 6 or not set_cols:
                col = int(rng.integers(0, 2 * SHARD_WIDTH))
                fld.set_bit(row, col)
                set_cols.append((fname, row, col))
            elif roll < 9:
                f2, r2, c2 = set_cols.pop(int(rng.integers(0, len(set_cols))))
                idx.field(f2).clear_bit(r2, c2)
            else:  # bulk import: not delta-coverable
                cols = np.unique(
                    rng.integers(0, 2 * SHARD_WIDTH, 50, dtype=np.uint64)
                )
                fld.import_bits(
                    np.full(cols.size, row, dtype=np.uint64), cols
                )
        if k % 3 == 0:
            q = "".join(f"Count({gen.bitmap()})" for _ in range(4))
        else:
            q = gen.query()
        want = [result_to_json(r) for r in host.execute("qg", q)]
        got = [result_to_json(r) for r in dev.execute("qg", q)]
        assert got == want, f"seed={seed} step#{k}: {q}"


def test_generated_multi_count_batches(holder):
    """Batched serving path: whole multi-Count requests of generated
    bitmaps must match the oracle call-for-call (exercises the pair-plan
    detection + generic scan grouping under arbitrary shapes)."""
    rng = np.random.default_rng(77)
    build_schema(holder, rng, shards=2)
    host = Executor(holder)
    dev = Executor(holder, backend=TPUBackend(holder))
    gen = QueryGenerator(7)
    for _ in range(4):
        q = "".join(f"Count({gen.bitmap()})" for _ in range(8))
        assert dev.execute("qg", q) == host.execute("qg", q), q
