"""Native C++ helpers + file-handle budget.

The pack hot loop (array-container scatter) runs in C++ when the lazily
built library is present; outputs must be bit-identical to the numpy
fallback. The WAL fd budget (reference syswrap/os.go:30-60) must evict
handles over the limit and transparently reopen on the next write.
"""

import os

import numpy as np
import pytest

from pilosa_tpu.native import has_native, scatter_positions
from pilosa_tpu.ops.blocks import WORDS_PER_SHARD, pack_fragment
from pilosa_tpu.utils import syswrap


class TestNativeScatter:
    def test_scatter_matches_numpy(self, rng):
        if not has_native():
            pytest.skip("no C++ toolchain")
        for n in (1, 5, 4096):
            pos = np.unique(rng.integers(0, 1 << 16, n, dtype=np.uint16))
            native = np.zeros(2048 + 64, dtype=np.uint32)
            assert scatter_positions(native, 64, pos)
            fallback = np.zeros(2048 + 64, dtype=np.uint32)
            p32 = pos.astype(np.uint32)
            np.bitwise_or.at(
                fallback, 64 + (p32 >> 5), np.uint32(1) << (p32 & np.uint32(31))
            )
            np.testing.assert_array_equal(native, fallback)

    def test_pack_fragment_uses_scatter(self, rng, tmp_path):
        """End-to-end: a packed fragment round-trips to the same columns."""
        from pilosa_tpu.core import Holder
        from pilosa_tpu.ops.blocks import unpack_row

        h = Holder(str(tmp_path / "h")).open()
        idx = h.create_index("i")
        f = idx.create_field("f")
        cols = np.unique(rng.integers(0, 1 << 16, 500, dtype=np.uint64))
        f.import_bits(np.zeros(cols.size, dtype=np.uint64), cols)
        frag = f.view("standard").fragment(0)
        words = pack_fragment(frag)
        np.testing.assert_array_equal(unpack_row(words[0]), cols)
        h.close()


class TestFileBudget:
    def test_wal_handles_evicted_and_reopen(self, tmp_path):
        from pilosa_tpu.core.fragment import Fragment

        old = syswrap.stats().get("open_files", 0)
        try:
            syswrap.set_max_file_count(old + 3)
            frags = []
            for i in range(8):
                fr = Fragment(str(tmp_path / f"frag{i}"), "i", "f", "standard", i).open()
                fr.set_bit(1, 5)  # opens + registers the WAL fd
                frags.append(fr)
            st = syswrap.stats()
            assert st["open_files"] <= old + 3
            assert st["file_evictions"] >= 5
            # An evicted fragment's next write transparently reopens.
            assert frags[0].set_bit(2, 6)
            # ... and the record survives a reopen from disk.
            frags[0].close()
            back = Fragment(str(tmp_path / "frag0"), "i", "f", "standard", 0).open()
            assert back.row_count(1) == 1 and back.row_count(2) == 1
            back.close()
            for fr in frags[1:]:
                fr.close()
        finally:
            syswrap.set_max_file_count(syswrap.DEFAULT_MAX_FILE_COUNT)


class TestImportContainers:
    """Native container-granular import (VERDICT r3 #6) must byte-match
    the numpy comparison-sort path."""

    def test_differential_vs_add_many(self, rng):
        import numpy as np

        from pilosa_tpu import native
        from pilosa_tpu.roaring import Bitmap
        from pilosa_tpu.shardwidth import SHARD_WIDTH, SHARD_WIDTH_EXP

        if not native.has_native():
            import pytest

            pytest.skip("no native toolchain")
        rows = rng.integers(0, 64, 30_000, dtype=np.uint64)
        cols = rng.integers(0, SHARD_WIDTH, 30_000, dtype=np.uint64)
        # include duplicates + a dense run (bitmap container)
        rows = np.concatenate([rows, np.zeros(9000, dtype=np.uint64)])
        cols = np.concatenate([cols, np.arange(9000, dtype=np.uint64)])
        groups = native.import_containers(rows, cols, SHARD_WIDTH_EXP)
        assert groups is not None
        keys, counts, lows = groups
        got = Bitmap()
        changed = got.import_container_groups(keys, counts, lows)
        want = Bitmap()
        want_changed = want.add_many(
            rows * np.uint64(SHARD_WIDTH) + (cols % np.uint64(SHARD_WIDTH))
        )
        assert changed == want_changed
        np.testing.assert_array_equal(got.to_array(), want.to_array())
        # Merging into EXISTING containers (second import overlaps).
        groups2 = native.import_containers(rows[:5000], cols[:5000] + np.uint64(7), SHARD_WIDTH_EXP)
        keys2, counts2, lows2 = groups2
        c2 = got.import_container_groups(keys2, counts2, lows2)
        w2 = want.add_many(
            rows[:5000] * np.uint64(SHARD_WIDTH)
            + ((cols[:5000] + np.uint64(7)) % np.uint64(SHARD_WIDTH))
        )
        assert c2 == w2
        np.testing.assert_array_equal(got.to_array(), want.to_array())

    def test_tall_rows_fall_back(self, rng):
        import numpy as np

        from pilosa_tpu import native
        from pilosa_tpu.shardwidth import SHARD_WIDTH_EXP

        if not native.has_native():
            import pytest

            pytest.skip("no native toolchain")
        rows = np.array([1 << 40], dtype=np.uint64)  # key above key_cap
        cols = np.array([3], dtype=np.uint64)
        assert native.import_containers(rows, cols, SHARD_WIDTH_EXP) is None
