"""Round-9 data-plane resilience (ISSUE r9): end-to-end deadlines,
per-peer circuit breaker, hedged shard reads, bounded idempotent-GET
retries, machine-readable error codes, loud-write invariants, and the
FaultProxy fault modes that exercise them — all in the in-process
2-node harness, bounded-timeout (tier-1, `chaos` marked where faults
are injected)."""

import json
import socket
import time
import urllib.error
import urllib.request

import pytest

from pilosa_tpu.cluster.breaker import (
    STATE_CLOSED,
    STATE_HALF_OPEN,
    STATE_OPEN,
    BreakerRegistry,
)
from pilosa_tpu.cluster.client import ClientError, InternalClient, peer_label
from pilosa_tpu.cluster.topology import NODE_STATE_DOWN, NODE_STATE_READY
from pilosa_tpu.core.view import VIEW_STANDARD
from pilosa_tpu.shardwidth import SHARD_WIDTH
from pilosa_tpu.utils.deadline import (
    Deadline,
    DeadlineExceeded,
    check_deadline,
    current_deadline,
    deadline_scope,
)
from pilosa_tpu.utils.stats import global_stats
from tests.cluster_harness import FaultProxy, RewriteClient, TestCluster


def _counter(name_prefix: str) -> float:
    snap = global_stats.snapshot()["counters"]
    return sum(v for k, v in snap.items() if k.startswith(name_prefix))


def _gauge(series: str):
    return global_stats.snapshot()["gauges"].get(series)


def _http_query(cn, index: str, pql: str, params: str = ""):
    """POST through the real HTTP surface (the deadline scope and the
    structured-error envelope live there, not in api.query). Returns
    (status, headers, body-dict) for success AND error responses."""
    url = f"http://127.0.0.1:{cn.server.port}/index/{index}/query{params}"
    req = urllib.request.Request(url, data=pql.encode(), method="POST")
    req.add_header("Content-Type", "text/plain")
    try:
        with urllib.request.urlopen(req, timeout=30) as resp:
            return resp.status, dict(resp.headers), json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), json.loads(e.read())


def _frag(cn, index, field, shard):
    v = cn.holder.index(index).field(field).view(VIEW_STANDARD)
    return v.fragment(shard) if v is not None else None


def _proxied(tc, i: int, j: int, timeout: float = 5.0) -> FaultProxy:
    """Route node i's outbound to node j through a fresh FaultProxy
    (asymmetric: every other direction stays direct)."""
    target = tc[j].node.uri
    proxy = FaultProxy(target.host, target.port)
    rc = RewriteClient(
        {f"{target.host}:{target.port}": f"127.0.0.1:{proxy.port}"},
        timeout=timeout,
    )
    tc[i].cluster.client = rc
    tc[i].cluster.broadcaster.client = rc
    return proxy


def _shards_by_primary(tc, index: str, node_id: str, upto: int = 16):
    topo = tc[0].cluster.topology
    return [
        s for s in range(upto)
        if topo.shard_nodes(index, s)[0].id == node_id
    ]


# ---------------------------------------------------------------------------
# Deadline unit semantics
# ---------------------------------------------------------------------------


class TestDeadline:
    def test_parse_rejects_garbage_and_nonpositive(self):
        with pytest.raises(ValueError):
            Deadline.parse("soon")
        with pytest.raises(ValueError):
            Deadline.parse("0")
        with pytest.raises(ValueError):
            Deadline.parse("-3")
        with pytest.raises(ValueError):
            # NaN satisfies neither <= 0 nor > 0: must 400, not produce
            # a budget whose every check() raises (review r9).
            Deadline.parse("nan")
        assert Deadline.parse("2").budget == 2.0

    def test_parse_caps_abusive_budgets(self):
        from pilosa_tpu.utils.deadline import MAX_TIMEOUT

        assert Deadline.parse("999999999").budget == MAX_TIMEOUT

    def test_check_counts_phase_on_expiry(self):
        before = _counter("deadline_exceeded_total")
        d = Deadline(0.001)
        time.sleep(0.005)
        with pytest.raises(DeadlineExceeded) as ei:
            d.check("gather")
        assert ei.value.phase == "gather"
        snap = global_stats.snapshot()["counters"]
        assert snap.get('deadline_exceeded_total{phase="gather"}', 0) >= 1
        assert _counter("deadline_exceeded_total") == before + 1

    def test_bound_clamps_to_remaining_with_floor(self):
        d = Deadline(0.5)
        assert d.bound(30.0) <= 0.5
        time.sleep(0.01)
        assert d.bound(30.0) > 0  # never 0: stdlib reads 0 as non-blocking
        expired = Deadline(0.001)
        time.sleep(0.005)
        assert expired.bound(30.0) == pytest.approx(0.001)

    def test_scope_keeps_tighter_deadline(self):
        tight = Deadline(0.2)
        loose = Deadline(60.0)
        with deadline_scope(tight):
            with deadline_scope(loose):
                # An inner layer must not LOOSEN the request budget.
                assert current_deadline() is tight
            assert current_deadline() is tight
        assert current_deadline() is None

    def test_scope_none_is_no_budget(self):
        with deadline_scope(None):
            assert current_deadline() is None
            check_deadline("parse")  # no-op, must not raise

    def test_header_value_subtracts_skew_margin(self):
        from pilosa_tpu.utils.deadline import SKEW_MARGIN

        d = Deadline(1.0)
        assert float(d.header_value()) <= 1.0 - SKEW_MARGIN + 0.01


# ---------------------------------------------------------------------------
# Breaker unit semantics
# ---------------------------------------------------------------------------


class TestBreaker:
    def test_threshold_consecutive_failures_open(self):
        reg = BreakerRegistry(threshold=3, cooldown=10.0)
        reg.record_failure("p:1")
        reg.record_failure("p:1")
        assert reg.state("p:1") == STATE_CLOSED
        assert not reg.is_blocked("p:1")
        reg.record_failure("p:1")
        assert reg.state("p:1") == STATE_OPEN
        assert reg.is_blocked("p:1")

    def test_success_resets_consecutive_count(self):
        reg = BreakerRegistry(threshold=2, cooldown=10.0)
        reg.record_failure("p:1")
        reg.record_success("p:1")  # not CONSECUTIVE anymore
        reg.record_failure("p:1")
        assert reg.state("p:1") == STATE_CLOSED

    def test_cooldown_relaxes_to_half_open_then_closes(self):
        reg = BreakerRegistry(threshold=1, cooldown=0.02, max_cooldown=0.02)
        reg.record_failure("p:1")
        assert reg.is_blocked("p:1")
        deadline = time.time() + 2
        while reg.is_blocked("p:1") and time.time() < deadline:
            time.sleep(0.005)
        assert reg.state("p:1") == STATE_HALF_OPEN
        reg.record_success("p:1")  # the probe RPC succeeded
        assert reg.state("p:1") == STATE_CLOSED
        assert not reg.is_blocked("p:1")

    def test_half_open_probe_failure_reopens_with_doubled_cooldown(
        self, monkeypatch
    ):
        # Pin the jitter factor at 1.0 so the doubling is observable
        # directly (the production windows overlap at their extremes).
        import pilosa_tpu.cluster.breaker as brk

        monkeypatch.setattr(brk.random, "random", lambda: 0.5)
        reg = BreakerRegistry(threshold=1, cooldown=0.02, max_cooldown=60.0)
        reg.record_failure("p:1")
        b = reg._peers["p:1"]
        first_cool = b.open_until - time.monotonic()
        b.open_until = 0.0  # force cooldown expiry
        assert not reg.is_blocked("p:1")  # relaxed to half-open
        reg.record_failure("p:1")  # the probe failed
        assert reg.state("p:1") == STATE_OPEN
        second_cool = b.open_until - time.monotonic()
        assert second_cool > first_cool
        assert second_cool == pytest.approx(0.04, abs=0.01)
        assert b.reopen_count == 2

    def test_state_gauge_and_transition_counters(self):
        before = _counter("peer_breaker_transitions_total")
        reg = BreakerRegistry(threshold=1, cooldown=30.0)
        reg.record_failure("gauge-peer:9")
        assert _gauge('peer_breaker_state{peer="gauge-peer:9"}') == 2
        reg.record_success("gauge-peer:9")
        assert _gauge('peer_breaker_state{peer="gauge-peer:9"}') == 0
        snap = global_stats.snapshot()["counters"]
        assert snap.get(
            'peer_breaker_transitions_total{peer="gauge-peer:9",to="open"}', 0
        ) >= 1
        assert snap.get(
            'peer_breaker_transitions_total{peer="gauge-peer:9",to="closed"}', 0
        ) >= 1
        assert _counter("peer_breaker_transitions_total") == before + 2


# ---------------------------------------------------------------------------
# Client: bounded idempotent-GET retries
# ---------------------------------------------------------------------------


@pytest.mark.chaos
class TestClientRetries:
    def test_get_retries_transient_reset_and_succeeds(self):
        with TestCluster(1) as tc:
            target = tc[0].node.uri
            proxy = FaultProxy(target.host, target.port)
            try:
                client = InternalClient(timeout=2.0, retries=1)
                uri = f"http://127.0.0.1:{proxy.port}"
                before = _counter("peer_rpc_retries_total")
                proxy.mode = "reset_once"  # kills exactly one connection
                out = client.status(uri)
                assert isinstance(out, dict) and out
                assert _counter("peer_rpc_retries_total") == before + 1
            finally:
                proxy.close()

    def test_post_is_never_retried(self):
        # reset_once auto-reverts to pass: if the POST retried, the retry
        # would SUCCEED — so a raised error proves no second attempt.
        with TestCluster(1) as tc:
            target = tc[0].node.uri
            proxy = FaultProxy(target.host, target.port)
            try:
                client = InternalClient(timeout=2.0, retries=3)
                proxy.mode = "reset_once"
                with pytest.raises(ClientError) as ei:
                    client.send_message(
                        f"http://127.0.0.1:{proxy.port}", b"{}"
                    )
                assert ei.value.transport
            finally:
                proxy.close()

    def test_nearly_spent_deadline_preempts_retry(self):
        client = InternalClient(timeout=1.0, retries=3)
        before = _counter("peer_rpc_retries_total")
        with deadline_scope(Deadline(0.03)):
            with pytest.raises(ClientError):
                client.status("http://127.0.0.1:1")  # nothing listens
        # The remaining budget could not cover a backoff sleep + dial:
        # no retry was attempted.
        assert _counter("peer_rpc_retries_total") == before

    def test_drop_mode_raises_transport_error(self):
        with TestCluster(1) as tc:
            target = tc[0].node.uri
            proxy = FaultProxy(target.host, target.port)
            try:
                proxy.drop_p = 1.0
                proxy.mode = "drop"
                client = InternalClient(timeout=1.0, retries=1)
                with pytest.raises(ClientError) as ei:
                    client.status(f"http://127.0.0.1:{proxy.port}")
                assert ei.value.transport
                proxy.drop_p = 0.0  # p=0 passes everything
                assert client.status(f"http://127.0.0.1:{proxy.port}")
            finally:
                proxy.close()


# ---------------------------------------------------------------------------
# FaultProxy hygiene (satellite: fd-leak regression)
# ---------------------------------------------------------------------------


class TestFaultProxyHygiene:
    def test_close_reaps_piped_connections(self):
        listener = socket.socket()
        listener.bind(("127.0.0.1", 0))
        listener.listen(4)
        proxy = FaultProxy("127.0.0.1", listener.getsockname()[1])
        conn = socket.create_connection(("127.0.0.1", proxy.port), timeout=2)
        conn.sendall(b"hello")
        upstream, _ = listener.accept()
        assert upstream.recv(5) == b"hello"  # the pipe is live
        proxy.close()
        # close() must join the accept loop and tear down the piped
        # sockets itself — the old close left them to the peers' whim.
        assert not proxy._thread.is_alive()
        deadline = time.time() + 2
        while proxy._conns and time.time() < deadline:
            time.sleep(0.01)
        assert not proxy._conns
        # The far ends observe the teardown promptly.
        upstream.settimeout(2)
        assert upstream.recv(100) == b""
        conn.close()
        upstream.close()
        listener.close()

    def test_close_unblocks_blackholed_connection(self):
        listener = socket.socket()
        listener.bind(("127.0.0.1", 0))
        listener.listen(4)
        proxy = FaultProxy("127.0.0.1", listener.getsockname()[1])
        proxy.mode = "blackhole"
        conn = socket.create_connection(("127.0.0.1", proxy.port), timeout=2)
        conn.sendall(b"GET / HTTP/1.1\r\n\r\n")
        time.sleep(0.05)  # let _serve enter its blackhole loop
        t0 = time.time()
        proxy.close()
        assert time.time() - t0 < 3  # join did not hang on the blackhole
        assert not proxy._thread.is_alive()
        conn.close()
        listener.close()

    def test_proxy_cycling_does_not_leak_fds(self):
        import os

        listener = socket.socket()
        listener.bind(("127.0.0.1", 0))
        listener.listen(16)
        port = listener.getsockname()[1]
        accepted = []

        def cycle():
            proxy = FaultProxy("127.0.0.1", port)
            c = socket.create_connection(("127.0.0.1", proxy.port), timeout=2)
            c.sendall(b"x")
            up, _ = listener.accept()
            accepted.append(up)
            up.recv(1)
            proxy.close()
            c.close()
            up.close()

        cycle()  # warm allocators/thread stacks before measuring
        base = len(os.listdir("/proc/self/fd"))
        for _ in range(10):
            cycle()
        time.sleep(0.2)
        grown = len(os.listdir("/proc/self/fd")) - base
        # Pre-fix each cycle leaked 2 established sockets (proxy-side
        # conn + upstream) = 20 fds over 10 cycles; allow unrelated noise.
        assert grown <= 6, grown
        listener.close()


# ---------------------------------------------------------------------------
# Structured error codes + Retry-After (satellite)
# ---------------------------------------------------------------------------


class TestErrorCodes:
    def test_every_error_body_carries_a_code(self):
        with TestCluster(1) as tc:
            port = tc[0].server.port
            # 404 from a route that predates structured codes.
            try:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/index/nope", timeout=10
                )
                raise AssertionError("expected 404")
            except urllib.error.HTTPError as e:
                assert e.code == 404
                assert json.loads(e.read())["code"] == "not-found"
            # 400 from a garbage ?timeout=.
            status, _, body = _http_query(
                tc[0], "nope", "Count(Row(f=1))", "?timeout=never"
            )
            assert status == 400
            assert body["code"] == "bad-request"

    def test_deadline_exceeded_is_504_with_retry_after(self):
        with TestCluster(1) as tc:
            tc.create_index("i")
            tc.create_field("i", "f")
            tc.query(0, "i", "Set(1, f=1)")
            # A 1 µs budget is always spent by the first phase check.
            status, headers, body = _http_query(
                tc[0], "i", "Count(Row(f=1))", "?timeout=0.000001"
            )
            assert status == 504
            assert body["code"] == "deadline-exceeded"
            assert headers.get("Retry-After") == "1"

    def test_generous_timeout_serves_normally(self):
        with TestCluster(2, replica_n=2) as tc:
            tc.create_index("i")
            tc.create_field("i", "f")
            cols = [s * SHARD_WIDTH + 3 for s in range(4)]
            tc.query(0, "i", " ".join(f"Set({c}, f=1)" for c in cols))
            status, _, body = _http_query(
                tc[0], "i", "Count(Row(f=1))", "?timeout=30"
            )
            assert status == 200
            assert body["results"][0] == len(cols)


# ---------------------------------------------------------------------------
# Loud-write invariant (satellite): no live replica => structured failure
# ---------------------------------------------------------------------------


class TestLoudWriteInvariant:
    def test_route_write_all_replicas_down_is_structured_503(self):
        with TestCluster(3, replica_n=1) as tc:
            tc.create_index("i")
            tc.create_field("i", "f")
            topo = tc[0].cluster.topology
            shard = next(
                s for s in range(64)
                if topo.shard_nodes("i", s)[0].id == "node2"
            )
            topo.node_by_id("node2").state = NODE_STATE_DOWN
            before = _counter("write_replica_unavailable_total")
            status, headers, body = _http_query(
                tc[0], "i", f"Set({shard * SHARD_WIDTH + 1}, f=1)"
            )
            assert status == 503
            assert body["code"] == "replicas-unavailable"
            assert headers.get("Retry-After") == "1"
            assert _counter("write_replica_unavailable_total") == before + 1

    def test_route_write_shards_all_replicas_down_is_loud(self):
        with TestCluster(3, replica_n=1) as tc:
            tc.create_index("i")
            tc.create_field("i", "f")
            topo = tc[0].cluster.topology
            mine = _shards_by_primary(tc, "i", "node0", 64)[0]
            theirs = next(
                s for s in range(64)
                if topo.shard_nodes("i", s)[0].id == "node2"
            )
            tc.query(0, "i", f"Set({mine * SHARD_WIDTH + 1}, f=2)")
            tc.query(0, "i", f"Set({theirs * SHARD_WIDTH + 1}, f=2)")
            topo.node_by_id("node2").state = NODE_STATE_DOWN
            before = _counter("write_replica_unavailable_total")
            # Multi-shard replicated write (ClearRow): one of its shards
            # has zero live replicas -> the WHOLE write fails loudly.
            status, _, body = _http_query(tc[0], "i", "ClearRow(f=2)")
            assert status == 503
            assert body["code"] == "replicas-unavailable"
            assert _counter("write_replica_unavailable_total") == before + 1

    def test_open_breaker_counts_as_down_for_writes(self):
        with TestCluster(2, replica_n=1) as tc:
            tc.create_index("i")
            tc.create_field("i", "f")
            shard = _shards_by_primary(tc, "i", "node1", 64)[0]
            peer = peer_label(tc[1].node.uri)
            breakers = tc[0].cluster.client.breakers
            for _ in range(breakers.threshold):
                breakers.record_failure(peer)
            assert breakers.is_blocked(peer)
            # node1 is READY in the topology — only its breaker is open —
            # yet the sole-replica write must still fail loudly rather
            # than eat a timeout or silently drop.
            status, _, body = _http_query(
                tc[0], "i", f"Set({shard * SHARD_WIDTH + 1}, f=1)"
            )
            assert status == 503
            assert body["code"] == "replicas-unavailable"

    def test_skipped_down_replica_write_lands_and_repairs(self):
        with TestCluster(2, replica_n=2) as tc:
            tc.create_index("i")
            tc.create_field("i", "f")
            tc.query(0, "i", "Set(1, f=1)")  # shard 0 exists everywhere
            tc.await_shard_convergence("i")
            topo = tc[0].cluster.topology
            topo.node_by_id("node1").state = NODE_STATE_DOWN
            col = 7
            out = tc.query(0, "i", f"Set({col}, f=1)")
            assert out["results"][0] is True  # landed on the live replica
            assert _frag(tc[0], "i", "f", 0).row(1).includes_column(col)
            assert not _frag(tc[1], "i", "f", 0).row(1).includes_column(col)
            # The replica returns: anti-entropy repairs the skipped write.
            topo.node_by_id("node1").state = NODE_STATE_READY
            tc.sync_all()
            assert _frag(tc[1], "i", "f", 0).row(1).includes_column(col)


# ---------------------------------------------------------------------------
# Chaos acceptance: breaker + hedge + deadline in the 2-node harness
# ---------------------------------------------------------------------------


@pytest.mark.chaos
class TestChaosAcceptance:
    def _load(self, tc):
        """Populate two shards primaried on EACH node (deterministic via
        the shared hasher), so every fan-out from node0 has a remote leg
        to node1 — the leg the faults are aimed at."""
        tc.create_index("i")
        tc.create_field("i", "f")
        shards = (
            _shards_by_primary(tc, "i", "node0", 64)[:2]
            + _shards_by_primary(tc, "i", "node1", 64)[:2]
        )
        assert len(shards) == 4
        cols = [s * SHARD_WIDTH + 5 for s in shards]
        tc.query(0, "i", " ".join(f"Set({c}, f=1)" for c in cols))
        tc.await_shard_convergence("i")
        return cols

    def test_blackholed_replica_hedge_completes_within_deadline(self):
        with TestCluster(2, replica_n=2) as tc:
            cols = self._load(tc)
            proxy = _proxied(tc, 0, 1, timeout=5.0)
            tc[0].cluster.hedge_delay = 0.2
            try:
                proxy.mode = "blackhole"
                before = _counter("hedged_requests_total")
                t0 = time.time()
                status, _, body = _http_query(
                    tc[0], "i", "Count(Row(f=1))", "?timeout=2"
                )
                elapsed = time.time() - t0
                # Correct, NON-partial result, inside the budget: the
                # straggler leg was re-launched at the local replica.
                assert status == 200
                assert body["results"][0] == len(cols)
                assert elapsed < 2.0, elapsed
                snap = global_stats.snapshot()["counters"]
                assert snap.get(
                    'hedged_requests_total{won="hedge"}', 0
                ) >= 1
                assert _counter("hedged_requests_total") > before
            finally:
                proxy.close()

    def test_breaker_opens_and_routes_around_dead_peer(self):
        with TestCluster(2, replica_n=2) as tc:
            cols = self._load(tc)
            proxy = _proxied(tc, 0, 1, timeout=5.0)
            peer = peer_label(tc[1].node.uri)
            breakers = tc[0].cluster.client.breakers
            try:
                proxy.mode = "refuse"
                # Each query's node1 leg fails instantly and re-splits to
                # the local replica — queries keep answering while the
                # consecutive failures accumulate to the threshold.
                for _ in range(breakers.threshold):
                    _, _, body = _http_query(tc[0], "i", "Count(Row(f=1))")
                    assert body["results"][0] == len(cols)
                assert breakers.state(peer) == STATE_OPEN
                assert _gauge(f'peer_breaker_state{{peer="{peer}"}}') == 2
                # With the breaker open the peer is skipped up front:
                # the query never pays a dial, so it is fast AND correct.
                t0 = time.time()
                status, _, body = _http_query(
                    tc[0], "i", "Count(Row(f=1))", "?timeout=2"
                )
                assert status == 200
                assert body["results"][0] == len(cols)
                assert time.time() - t0 < 1.0
            finally:
                proxy.close()

    def test_breaker_half_open_probe_recovers(self):
        with TestCluster(2, replica_n=2) as tc:
            cols = self._load(tc)
            proxy = _proxied(tc, 0, 1, timeout=5.0)
            rc = tc[0].cluster.client
            rc.breakers = BreakerRegistry(
                threshold=1, cooldown=0.05, max_cooldown=0.05
            )
            peer = peer_label(tc[1].node.uri)
            try:
                proxy.mode = "refuse"
                _http_query(tc[0], "i", "Count(Row(f=1))")
                assert rc.breakers.state(peer) == STATE_OPEN
                # Heal the link; the jittered cooldown (≤ 75 ms) relaxes
                # the breaker to HALF_OPEN, the next query is the probe,
                # and its success closes the breaker.
                proxy.mode = "pass"
                deadline = time.time() + 2
                while rc.breakers.is_blocked(peer) and time.time() < deadline:
                    time.sleep(0.01)
                assert rc.breakers.state(peer) == STATE_HALF_OPEN
                _, _, body = _http_query(tc[0], "i", "Count(Row(f=1))")
                assert body["results"][0] == len(cols)
                assert rc.breakers.state(peer) == STATE_CLOSED
            finally:
                proxy.close()

    def test_remote_node_observes_propagated_deadline_and_aborts(self):
        with TestCluster(2, replica_n=1) as tc:
            tc.create_index("i")
            tc.create_field("i", "f")
            shard = _shards_by_primary(tc, "i", "node1", 64)[0]
            tc.query(0, "i", f"Set({shard * SHARD_WIDTH + 1}, f=1)")
            # Slow down node1's per-call execution past the propagated
            # budget: the FIRST call overruns, and the phase check at the
            # SECOND call's boundary must abort the leg remotely.
            orig = tc[1].executor.execute_call

            def slow(index, call, shards, opt):
                time.sleep(0.4)
                return orig(index, call, shards, opt)

            tc[1].executor.execute_call = slow
            snap0 = global_stats.snapshot()["counters"]

            def remote_aborts() -> float:
                # The coordinator's own expiries land on gather/peer_rpc;
                # these phases can only have fired on the REMOTE node,
                # inside the scope it opened from X-Pilosa-Deadline.
                snap = global_stats.snapshot()["counters"]
                return sum(
                    snap.get(f'deadline_exceeded_total{{phase="{p}"}}', 0)
                    - snap0.get(f'deadline_exceeded_total{{phase="{p}"}}', 0)
                    for p in ("parse", "plan", "device_dispatch", "serialize")
                )

            status, _, body = _http_query(
                tc[0], "i", "Row(f=1) Row(f=1)", "?timeout=0.3"
            )
            assert status in (502, 504)
            assert body["code"] in ("deadline-exceeded", "peer-error")
            # The remote aborted at an EXECUTOR phase boundary rather than
            # completing abandoned work; its leg outlives the
            # coordinator's 504 by ~the overrun, so poll.
            deadline = time.time() + 3
            while remote_aborts() < 1 and time.time() < deadline:
                time.sleep(0.02)
            assert remote_aborts() >= 1

    def test_gather_wait_is_budget_derived(self):
        """A blackholed sole-owner leg with no hedge/replica escape must
        surface as deadline-exceeded WITHIN the budget — not after the
        old flat client.timeout + 30 gather wait."""
        with TestCluster(2, replica_n=1) as tc:
            tc.create_index("i")
            tc.create_field("i", "f")
            shard = _shards_by_primary(tc, "i", "node1", 64)[0]
            tc.query(0, "i", f"Set({shard * SHARD_WIDTH + 1}, f=1)")
            proxy = _proxied(tc, 0, 1, timeout=30.0)
            try:
                proxy.mode = "blackhole"
                t0 = time.time()
                status, _, body = _http_query(
                    tc[0], "i", "Count(Row(f=1))", "?timeout=1"
                )
                elapsed = time.time() - t0
                assert status in (502, 504)
                assert elapsed < 5.0, elapsed
            finally:
                proxy.close()
