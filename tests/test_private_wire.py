"""Typed binary control plane (cluster/private_wire.py; reference
internal/private.proto + encoding/proto Serializer): every registered
message round-trips exactly, legacy JSON frames still decode, and the
live cluster bus (exercised by tests/test_cluster2.py end to end) rides
this wire."""

import json

import pytest

from pilosa_tpu.cluster.broadcast import Message
from pilosa_tpu.cluster.private_wire import (
    JSONSerializer,
    ProtoSerializer,
    WIRE_VERSION,
)

NODE = {
    "id": "node-a",
    "uri": {"scheme": "http", "host": "10.0.0.1", "port": 10101},
    "isCoordinator": True,
    "state": "READY",
}

SCHEMA = {
    "indexes": [
        {
            "name": "i1",
            "options": {"keys": True, "trackExistence": False},
            "fields": [
                {
                    "name": "f1",
                    "options": {
                        "type": "int",
                        "cacheType": "",
                        "cacheSize": 0,
                        "min": -100,
                        "max": 250,
                        "base": -3,
                        "bitDepth": 9,
                        "timeQuantum": "",
                        "keys": False,
                        "noStandardView": False,
                    },
                },
                {
                    "name": "f2",
                    "options": {
                        "type": "time",
                        "cacheType": "ranked",
                        "cacheSize": 50000,
                        "min": 0,
                        "max": 0,
                        "base": 0,
                        "bitDepth": 0,
                        "timeQuantum": "YMDH",
                        "keys": True,
                        "noStandardView": True,
                    },
                },
            ],
            "shardWidth": 1 << 20,
        }
    ]
}

MESSAGES = [
    Message.make("create-shard", index="i1", field="f1", shard=7),
    Message.make("delete-available-shard", index="i1", field="f1", shard=3),
    Message.make("cluster-status", state="NORMAL", nodes=[NODE], replicaN=2),
    Message.make("cluster-status", state="RESIZING"),
    Message.make("node-status", schema=SCHEMA,
                 available={"i1": {"f1": [0, 5, 9], "f2": []}}),
    Message.make("node-event", event="join", node=NODE,
                 status={"schema": SCHEMA, "available": {"i1": {"f1": [1]}}}),
    Message.make("node-event", event="join", node=NODE, status={},
                 forwarded=True),
    Message.make("node-state", id="node-b", state="DOWN"),
    Message.make(
        "resize-instruction",
        job=4,
        epoch=3,
        node="node-b",
        coordinator=NODE,
        schema=SCHEMA,
        available={"i1": {"f1": [0, 2]}},
        sources=[{"index": "i1", "field": "f1", "shard": 2,
                  "from": "http://10.0.0.1:10101", "alts": []},
                 {"index": "i1", "field": "f1", "shard": 5,
                  "from": "http://10.0.0.1:10101",
                  "alts": ["http://10.0.0.2:10101",
                           "http://10.0.0.3:10101"]}],
    ),
    Message.make("resize-complete", job=4, epoch=3, node="node-b"),
    Message.make("resize-complete", job=4, node="node-b", error="boom"),
    Message.make("resize-abort"),
    Message.make("set-coordinator", id="node-b"),
    Message.make("recalculate-caches"),
]


@pytest.mark.parametrize("msg", MESSAGES, ids=lambda m: m["type"])
def test_round_trip_binary(msg):
    s = ProtoSerializer()
    data = s.marshal(msg)
    assert data[0] != 0x7B  # binary frame, not JSON
    assert data[1] == WIRE_VERSION
    back = s.unmarshal(data)
    # Decoded fields must cover everything the receive path reads; defaults
    # may add keys, so compare per original key plus type.
    for k, v in msg.items():
        assert back[k] == v, (msg["type"], k, back.get(k), v)


def test_unregistered_type_falls_back_to_json():
    s = ProtoSerializer()
    m = Message.make("future-thing", payload={"x": 1})
    data = s.marshal(m)
    assert data[0] == 0x7B
    assert s.unmarshal(data) == m


def test_legacy_json_frame_decodes():
    s = ProtoSerializer()
    legacy = json.dumps(
        {"type": "cluster-status", "state": "NORMAL", "nodes": [NODE]}
    ).encode()
    back = s.unmarshal(legacy)
    assert back["state"] == "NORMAL" and back["nodes"] == [NODE]


def test_bad_frames_error_or_ignorable():
    s = ProtoSerializer()
    with pytest.raises(ValueError):
        s.unmarshal(b"")
    with pytest.raises(ValueError):
        s.unmarshal(bytes([0x01]))  # truncated header
    # Frames from a NEWER peer decode to an ignorable message so the
    # receive dispatch skips them (rolling-upgrade forward compat).
    assert s.unmarshal(bytes([0xEE, 1, 2, 3]))["type"].startswith("unknown-wire-")
    assert s.unmarshal(bytes([0x01, 99]))["type"].startswith("unknown-wire-")


def test_message_bytes_ride_the_proto_wire():
    m = Message.make("node-state", id="n1", state="DOWN")
    data = m.to_bytes()
    assert data[0] == 0x06
    assert Message.from_bytes(data) == {"type": "node-state", "id": "n1",
                                        "state": "DOWN"}


def test_json_serializer_swap():
    from pilosa_tpu.cluster import broadcast

    broadcast.set_serializer(JSONSerializer())
    try:
        m = Message.make("node-state", id="n1", state="DOWN")
        assert m.to_bytes()[0] == 0x7B
        assert Message.from_bytes(m.to_bytes()) == m
    finally:
        broadcast.set_serializer(None)
        broadcast._serializer()  # restore the default lazily
