"""Hardening long tail (VERDICT r2 #7 / missing #4-5):

- codec fuzz: deserialize/apply_ops over truncated and bit-flipped
  inputs must raise ValueError or parse cleanly — never crash with an
  unexpected exception type (reference roaring/fuzzer.go).
- naive differential: a dead-simple set-of-ints bitmap as the trusted
  reference for randomized op sequences (reference roaring/naive.go).
- paranoia leg: the roaring suite re-runs in a subprocess with
  PILOSA_TPU_PARANOIA=1 so the invariant checks actually execute
  (reference roaringparanoia build tag).
- subprocess cluster: three REAL server processes on real ports,
  SIGKILL one mid-load, queries must survive via replicas, and
  anti-entropy must heal the restarted node (reference
  internal/clustertests/cluster_test.go:68-92 with pumba).
"""

from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
import tempfile
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from pilosa_tpu.roaring import Bitmap
from pilosa_tpu.roaring.codec import apply_ops, deserialize, serialize

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _random_bitmap(rng, n=3000, spread=1 << 22) -> Bitmap:
    b = Bitmap()
    b.add_many(rng.integers(0, spread, n, dtype=np.uint64), log=False)
    return b


class TestCodecFuzz:
    def test_truncations_error_cleanly(self, rng):
        """Every truncation point either raises ValueError or (past the
        storage region, where the tail is op-log) parses to a bitmap —
        no IndexError/struct.error/segfault class escapes."""
        b = _random_bitmap(rng)
        data = serialize(b)
        want = b.count()
        points = sorted(set(rng.integers(0, len(data), 80).tolist()) | {0, 1, 7, 8})
        for cut in points:
            try:
                got = deserialize(data[:cut])
            except ValueError:
                continue
            # Parsed: must be a structurally sound bitmap.
            assert got.count() <= want

    def test_bitflips_error_or_parse(self, rng):
        b = _random_bitmap(rng)
        data = bytearray(serialize(b))
        for _ in range(300):
            pos = int(rng.integers(0, len(data)))
            bit = 1 << int(rng.integers(0, 8))
            corrupted = bytearray(data)
            corrupted[pos] ^= bit
            try:
                got = deserialize(bytes(corrupted))
                # Survived: exercise the result; must not blow up.
                got.count()
                got.to_array()
            except ValueError:
                pass

    def test_oplog_corruption_error_or_clean(self, rng):
        """apply_ops over random garbage after a valid snapshot must raise
        ValueError (checksum/shape) or apply cleanly."""
        b = _random_bitmap(rng, n=500)
        data = serialize(b)
        for _ in range(120):
            garbage = rng.integers(0, 256, int(rng.integers(1, 64)), dtype=np.uint8)
            blob = data + garbage.tobytes()
            fresh = deserialize(data)
            try:
                apply_ops(fresh, blob, len(data))
            except ValueError:
                pass

    def test_hostile_container_counts_bounded(self, rng):
        """Flipping header bytes (container counts/offsets) must never
        allocate unboundedly or hang — covered by running the flips over
        the header region specifically."""
        b = _random_bitmap(rng, n=100)
        data = bytearray(serialize(b))
        header = min(64, len(data))
        for pos in range(header):
            for bit in (0x01, 0x80):
                corrupted = bytearray(data)
                corrupted[pos] ^= bit
                try:
                    deserialize(bytes(corrupted))
                except ValueError:
                    pass


class NaiveBitmap:
    """Trusted reference: a plain Python set (reference roaring/naive.go)."""

    def __init__(self):
        self.s: set[int] = set()

    def add_many(self, vs):
        self.s.update(int(v) for v in vs)

    def remove_many(self, vs):
        self.s.difference_update(int(v) for v in vs)

    def count(self):
        return len(self.s)

    def count_range(self, lo, hi):
        return sum(1 for v in self.s if lo <= v < hi)

    def to_array(self):
        return np.array(sorted(self.s), dtype=np.uint64)


class TestNaiveDifferential:
    @pytest.mark.parametrize("seed", [3, 17, 99])
    def test_random_op_sequences(self, seed):
        rng = np.random.default_rng(seed)
        real, naive = Bitmap(), NaiveBitmap()
        other_real, other_naive = Bitmap(), NaiveBitmap()
        for vs in (rng.integers(0, 1 << 20, 4000, dtype=np.uint64),):
            other_real.add_many(vs)
            other_naive.add_many(vs)
        for step in range(40):
            op = int(rng.integers(0, 6))
            vs = rng.integers(0, 1 << 20, int(rng.integers(1, 800)), dtype=np.uint64)
            if op == 0:
                real.add_many(vs)
                naive.add_many(vs)
            elif op == 1:
                real.remove_many(vs)
                naive.remove_many(vs)
            elif op == 2:
                real = real.union(other_real)
                naive.s = naive.s | other_naive.s
            elif op == 3:
                real = real.intersect(other_real)
                naive.s = naive.s & other_naive.s
            elif op == 4:
                real = real.difference(other_real)
                naive.s = naive.s - other_naive.s
            else:
                real = real.xor(other_real)
                naive.s = naive.s ^ other_naive.s
            assert real.count() == naive.count(), (seed, step)
            lo, hi = sorted(rng.integers(0, 1 << 20, 2).tolist())
            assert real.count_range(lo, hi) == naive.count_range(lo, hi)
            # Serialize round trip preserves contents exactly.
            if step % 10 == 0:
                back = deserialize(serialize(real))
                np.testing.assert_array_equal(back.to_array(), naive.to_array())
        np.testing.assert_array_equal(real.to_array(), naive.to_array())


class TestParanoiaLeg:
    def test_roaring_suite_under_paranoia(self):
        """The invariant checks must actually run against the suite
        (VERDICT r2 weak #9: the flag existed with zero consumers)."""
        env = dict(os.environ, PILOSA_TPU_PARANOIA="1", PYTHONPATH=REPO)
        out = subprocess.run(
            [sys.executable, "-m", "pytest", "tests/test_roaring.py", "-q",
             "--no-header", "-x"],
            cwd=REPO,
            env=env,
            capture_output=True,
            text=True,
            timeout=300,
        )
        assert out.returncode == 0, out.stdout[-2000:] + out.stderr[-2000:]
        # Prove the flag was actually on in that interpreter.
        probe = subprocess.run(
            [sys.executable, "-c",
             "from pilosa_tpu.roaring.bitmap import PARANOIA; print(PARANOIA)"],
            cwd=REPO, env=env, capture_output=True, text=True, timeout=60,
        )
        assert probe.stdout.strip() == "True"


def _free_ports(n: int) -> list[int]:
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        ports.append(s.getsockname()[1])
        socks.append(s)
    for s in socks:
        s.close()
    return ports


def _req(port: int, method: str, path: str, body=None, timeout=10):
    data = body.encode() if isinstance(body, str) else (
        json.dumps(body).encode() if body is not None else None
    )
    r = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=data, method=method
    )
    with urllib.request.urlopen(r, timeout=timeout) as resp:
        raw = resp.read()
    return json.loads(raw) if raw else {}


class _ProcHarness:
    """Shared multi-process helpers (real servers, real ports)."""

    N = 3

    def _spawn(self, i, ports, tmp, extra=()):
        hosts = ",".join(f"http://127.0.0.1:{p}" for p in ports)
        env = dict(
            os.environ,
            PYTHONPATH=REPO,
            JAX_PLATFORMS="cpu",
            PILOSA_TPU_CLUSTER_HOSTS=hosts,
            PILOSA_TPU_CLUSTER_REPLICAS=str(self.N),
            PILOSA_TPU_ANTI_ENTROPY_INTERVAL="1",
        )
        return subprocess.Popen(
            [sys.executable, "-m", "pilosa_tpu.cli", "server",
             "-d", f"{tmp}/node{i}", "-b", f"127.0.0.1:{ports[i]}",
             "--executor", "cpu", *extra],
            env=env,
            stdout=subprocess.DEVNULL,
            stderr=subprocess.STDOUT,
            cwd=REPO,
        )

    def _wait_ready(self, port, timeout=30):
        deadline = time.time() + timeout
        while time.time() < deadline:
            try:
                _req(port, "GET", "/status", timeout=2)
                return
            except (urllib.error.URLError, OSError):
                time.sleep(0.2)
        raise TimeoutError(f"server on {port} never became ready")

    @staticmethod
    def _kill_all(procs) -> None:
        for p in procs.values():
            if p.poll() is None:
                p.kill()
                p.wait(timeout=10)


class TestSubprocessCluster(_ProcHarness):
    """Real processes, real sockets, real SIGKILL — catches the
    serialization/lifecycle classes an in-process harness can't
    (reference internal/clustertests)."""

    def test_sigkill_survival_and_heal(self):
        ports = _free_ports(self.N)
        tmp = tempfile.mkdtemp(prefix="pilosa-tpu-proctest-")
        procs = {}
        try:
            for i in range(self.N):
                procs[i] = self._spawn(i, ports, tmp)
            for p in ports:
                self._wait_ready(p)

            _req(ports[0], "POST", "/index/i", {})
            _req(ports[0], "POST", "/index/i/field/f", {})
            from pilosa_tpu.shardwidth import SHARD_WIDTH

            cols = [s * SHARD_WIDTH + 7 for s in range(4)]
            _req(ports[0], "POST", "/index/i/query",
                 " ".join(f"Set({c}, f=1)" for c in cols))
            out = _req(ports[0], "POST", "/index/i/query", "Count(Row(f=1))")
            assert out["results"][0] == len(cols)

            # SIGKILL a non-coordinator mid-flight; queries keep working
            # through replica retry.
            procs[2].send_signal(signal.SIGKILL)
            procs[2].wait(timeout=10)
            out = _req(ports[0], "POST", "/index/i/query", "Count(Row(f=1))",
                       timeout=30)
            assert out["results"][0] == len(cols)

            # Wait for the failure detector to mark the node DOWN, then
            # write — DOWN replicas are skipped, anti-entropy heals them.
            deadline = time.time() + 30
            while time.time() < deadline:
                st = _req(ports[0], "GET", "/status")
                dead = [n for n in st["nodes"] if n["state"] == "DOWN"]
                if dead:
                    break
                time.sleep(0.5)
            assert dead, "failure detector never marked the killed node DOWN"
            extra_col = 5 * SHARD_WIDTH + 11
            _req(ports[0], "POST", "/index/i/query", f"Set({extra_col}, f=1)")

            # Restart the killed node on the same port + data dir;
            # anti-entropy (interval=1s) must deliver the missed write.
            procs[2] = self._spawn(2, ports, tmp)
            self._wait_ready(ports[2])

            # Wait for the heal: node2's LOCAL fragment for the new shard
            # must appear (checked via the node-local blocks endpoint —
            # a cluster query would mask missing local data).
            extra_shard = extra_col // SHARD_WIDTH
            deadline = time.time() + 45
            healed = False
            while time.time() < deadline:
                try:
                    _req(ports[2], "GET",
                         f"/internal/fragment/blocks?index=i&field=f&shard={extra_shard}")
                    healed = True
                    break
                except urllib.error.HTTPError:
                    time.sleep(0.5)
            assert healed, "anti-entropy never created the missed fragment"

            # Kill everyone else: only node2's own healed copy can serve.
            for i in (0, 1):
                procs[i].send_signal(signal.SIGKILL)
                procs[i].wait(timeout=10)
            deadline = time.time() + 45
            got = None
            while time.time() < deadline:
                try:
                    out = _req(ports[2], "POST", "/index/i/query",
                               "Count(Row(f=1))", timeout=30)
                    got = out["results"][0]
                    if got == len(cols) + 1:
                        break
                except (urllib.error.URLError, OSError):
                    pass
                time.sleep(1.0)
            assert got == len(cols) + 1, f"anti-entropy never healed: {got}"
        finally:
            self._kill_all(procs)


class TestSentinelMode:
    def test_containers_frozen_under_paranoia(self):
        """Sentinel analog (reference roaringsentinel): under
        PILOSA_TPU_PARANOIA=1, in-place mutation of a shared container
        array raises instead of corrupting every structural sharer."""
        env = dict(os.environ, PILOSA_TPU_PARANOIA="1", PYTHONPATH=REPO)
        probe = subprocess.run(
            [sys.executable, "-c", (
                "import numpy as np\n"
                "from pilosa_tpu.roaring import Bitmap\n"
                "b = Bitmap([1, 2, 3])\n"
                "c = b.container(0)\n"
                "try:\n"
                "    c.data[0] = 99\n"
                "    print('MUTATED')\n"
                "except ValueError:\n"
                "    print('FROZEN')\n"
            )],
            cwd=REPO, env=env, capture_output=True, text=True, timeout=60,
        )
        assert probe.stdout.strip() == "FROZEN", probe.stdout + probe.stderr


class TestSubprocessJoin(_ProcHarness):
    """The REAL `server --join` path: a fourth process announces to a
    live cluster and becomes a serving member with no operator call."""

    def test_cli_join(self):
        ports = _free_ports(self.N + 1)
        tmp = tempfile.mkdtemp(prefix="pilosa-tpu-jointest-")
        procs = {}
        try:
            for i in range(self.N):
                procs[i] = self._spawn(i, ports[: self.N], tmp)
            for p in ports[: self.N]:
                self._wait_ready(p)
            _req(ports[0], "POST", "/index/i", {})
            _req(ports[0], "POST", "/index/i/field/f", {})
            from pilosa_tpu.shardwidth import SHARD_WIDTH

            cols = [s * SHARD_WIDTH + 3 for s in range(5)]
            _req(ports[0], "POST", "/index/i/query",
                 " ".join(f"Set({c}, f=1)" for c in cols))

            # Joiner: its own env (no static hosts), --join at the
            # coordinator.
            env = dict(
                os.environ,
                PYTHONPATH=REPO,
                JAX_PLATFORMS="cpu",
                PILOSA_TPU_ANTI_ENTROPY_INTERVAL="1",
            )
            for k in ("PILOSA_TPU_CLUSTER_HOSTS",
                      "PILOSA_TPU_CLUSTER_REPLICAS",
                      "PILOSA_TPU_CLUSTER_COORDINATOR"):
                env.pop(k, None)
            jp = ports[self.N]
            procs["join"] = subprocess.Popen(
                [sys.executable, "-m", "pilosa_tpu.cli", "server",
                 "-d", f"{tmp}/joiner", "-b", f"127.0.0.1:{jp}",
                 "--executor", "cpu", "--join", f"http://127.0.0.1:{ports[0]}"],
                env=env, stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT,
                cwd=REPO,
            )
            self._wait_ready(jp)
            # Wait until the joiner is a member of the full topology.
            deadline = time.time() + 45
            while time.time() < deadline:
                st = _req(jp, "GET", "/status")
                if len(st["nodes"]) == self.N + 1 and st["state"] == "NORMAL":
                    break
                time.sleep(0.5)
            assert len(st["nodes"]) == self.N + 1, st
            assert st["state"] == "NORMAL", st
            # The joiner answers queries with correct cluster-wide counts.
            out = _req(jp, "POST", "/index/i/query", "Count(Row(f=1))",
                       timeout=30)
            assert out["results"][0] == len(cols)
            # Every original node agrees on the new topology.
            for p in ports[: self.N]:
                st = _req(p, "GET", "/status")
                assert len(st["nodes"]) == self.N + 1, p
        finally:
            self._kill_all(procs)


class TestSigstopPartition(_ProcHarness):
    """Hung-but-connected peer (VERDICT r3 #7; the reference's pumba
    pause leg, internal/clustertests/cluster_test.go:68-92): SIGSTOP
    freezes a node WITHOUT killing its sockets, exercising the
    timeout/retry paths SIGKILL never touches."""

    def _spawn(self, i, ports, tmp, extra=()):
        # Short client timeout so hung-peer retries happen in test time.
        os.environ["PILOSA_TPU_CLIENT_TIMEOUT"] = "3"
        try:
            return super()._spawn(i, ports, tmp, extra)
        finally:
            del os.environ["PILOSA_TPU_CLIENT_TIMEOUT"]

    def test_sigstop_hang_then_heal(self):
        ports = _free_ports(self.N)
        tmp = tempfile.mkdtemp(prefix="pilosa-tpu-sigstop-")
        procs = {}
        try:
            for i in range(self.N):
                procs[i] = self._spawn(i, ports, tmp)
            for p in ports:
                self._wait_ready(p)
            _req(ports[0], "POST", "/index/i", {})
            _req(ports[0], "POST", "/index/i/field/f", {})
            from pilosa_tpu.shardwidth import SHARD_WIDTH

            cols = [s * SHARD_WIDTH + 3 for s in range(4)]
            _req(ports[0], "POST", "/index/i/query",
                 " ".join(f"Set({c}, f=1)" for c in cols))

            # Freeze node 2: connections to it now HANG (backlog), they
            # don't refuse.
            procs[2].send_signal(signal.SIGSTOP)
            try:
                # Query through a live node: must complete within the
                # client timeout + retry budget, not hang forever.
                t0 = time.time()
                out = _req(ports[0], "POST", "/index/i/query",
                           "Count(Row(f=1))", timeout=25)
                assert out["results"][0] == len(cols)
                assert time.time() - t0 < 20, "query took longer than timeout+retry"

                # The failure detector's probes time out too: the frozen
                # node is marked DOWN (then queries skip it proactively).
                deadline = time.time() + 60
                down = False
                while time.time() < deadline:
                    st = _req(ports[0], "GET", "/status", timeout=10)
                    if any(n["state"] == "DOWN" for n in st["nodes"]):
                        down = True
                        break
                    time.sleep(1.0)
                assert down, "frozen node never marked DOWN"
                out = _req(ports[0], "POST", "/index/i/query",
                           "Count(Row(f=1))", timeout=15)
                assert out["results"][0] == len(cols)
            finally:
                procs[2].send_signal(signal.SIGCONT)

            # After SIGCONT the node heals back to READY.
            deadline = time.time() + 60
            healed = False
            while time.time() < deadline:
                st = _req(ports[0], "GET", "/status", timeout=10)
                if all(n["state"] != "DOWN" for n in st["nodes"]):
                    healed = True
                    break
                time.sleep(1.0)
            assert healed, "node never recovered after SIGCONT"
        finally:
            self._kill_all(procs)


class TestCoordinatorFailoverSubprocess(_ProcHarness):
    """Kill the coordinator (real SIGKILL, real sockets): a survivor
    promotes itself deterministically and a NEW node can still join
    through it (VERDICT r3 #5; reference api.go:1193-1261)."""

    def _spawn(self, i, ports, tmp, extra=()):
        os.environ["PILOSA_TPU_CLIENT_TIMEOUT"] = "3"
        try:
            return super()._spawn(i, ports, tmp, extra)
        finally:
            del os.environ["PILOSA_TPU_CLIENT_TIMEOUT"]

    def test_kill_coordinator_promote_and_join(self):
        ports = _free_ports(self.N + 1)
        cluster_ports = ports[: self.N]
        join_port = ports[self.N]
        tmp = tempfile.mkdtemp(prefix="pilosa-tpu-failover-")
        procs = {}
        try:
            for i in range(self.N):
                procs[i] = self._spawn(i, cluster_ports, tmp)
            for p in cluster_ports:
                self._wait_ready(p)
            _req(cluster_ports[0], "POST", "/index/i", {})
            _req(cluster_ports[0], "POST", "/index/i/field/f", {})
            from pilosa_tpu.shardwidth import SHARD_WIDTH

            cols = [s * SHARD_WIDTH + 9 for s in range(3)]
            _req(cluster_ports[0], "POST", "/index/i/query",
                 " ".join(f"Set({c}, f=1)" for c in cols))

            st = _req(cluster_ports[0], "GET", "/status")
            coord_id = next(n["id"] for n in st["nodes"] if n["isCoordinator"])
            coord_i = next(
                i for i, p in enumerate(cluster_ports)
                if f"-{p}" in coord_id or coord_id.endswith(str(p))
            )
            survivors = [p for i, p in enumerate(cluster_ports) if i != coord_i]

            procs[coord_i].send_signal(signal.SIGKILL)
            procs[coord_i].wait(timeout=10)

            # A survivor promotes itself; every live node converges on the
            # same successor (broadcast or piggybacked view merge).
            deadline = time.time() + 90
            new_coord = None
            while time.time() < deadline:
                views = []
                for p in survivors:
                    try:
                        st = _req(p, "GET", "/status", timeout=10)
                        views.append(
                            next(
                                (n["id"] for n in st["nodes"] if n["isCoordinator"]),
                                None,
                            )
                        )
                    except (urllib.error.URLError, OSError):
                        views.append(None)
                if (
                    len(set(views)) == 1
                    and views[0] is not None
                    and views[0] != coord_id
                ):
                    new_coord = views[0]
                    break
                time.sleep(1.0)
            assert new_coord, f"no converged successor: {views}"

            # The promoted coordinator accepts a dynamic join.
            new_coord_port = next(
                p for p in survivors
                if f"-{p}" in new_coord or new_coord.endswith(str(p))
            )
            procs["joiner"] = self._spawn(
                self.N, cluster_ports + [join_port], tmp,
                extra=("--join", f"http://127.0.0.1:{new_coord_port}"),
            )
            # The joiner spawns with a topology of itself only; _spawn's
            # hosts env lists all ports but --join overrides membership.
            self._wait_ready(join_port)
            deadline = time.time() + 90
            joined = False
            while time.time() < deadline:
                try:
                    st = _req(join_port, "GET", "/status", timeout=10)
                    ids = [n["id"] for n in st["nodes"]]
                    # DEGRADED is the CORRECT steady state here: the dead
                    # old coordinator is still a (DOWN) member.
                    if len(ids) >= self.N + 1 and st["state"] in (
                        "NORMAL", "DEGRADED"
                    ):
                        joined = True
                        break
                except (urllib.error.URLError, OSError):
                    pass
                time.sleep(1.0)
            assert joined, "new node never joined the post-failover cluster"
            # And the new cluster still answers queries with full data.
            out = _req(join_port, "POST", "/index/i/query",
                       "Count(Row(f=1))", timeout=30)
            assert out["results"][0] == len(cols)
        finally:
            self._kill_all(procs)
