"""Replica consistency plane tests (ISSUE r15): per-block epoch
stamping + sidecar persistence, directed-replace semantics, read-path
divergence detection (hedge-race observation, bounded queue, targeted
repair), the /debug/consistency ledger, the SymmetricPartition chaos
primitive, and the anti-entropy-vs-resize skip."""

from __future__ import annotations

import json
import time
import urllib.request

import pytest

from pilosa_tpu.cluster.consistency import DivergenceMonitor
from pilosa_tpu.core.fragment import EPOCHS_EXT, Fragment
from pilosa_tpu.utils.stats import global_stats
from tests.cluster_harness import SymmetricPartition, TestCluster

VIEW_STANDARD = "standard"


def _counter(name: str) -> float:
    snap = global_stats.snapshot()["counters"]
    return sum(v for k, v in snap.items() if k.startswith(name))


def _frag(cn, index, field, shard):
    idx = cn.holder.index(index)
    f = idx.field(field) if idx else None
    v = f.view(VIEW_STANDARD) if f else None
    return v.fragment(shard) if v else None


def _await(cond, timeout=10.0, every=0.02, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(every)
    raise TimeoutError(f"{what} never held within {timeout}s")


# ---------------------------------------------------------------------------
# Per-block epochs: stamping, tombstones, persistence
# ---------------------------------------------------------------------------


class TestBlockEpochs:
    def test_every_mutation_stamps_its_block(self):
        f = Fragment(None, "i", "f", "standard", 0)
        f.set_bit(3, 7)
        e1 = f.block_epoch(0)
        assert e1 > 0
        f.set_bit(250, 7)  # block 2
        assert f.block_epoch(2) > e1  # per-fragment monotone
        f.clear_bit(3, 7)
        assert f.block_epoch(0) > e1  # clears stamp too (tombstones)

    def test_tombstone_reported_on_wire_payload(self):
        f = Fragment(None, "i", "f", "standard", 0)
        f.set_bit(1, 5)
        f.clear_bit(1, 5)
        blocks = f.block_sums_epochs()
        assert blocks == [(0, 0, f.block_epoch(0))]
        assert f.checksum_blocks() == []  # the legacy view skips empties

    def test_epochs_survive_clean_restart(self, tmp_path):
        path = str(tmp_path / "frag")
        f = Fragment(path, "i", "f", "standard", 0).open()
        f.set_bit(1, 5)
        e = f.block_epoch(0)
        f.close()
        g = Fragment(path, "i", "f", "standard", 0).open()
        assert g.block_epoch(0) == e
        # The reopened fragment's next mint lands strictly after.
        g.set_bit(1, 6)
        assert g.block_epoch(0) > e
        g.close()

    def test_stale_sidecar_degrades_to_unknown(self, tmp_path):
        """WAL bytes appended after the last sidecar write (the crash
        shape: no clean close) make the sidecar unadoptable — those
        blocks report epoch 0 and repair degrades to union, never a
        misdirected wipe."""
        path = str(tmp_path / "frag")
        f = Fragment(path, "i", "f", "standard", 0).open()
        f.set_bit(1, 5)
        f.close()  # sidecar written, size-stamped
        g = Fragment(path, "i", "f", "standard", 0).open()
        g.set_bit(1, 6)  # WAL grows past the sidecar's stamp
        # Simulated crash: drop the handle without close() (no sidecar
        # rewrite), then reopen.
        g._file.release()
        h = Fragment(path, "i", "f", "standard", 0).open()
        assert h.row_count(1) == 2  # WAL replayed fine
        assert h.block_epoch(0) == 0  # epochs honestly unknown
        h.close()

    def test_replace_block_floors_the_mint_clock(self):
        """HLC receive rule: after adopting a peer's (possibly
        future-skewed) epoch, the next LOCAL mint must land strictly
        after it — otherwise a skewed-back clock stamps a genuine new
        write below the epoch the block already carries and the peer's
        OLDER block wins directed repair (review finding)."""
        a = Fragment(None, "i", "f", "standard", 0)
        b = Fragment(None, "i", "f", "standard", 0)
        b.set_bit(1, 7)
        # Simulate B's wall clock running far ahead of A's.
        future = b.block_epoch(0) + 10**12
        a.replace_block(0, b.block_data(0), future)
        assert a.block_epoch(0) == future
        a.set_bit(1, 9)  # a genuinely NEWER local write
        assert a.block_epoch(0) > future

    def test_replace_block_skips_on_stale_expected_epoch(self):
        """The snapshot-to-replace race: a client write landing between
        the sync pass's epoch snapshot and the directed replace mints a
        newer local epoch the decision never saw — replacing anyway
        would wipe the acked write and re-date the block OLDER (review
        finding). A mismatched expected_local_epoch skips untouched."""
        a = Fragment(None, "i", "f", "standard", 0)
        b = Fragment(None, "i", "f", "standard", 0)
        a.set_bit(1, 5)
        snapshot_epoch = a.block_epoch(0)
        b.set_bit(1, 7)
        a.set_bit(1, 9)  # the racing client write, after the snapshot
        racing_epoch = a.block_epoch(0)
        assert a.replace_block(
            0, b.block_data(0), b.block_epoch(0),
            expected_local_epoch=snapshot_epoch,
        ) is None
        assert sorted(a.row(1).columns().tolist()) == [5, 9]  # untouched
        assert a.block_epoch(0) == racing_epoch
        # A matching expectation still replaces.
        assert a.replace_block(
            0, b.block_data(0), b.block_epoch(0),
            expected_local_epoch=racing_epoch,
        ) is not None
        assert a.row(1).columns().tolist() == [7]

    def test_replace_block_tombstone_purges_rank_cache(self):
        """A row wholly cleared by tombstone repair must leave the TopN
        rank cache too: rebuilding only the rows present AFTER the
        directed copy misses it (review finding) — the stale entry
        would resurrect the row in TopN answers."""
        a = Fragment(None, "i", "f", "standard", 0)
        b = Fragment(None, "i", "f", "standard", 0)
        for frag in (a, b):
            frag.set_bit(1, 5)
            frag.set_bit(1, 9)
        b.clear_bit(1, 5)
        b.clear_bit(1, 9)  # block 0 tombstoned on b
        a.replace_block(0, b.block_data(0), b.block_epoch(0))
        assert a.row_count(1) == 0
        assert all(p.id != 1 for p in a.top(n=10))

    def test_replace_block_adopts_peer_state_and_epoch(self):
        a = Fragment(None, "i", "f", "standard", 0)
        b = Fragment(None, "i", "f", "standard", 0)
        a.set_bit(1, 5)
        a.set_bit(1, 9)
        b.set_bit(1, 7)
        peer_epoch = b.block_epoch(0)
        added, removed = a.replace_block(0, b.block_data(0), peer_epoch)
        assert (added, removed) == (1, 2)
        assert a.row(1).columns().tolist() == [7]
        assert a.block_epoch(0) == peer_epoch
        # Byte-convergence: both sides now report identical pairs.
        assert a.block_sums_epochs() == b.block_sums_epochs()

    def test_bulk_import_stamps_only_touched_blocks(self):
        """An import into one block must NOT re-date the others: a
        re-stamped stale block would WIN directed repair over a peer's
        genuinely newer copy — silent write loss of exactly the class
        the epoch plane exists to prevent (review finding on the
        argless _mutated() bulk paths)."""
        import numpy as np

        f = Fragment(None, "i", "f", "standard", 0)
        f.set_bit(210, 7)  # block 2
        e_block2 = f.block_epoch(2)
        # Bulk positions import into block 5 only.
        f.bulk_import(
            np.array([500, 501], dtype=np.uint64),
            np.array([3, 4], dtype=np.uint64),
        )
        assert f.block_epoch(5) > e_block2
        assert f.block_epoch(2) == e_block2  # untouched block keeps its date
        # BSI value writes stamp only the plane blocks (block 0).
        f.import_value(
            np.array([9], dtype=np.uint64),
            np.array([42], dtype=np.int64),
            bit_depth=8,
        )
        assert f.block_epoch(0) > 0
        assert f.block_epoch(2) == e_block2
        # Roaring blob import: rows derived from the blob's containers.
        from pilosa_tpu.roaring import Bitmap, serialize
        from pilosa_tpu.shardwidth import SHARD_WIDTH

        blob = serialize(Bitmap(np.array(
            [700 * SHARD_WIDTH + 11], dtype=np.uint64
        )))
        f.import_roaring(blob)  # row 700 -> block 7
        assert f.block_epoch(7) > 0
        assert f.block_epoch(2) == e_block2

    def test_noop_reimport_never_redates_blocks(self):
        """An idempotent re-import that moves ZERO bits must not mint:
        a re-dated unchanged block would WIN directed repair over a
        replica's genuinely newer block — silent loss for an import
        that changed nothing (review finding)."""
        import numpy as np

        from pilosa_tpu.roaring import Bitmap, serialize
        from pilosa_tpu.shardwidth import SHARD_WIDTH

        f = Fragment(None, "i", "f", "standard", 0)
        f.bulk_import(
            np.array([3], dtype=np.uint64), np.array([7], dtype=np.uint64)
        )
        e = f.block_epoch(0)
        f.bulk_import(  # client retry of the same data
            np.array([3], dtype=np.uint64), np.array([7], dtype=np.uint64)
        )
        assert f.block_epoch(0) == e
        blob = serialize(Bitmap(np.array(
            [3 * SHARD_WIDTH + 7], dtype=np.uint64
        )))
        f.import_roaring(blob)  # every bit already present
        assert f.block_epoch(0) == e

    def test_migration_copy_lands_epoch_unknown(self):
        """A resize-migrated fragment is a COPY of data that already
        exists elsewhere: minting fresh epochs for it would out-date
        genuinely newer blocks on surviving replicas, and directed
        repair would wipe them with the stale copy (review finding).
        epoch_unknown imports land at epoch 0 = union-only."""
        import numpy as np

        from pilosa_tpu.roaring import Bitmap, serialize
        from pilosa_tpu.shardwidth import SHARD_WIDTH

        src = Fragment(None, "i", "f", "standard", 0)
        src.set_bit(3, 7)
        blob = serialize(Bitmap(np.array(
            [3 * SHARD_WIDTH + 7], dtype=np.uint64
        )))
        dst = Fragment(None, "i", "f", "standard", 0)
        dst.import_roaring(blob, epoch_unknown=True)
        assert dst.row_count(3) == 1  # data landed
        assert dst.block_epoch(0) == 0  # honestly unknown, union-only
        assert src.block_epoch(0) > 0  # the real write did mint

    def test_deleted_fragment_removes_epoch_sidecar(self, tmp_path):
        from pilosa_tpu.core.view import View

        v = View(str(tmp_path / "v"), "i", "f", "standard")
        v.open()
        frag = v.create_fragment_if_not_exists(0)
        frag.set_bit(1, 5)
        frag.close()
        import os

        assert os.path.exists(frag.path + EPOCHS_EXT)
        v.delete_fragment(0)
        assert not os.path.exists(frag.path + EPOCHS_EXT)


# ---------------------------------------------------------------------------
# Divergence monitor: queue semantics + targeted repair
# ---------------------------------------------------------------------------


class TestDivergenceMonitor:
    def test_bounded_queue_drops_and_counts(self):
        with TestCluster(1) as c:
            mon = DivergenceMonitor(c[0].cluster, max_queue=2)
            # NOT started: observes pile up so the bound is observable.
            drop0 = _counter("read_repair_dropped_total")
            enq0 = _counter("read_repair_enqueued_total")
            # A probe already pending dedups silently (re-diffing a hot
            # hedged pair back to back buys nothing): not enqueued, not
            # a drop.
            for _ in range(3):
                mon.observe("i", [0], "node0", "node1")
            assert _counter("read_repair_enqueued_total") - enq0 == 1
            assert _counter("read_repair_dropped_total") - drop0 == 0
            # Distinct probes fill the bound; overflow counts as drops.
            for shard in (1, 2, 3, 4):
                mon.observe("i", [shard], "node0", "node1")
            assert _counter("read_repair_enqueued_total") - enq0 == 2
            assert _counter("read_repair_dropped_total") - drop0 == 3

    def test_probe_repairs_divergent_replicas(self):
        """An observed replica pair with differing blocks is counted,
        ledgered, and healed by targeted epoch-directed repair on both
        nodes — without any full anti-entropy pass."""
        with TestCluster(2, replica_n=2) as c:
            c.create_index("i")
            c.create_field("i", "f")
            c.query(0, "i", "Set(5, f=1)")
            c.await_shard_convergence("i")
            # The partition shape: a clear that reached one replica.
            _frag(c[1], "i", "f", 0).clear_bit(1, 5)
            div0 = _counter("replica_divergence_blocks_total")
            mon = DivergenceMonitor(c[0].cluster, max_queue=8).start()
            try:
                mon.observe("i", [0], "node0", "node1")
                _await(
                    lambda: _frag(c[0], "i", "f", 0).row_count(1) == 0,
                    what="read repair convergence",
                )
                assert _counter("replica_divergence_blocks_total") > div0
                dump = mon.debug_dump()
                assert dump["entries"], dump
                assert dump["entries"][0]["index"] == "i"
                # The healed pair converged to the clear (higher epoch).
                assert _frag(c[1], "i", "f", 0).row_count(1) == 0
            finally:
                mon.stop()

    def test_debug_consistency_endpoint(self):
        with TestCluster(2, replica_n=2) as c:
            uri = str(c[0].node.uri)
            with urllib.request.urlopen(uri + "/debug/consistency", timeout=5) as r:
                body = json.loads(r.read())
            assert body["enabled"] is False  # no monitor wired
            mon = DivergenceMonitor(c[0].cluster, max_queue=4)
            try:
                with urllib.request.urlopen(
                    uri + "/debug/consistency", timeout=5
                ) as r:
                    body = json.loads(r.read())
                assert body["enabled"] is True
                assert body["entries"] == []
                assert body["maxQueue"] == 4
            finally:
                mon.stop()

    @pytest.mark.chaos
    def test_hedge_race_feeds_the_monitor(self):
        """The serving-path hook: a slow-but-healthy replica makes the
        hedge fire, BOTH replicas answer, and the losing response's
        arrival enqueues a divergence probe — which then finds and
        repairs the seeded divergence."""
        from tests.cluster_harness import FaultProxy, RewriteClient

        from pilosa_tpu.shardwidth import SHARD_WIDTH

        with TestCluster(2, replica_n=2) as c:
            c.create_index("i")
            c.create_field("i", "f")
            # Hedging applies only to REMOTE legs: pick a shard whose
            # PRIMARY owner is node1, so node0's fan-out dispatches the
            # slow remote primary and hedges to its own local replica.
            topo = c[0].cluster.topology
            shard = next(
                s for s in range(16)
                if topo.shard_nodes("i", s)[0].id == "node1"
            )
            col = shard * SHARD_WIDTH + 5
            c.query(0, "i", f"Set({col}, f=1)")
            c.await_shard_convergence("i")
            _frag(c[1], "i", "f", shard).clear_bit(1, col)
            target = c[1].node.uri
            proxy = FaultProxy(target.host, target.port)
            proxy.mode = "latency"
            proxy.latency_s = 0.3
            rc = RewriteClient(
                {f"{target.host}:{target.port}": f"127.0.0.1:{proxy.port}"},
                timeout=5.0,
            )
            c[0].cluster.client = rc
            c[0].cluster.broadcaster.client = rc
            c[0].cluster.hedge_delay = 0.05
            mon = DivergenceMonitor(c[0].cluster, max_queue=8).start()
            enq0 = _counter("read_repair_enqueued_total")
            try:
                # Fan out from node0: node1's primary leg stalls behind
                # the proxy, the hedge answers locally, the straggler's
                # late answer is the second replica of the pair.
                res = c[0].api.query("i", "Count(Row(f=1))")
                assert res["results"][0] in (0, 1)  # divergent replicas
                _await(
                    lambda: _counter("read_repair_enqueued_total") > enq0,
                    what="hedge-race divergence observation",
                )
                _await(
                    lambda: (
                        _frag(c[0], "i", "f", shard).row_count(1)
                        == _frag(c[1], "i", "f", shard).row_count(1)
                        == 0
                    ),
                    what="read-repair convergence to the clear",
                )
            finally:
                mon.stop()
                proxy.close()


# ---------------------------------------------------------------------------
# SymmetricPartition primitive (chaos)
# ---------------------------------------------------------------------------


class TestSymmetricPartition:
    @pytest.mark.chaos
    def test_partition_blackholes_both_directions_heal_restores(self):
        from pilosa_tpu.cluster.client import ClientError

        with TestCluster(2, replica_n=2) as c:
            c.create_index("i")
            c.create_field("i", "f")
            c.query(0, "i", "Set(5, f=1)")
            c.await_shard_convergence("i")
            with SymmetricPartition(c, 0, 1, timeout=0.4) as part:
                part.partition()
                for src, dst in ((c[0], c[1]), (c[1], c[0])):
                    with pytest.raises(ClientError):
                        src.cluster.client.status(dst.node)
                part.heal()
                for src, dst in ((c[0], c[1]), (c[1], c[0])):
                    assert src.cluster.client.status(dst.node)["nodes"]


# ---------------------------------------------------------------------------
# Anti-entropy vs resize: mid-migration shards are skipped
# ---------------------------------------------------------------------------


class TestAntiEntropySkipsMigration:
    def test_migrating_shard_skipped_and_counted(self):
        from pilosa_tpu.cluster.sync import HolderSyncer

        with TestCluster(2, replica_n=2) as c:
            c.create_index("i")
            c.create_field("i", "f")
            c.query(0, "i", "Set(5, f=1)")
            c.await_shard_convergence("i")
            # Diverge so an unskipped pass WOULD repair.
            _frag(c[1], "i", "f", 0).clear_bit(1, 5)
            rz = c[0].cluster.resizer
            with rz._migrating_lock:
                rz._migrating.add(("i", 0))
            skip0 = _counter("anti_entropy_skipped_total")
            try:
                HolderSyncer(c[0].cluster).sync_holder()
                assert _counter("anti_entropy_skipped_total") > skip0
                # The mid-move shard was left alone.
                assert _frag(c[0], "i", "f", 0).row_count(1) == 1
            finally:
                with rz._migrating_lock:
                    rz._migrating.discard(("i", 0))
            # Window over: the next pass heals it (clear wins).
            HolderSyncer(c[0].cluster).sync_holder()
            assert _frag(c[0], "i", "f", 0).row_count(1) == 0

    def test_targeted_repair_skips_migrating_shard(self):
        from pilosa_tpu.cluster.sync import HolderSyncer

        with TestCluster(2, replica_n=2) as c:
            c.create_index("i")
            c.create_field("i", "f")
            c.query(0, "i", "Set(5, f=1)")
            c.await_shard_convergence("i")
            _frag(c[1], "i", "f", 0).clear_bit(1, 5)
            rz = c[0].cluster.resizer
            with rz._migrating_lock:
                rz._migrating.add(("i", 0))
            assert (
                HolderSyncer(c[0].cluster).sync_fragment_targeted(
                    "i", "f", "standard", 0
                )
                == 0
            )
            assert _frag(c[0], "i", "f", 0).row_count(1) == 1

    def test_targeted_repair_skips_unowned_shard(self):
        """A read-repair RPC can land minutes after the hedge
        observation (bounded queue x per-probe budget); if a resize
        moved the shard off this node meanwhile, repairing would
        recreate and repopulate a fragment cleanup already removed
        (review finding) — the targeted path needs the daemon pass's
        ownership guard."""
        from pilosa_tpu.cluster.sync import HolderSyncer

        with TestCluster(2, replica_n=1) as c:
            c.create_index("i")
            c.create_field("i", "f")
            # replica_n=1: every shard has exactly one owner — pick a
            # shard node0 does NOT own and aim the repair at node0.
            topo = c[0].cluster.topology
            shard = next(
                s for s in range(8)
                if topo.shard_nodes("i", s)[0].id != "node0"
            )
            before = _counter("anti_entropy_skipped_total")
            assert (
                HolderSyncer(c[0].cluster).sync_fragment_targeted(
                    "i", "f", "standard", shard
                )
                == 0
            )
            assert _counter("anti_entropy_skipped_total") == before + 1
