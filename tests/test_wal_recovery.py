"""Write-plane robustness (ISSUE r8): crash-safe WAL recovery, the
torn-tail contract, snapshot-under-load, journal compaction, and import
backpressure.

Layers covered:
- WAL corpus through Fragment.open(): torn tail at EVERY byte offset of
  the final record, checksum-failing final record, bit-flip mid-log,
  empty file, snapshot+WAL combinations, snapshot-section corruption.
- OpWriter/_WalFile write discipline: a record is never split across
  OS writes even when the raw fd writes short; close() flushes.
- Rank-cache durability: a stale .cache is rebuilt, not trusted, when
  replay applied ops.
- Off-hot-path snapshotting: concurrent writes during the rewrite
  survive the swap; op_n and the WAL backlog drop.
- Journal run compaction: version walks stay journal-backed across
  churn windows far past JOURNAL_MAX writes.
- Import backpressure: 429/503 + Retry-After + code through the real
  HTTP surface, peer-shed propagation through cluster import routing.
- Chaos: in-process SIGKILL-simulation (abrupt fd close + torn tail,
  tier-1-safe) and a real-subprocess SIGKILL harness (skips where
  subprocess networking is restricted).
"""

from __future__ import annotations

import json
import os
import pathlib
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from pilosa_tpu.core import Holder
from pilosa_tpu.core.field import options_for_int
from pilosa_tpu.core.fragment import (
    MAX_OP_N,
    WAL_BACKLOG,
    Fragment,
    FragmentCorruptError,
    _WalFile,
)
from pilosa_tpu.core.view import View
from pilosa_tpu.exec import Executor
from pilosa_tpu.roaring.codec import (
    OP_ADD,
    CorruptWalError,
    OpWriter,
    ReplayInfo,
    apply_ops,
    encode_op,
)
from pilosa_tpu.server.api import API, APIError
from pilosa_tpu.server.http import Server
from pilosa_tpu.shardwidth import SHARD_WIDTH
from pilosa_tpu.utils.stats import global_stats

REPO = str(pathlib.Path(__file__).resolve().parent.parent)


def _counter(name: str) -> float:
    snap = global_stats.snapshot()["counters"]
    return sum(v for k, v in snap.items() if k.startswith(name))


def _fragment(path: str, **kw) -> Fragment:
    return Fragment(path, "i", "f", "standard", 0, **kw)


def _read(path: str) -> bytes:
    with open(path, "rb") as f:
        return f.read()


def _write(path: str, data: bytes) -> None:
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "wb") as f:
        f.write(data)


# ---------------------------------------------------------------------------
# WAL corpus through Fragment.open()
# ---------------------------------------------------------------------------


class TestWalCorpus:
    def _seed(self, tmp_path):
        """A fragment file with 2 good single-bit records, then one
        final add-batch record. Returns (good_prefix, full_file)."""
        base = str(tmp_path / "seed" / "0")
        f = _fragment(base).open()
        f.set_bit(1, 10)
        f.set_bit(2, 20)
        good = _read(base)
        f.bulk_import(
            np.array([3, 3, 3], dtype=np.uint64),
            np.array([30, 31, 32], dtype=np.uint64),
        )
        full = _read(base)
        f.close()
        assert len(full) > len(good)
        return good, full

    def _open_and_rows(self, path: str) -> dict[int, list[int]]:
        fr = _fragment(path).open()
        try:
            return {
                r: fr.row(r).columns().tolist() for r in fr.row_ids()
            }
        finally:
            fr.close()

    def test_torn_tail_every_byte_offset(self, tmp_path):
        """A final record cut at EVERY length < its size recovers to the
        last good record; the file is truncated back to match."""
        good, full = self._seed(tmp_path)
        tail = full[len(good):]
        trunc0 = _counter("wal_truncated_records_total")
        for cut in range(len(tail)):
            p = str(tmp_path / f"cut{cut}" / "0")
            _write(p, good + tail[:cut])
            rows = self._open_and_rows(p)
            assert rows == {1: [10], 2: [20]}, cut
            if cut:  # cut=0 is simply the clean shorter log
                assert os.path.getsize(p) == len(good), cut
        # Every nonzero cut truncated exactly one torn record.
        assert _counter("wal_truncated_records_total") - trunc0 == len(tail) - 1

    def test_full_final_record_applies(self, tmp_path):
        _good, full = self._seed(tmp_path)
        p = str(tmp_path / "whole" / "0")
        _write(p, full)
        rows = self._open_and_rows(p)
        assert rows == {1: [10], 2: [20], 3: [30, 31, 32]}

    def test_checksum_failing_final_record_truncates(self, tmp_path):
        """A bit flip in the FINAL record's payload is indistinguishable
        from a mid-append crash: recovery truncates it away."""
        good, full = self._seed(tmp_path)
        p = str(tmp_path / "flip-tail" / "0")
        damaged = bytearray(full)
        damaged[-3] ^= 0x40  # payload byte of the final batch record
        _write(p, bytes(damaged))
        rows = self._open_and_rows(p)
        assert rows == {1: [10], 2: [20]}
        assert os.path.getsize(p) == len(good)

    def test_bit_flip_mid_log_refuses_open(self, tmp_path):
        """Corruption BEFORE the tail (valid records follow) must refuse
        to open — truncating there would drop acknowledged records."""
        good, full = self._seed(tmp_path)
        p = str(tmp_path / "flip-mid" / "0")
        damaged = bytearray(full)
        # good ends with two 13-byte point records; flip a value byte of
        # the FIRST one (checksum covers bytes [0:9]).
        first_rec = len(good) - 26
        damaged[first_rec + 3] ^= 0x01
        _write(p, bytes(damaged))
        corrupt0 = _counter('fragment_recovery_total{outcome="corrupt"}')
        with pytest.raises(FragmentCorruptError) as e:
            _fragment(p).open()
        assert e.value.reason == "checksum"
        assert _counter('fragment_recovery_total{outcome="corrupt"}') - corrupt0 == 1
        # The file is untouched: nothing was silently dropped.
        assert _read(p) == bytes(damaged)

    def test_empty_file_opens_empty(self, tmp_path):
        p = str(tmp_path / "empty" / "0")
        _write(p, b"")
        fr = _fragment(p).open()
        try:
            assert not fr.storage.any()
            # The open wrote a valid empty-bitmap header for the WAL.
            assert os.path.getsize(p) > 0
        finally:
            fr.close()

    def test_snapshot_plus_wal_torn_tail(self, tmp_path):
        """The compacted-snapshot + WAL + torn-garbage combination: the
        snapshot section and the good WAL records survive."""
        p = str(tmp_path / "snapwal" / "0")
        f = _fragment(p).open()
        f.bulk_import(
            np.zeros(50, dtype=np.uint64),
            np.arange(50, dtype=np.uint64),
        )
        f.snapshot()  # file is now a pure snapshot, op_n == 0
        f.set_bit(7, 70)
        f.set_bit(8, 80)
        f.close()
        good = _read(p)
        # Torn garbage: the prefix of a valid record (what a SIGKILL
        # mid-append leaves).
        _write(p, good + encode_op(OP_ADD, value=9 * SHARD_WIDTH + 90)[:6])
        rows = self._open_and_rows(p)
        assert rows[0] == list(range(50))
        assert rows[7] == [70] and rows[8] == [80]
        assert os.path.getsize(p) == len(good)

    def test_snapshot_section_corruption_refuses_open(self, tmp_path):
        p = str(tmp_path / "snapbad" / "0")
        f = _fragment(p).open()
        f.bulk_import(
            np.zeros(10, dtype=np.uint64), np.arange(10, dtype=np.uint64)
        )
        f.snapshot()
        f.close()
        damaged = bytearray(_read(p))
        # Container type code (u16 at offset 8+8 of the first container
        # descriptor) -> structurally impossible value.
        damaged[16] = 0x7F
        _write(p, bytes(damaged))
        with pytest.raises(FragmentCorruptError):
            _fragment(p).open()

    def test_wire_deserialize_stays_strict(self):
        """Without a ReplayInfo (wire payloads, block merges) a torn
        tail still raises — peers' serialized bitmaps have no legitimate
        truncation."""
        from pilosa_tpu.roaring import Bitmap, serialize

        b = Bitmap([1, 2, 3])
        data = serialize(b) + encode_op(OP_ADD, value=9)[:6]
        with pytest.raises(ValueError):
            Bitmap.from_bytes(data)

    def test_apply_ops_reports_replay_info(self):
        from pilosa_tpu.roaring import Bitmap

        log = encode_op(OP_ADD, value=1) + encode_op(OP_ADD, value=2)
        info = ReplayInfo()
        n = apply_ops(Bitmap(), log + log[:5], 0, info)
        assert n == 2 and info.ops_applied == 2
        assert info.torn_offset == len(log)
        assert info.torn_reason == "short-record"

    def test_apply_ops_mid_log_raises_corrupt(self):
        from pilosa_tpu.roaring import Bitmap

        rec = bytearray(encode_op(OP_ADD, value=1))
        rec[4] ^= 0x01
        log = bytes(rec) + encode_op(OP_ADD, value=2)
        with pytest.raises(CorruptWalError) as e:
            apply_ops(Bitmap(), log, 0, ReplayInfo())
        assert e.value.offset == 0 and e.value.reason == "checksum"


# ---------------------------------------------------------------------------
# OpWriter / _WalFile write discipline
# ---------------------------------------------------------------------------


class _ShortWriter:
    """Raw-file proxy whose write() lands at most `chunk` bytes per call
    — the short-write behavior a raw unbuffered fd is allowed to have."""

    def __init__(self, fh, chunk=3):
        self._fh = fh
        self.chunk = chunk
        self.calls = 0

    def write(self, data):
        self.calls += 1
        return self._fh.write(bytes(data)[: self.chunk])

    def __getattr__(self, name):
        return getattr(self._fh, name)


class TestWalWriteDiscipline:
    def test_short_raw_writes_never_tear_a_record(self, tmp_path):
        """_WalFile loops raw short writes until the whole record is
        down (ISSUE r8 satellite: buffering=0 returns a raw FileIO whose
        write() may be partial)."""
        p = str(tmp_path / "wal")
        wal = _WalFile(p)
        wal.write(b"")  # open the fd
        short = _ShortWriter(wal._fh, chunk=3)
        wal._fh = short
        w = OpWriter(wal)
        vals = np.array([5, 6, 7, 8, 9], dtype=np.uint64)
        w.append_add_batch(vals)
        w.append_add(11)
        wal._fh = short._fh
        wal.close()
        from pilosa_tpu.roaring import Bitmap

        b = Bitmap()
        info = ReplayInfo()
        apply_ops(b, _read(p), 0, info)
        assert info.torn_offset is None and info.ops_applied == 2
        assert sorted(b.to_array().tolist()) == [5, 6, 7, 8, 9, 11]
        assert short.calls > 2  # the loop actually looped

    def test_one_write_call_per_record(self, tmp_path):
        """Each append_* hands the file exactly ONE already-encoded
        record — no record is ever split across two writer calls."""
        writes = []

        class Recorder:
            def write(self, data):
                writes.append(bytes(data))

            def flush(self):
                pass

        from pilosa_tpu.roaring import Bitmap, serialize

        w = OpWriter(Recorder())
        w.append_add(1)
        w.append_remove(2)
        w.append_add_batch(np.array([3, 4], dtype=np.uint64))
        w.append_roaring(serialize(Bitmap([9])), 1, clear=False)
        assert len(writes) == 4

        for rec in writes:
            # Every captured write is a whole, self-checksummed record.
            info = ReplayInfo()
            apply_ops(Bitmap(), rec, 0, info)
            assert info.ops_applied == 1 and info.torn_offset is None

    def test_close_flushes_buffered_writer(self, tmp_path):
        """Fragment.close() flushes the op writer before detaching: a
        buffered writer's tail records must reach the file."""
        p = str(tmp_path / "frag" / "0")
        f = _fragment(p).open()
        f.set_bit(1, 10)

        class Buffered:
            def __init__(self, inner):
                self.inner = inner
                self.buf = b""

            def write(self, data):
                self.buf += bytes(data)
                return len(data)

            def flush(self):
                if self.buf:
                    self.inner.write(self.buf)
                    self.buf = b""

        buffered = Buffered(f._file)
        f.storage.op_writer = OpWriter(buffered)
        f.set_bit(2, 20)
        assert buffered.buf  # still buffered, not on disk
        f.close()
        rows = {1: [10], 2: [20]}
        fr = _fragment(p).open()
        try:
            assert {r: fr.row(r).columns().tolist() for r in fr.row_ids()} == rows
        finally:
            fr.close()


# ---------------------------------------------------------------------------
# Rank-cache durability after replay
# ---------------------------------------------------------------------------


class TestRankCacheRecovery:
    def test_stale_cache_rebuilt_after_replay(self, tmp_path):
        p = str(tmp_path / "frag" / "0")
        f = _fragment(p).open()
        f.set_bit(1, 10)
        f.close()  # flushes .cache with {1: 1}
        # Crash-sim: an acknowledged write whose cache flush never
        # happened — append its WAL record directly to the file.
        with open(p, "ab", buffering=0) as fh:
            fh.write(encode_op(OP_ADD, value=2 * SHARD_WIDTH + 20))
        f2 = _fragment(p).open()
        try:
            # Pre-fix, load_cache trusted the stale file ({1: 1}) and
            # row 2 was invisible to TopN until a write touched it.
            top = {pr.id: pr.count for pr in f2.cache.top()}
            assert top == {1: 1, 2: 1}
        finally:
            f2.close()

    def test_clean_reopen_still_loads_cache(self, tmp_path):
        p = str(tmp_path / "frag" / "0")
        f = _fragment(p).open()
        f.set_bit(1, 10)
        f.snapshot()  # empty WAL: the next open replays nothing
        f.close()
        f2 = _fragment(p).open()
        try:
            assert {pr.id: pr.count for pr in f2.cache.top()} == {1: 1}
        finally:
            f2.close()


# ---------------------------------------------------------------------------
# Snapshot off the hot path
# ---------------------------------------------------------------------------


class TestSnapshotUnderLoad:
    def test_threshold_triggers_background_rewrite(self, tmp_path):
        p = str(tmp_path / "frag" / "0")
        f = _fragment(p, cache_type="none").open()
        snaps0 = _counter("fragment_snapshots_total")
        batch = np.arange(MAX_OP_N + 50, dtype=np.uint64)
        f.bulk_import(np.zeros(batch.size, dtype=np.uint64), batch)
        f.await_snapshot()
        assert f.storage.op_n == 0
        assert _counter("fragment_snapshots_total") - snaps0 == 1
        # The stall is visible as a histogram observation.
        assert any(
            k.startswith("fragment_snapshot_seconds")
            for k in global_stats.histogram_snapshot()
        )
        f.close()

    def test_writes_during_rewrite_survive_the_swap(self, tmp_path):
        """Concurrent writers keep landing in the live WAL while the
        rewrite serializes; the post-swap file replays to the full
        state (the tail-splice contract)."""
        p = str(tmp_path / "frag" / "0")
        f = _fragment(p, cache_type="none").open()
        f.bulk_import(
            np.zeros(200, dtype=np.uint64), np.arange(200, dtype=np.uint64)
        )
        stop = threading.Event()
        written: list[int] = []

        def writer():
            col = 1000
            while not stop.is_set():
                f.set_bit(3, col)
                written.append(col)
                col += 1

        t = threading.Thread(target=writer, daemon=True)
        t.start()
        try:
            for _ in range(5):
                f.snapshot()
        finally:
            stop.set()
            t.join(timeout=10)
        f.close()
        fr = _fragment(p, cache_type="none").open()
        try:
            assert fr.row(0).columns().tolist() == list(range(200))
            got = fr.row(3).columns().tolist()
            assert got == written  # every acknowledged write present
        finally:
            fr.close()

    def test_backlog_gauge_tracks_pending_ops(self, tmp_path):
        p = str(tmp_path / "frag" / "0")
        f = _fragment(p, cache_type="none").open()
        ops0 = WAL_BACKLOG.ops
        for i in range(7):
            f.set_bit(0, i)
        assert WAL_BACKLOG.ops - ops0 == 7
        f.snapshot()
        assert WAL_BACKLOG.ops == ops0
        f.set_bit(0, 99)
        assert WAL_BACKLOG.ops - ops0 == 1
        f.close()  # the fragment's contribution leaves with it
        assert WAL_BACKLOG.ops == ops0


# ---------------------------------------------------------------------------
# Journal run compaction
# ---------------------------------------------------------------------------


class TestJournalCompaction:
    def test_contiguous_runs_survive_far_past_journal_max(self):
        v = View(None, "i", "f", "standard")
        gen0 = v.generation
        n = View.JOURNAL_MAX * 10
        for _ in range(n):
            v._bump_data(5)
        for _ in range(n):
            v._bump_data(6)
        # 2 runs occupy 2 slots: the whole window stays explained.
        assert v.dirty_shards_since(gen0) == {5, 6}
        assert len(v._journal) == 2

    def test_interleaving_depth_still_bounds(self):
        """Worst-case alternation compacts nothing — the documented
        bound is interleaving depth, not write count."""
        v = View(None, "i", "f", "standard")
        gen0 = v.generation
        for i in range(View.JOURNAL_MAX + 10):
            v._bump_data(i % 2)
            v._bump_data(2 + i % 2)
        assert v.dirty_shards_since(gen0) is None  # evicted: full walk
        assert v.dirty_shards_since(v.generation) == set()

    def test_run_boundaries_are_exact(self):
        v = View(None, "i", "f", "standard")
        v._bump_data(1)
        g_mid = v.generation
        v._bump_data(1)  # extends the SAME run past g_mid
        v._bump_data(2)
        assert v.dirty_shards_since(g_mid) == {1, 2}
        assert v.dirty_shards_since(v.generation) == set()

    def test_long_churn_version_walks_stay_journal_backed(self):
        """ISSUE r8 tentpole 4 acceptance: a churn window far past the
        old JOURNAL_MAX entry bound (every write on one hot fragment —
        the append-style ingest shape) keeps the pair tier's
        version_walk_total{kind=full} FLAT."""
        tpu = pytest.importorskip(
            "pilosa_tpu.exec.tpu",
            reason="device backend needs jax.shard_map",
            exc_type=ImportError,
        )
        from pilosa_tpu.pql import parse_string

        holder = Holder(None).open()
        try:
            idx = holder.create_index("i")
            rng = np.random.default_rng(29)
            n_shards = 3
            for fname in ("f", "g"):
                fobj = idx.create_field(fname)
                for shard in range(n_shards):
                    cols = (
                        np.unique(
                            rng.integers(0, SHARD_WIDTH, 200, dtype=np.uint64)
                        )
                        + shard * SHARD_WIDTH
                    )
                    fobj.import_bits(
                        rng.integers(0, 4, cols.size, dtype=np.uint64), cols
                    )
            be = tpu.TPUBackend(holder)
            shards = list(range(n_shards))
            q = "Count(Intersect(Row(f=1), Row(g=2)))"
            calls = [parse_string(q).calls[0].children[0]]
            be.count_batch("i", calls, shards)  # warm
            fobj = idx.field("f")

            def full_walks():
                return _counter('version_walk_total{kind="full",tier="pair"}')

            w0 = full_walks()
            for epoch in range(3):
                # One churn window: WAY past JOURNAL_MAX point writes,
                # all on shard 0 (one run in the compacted journal).
                for i in range(View.JOURNAL_MAX * 2 + 17):
                    fobj.set_bit(1, (epoch * 10_000 + i) % SHARD_WIDTH)
                be.count_batch("i", calls, shards)
            assert full_walks() == w0  # zero full walks across the churn
        finally:
            holder.close()


# ---------------------------------------------------------------------------
# Import backpressure through the real HTTP surface
# ---------------------------------------------------------------------------


@pytest.fixture
def server(tmp_path):
    holder = Holder(str(tmp_path / "data")).open()
    srv = Server(API(holder, Executor(holder)), host="localhost", port=0).open()
    yield srv
    srv.close()
    holder.close()


def _req(srv, method, path, body=None):
    data = None
    if body is not None:
        data = body if isinstance(body, bytes) else json.dumps(body).encode()
    r = urllib.request.Request(
        srv.uri + path, data=data, method=method,
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(r) as resp:
        return json.loads(resp.read())


class TestImportBackpressure:
    def _schema(self, srv):
        _req(srv, "POST", "/index/i", {})
        _req(srv, "POST", "/index/i/field/f", {})

    def test_inflight_bytes_cap_sheds_429(self, server):
        self._schema(server)
        api = server.api
        api.max_import_bytes = 64
        assert api.begin_import(80) is None  # large-but-idle is admitted
        shed0 = _counter('import_shed_total{reason="inflight-bytes"}')
        try:
            with pytest.raises(urllib.error.HTTPError) as e:
                _req(server, "POST", "/index/i/field/f/import",
                     {"rowIDs": [1], "columnIDs": [2]})
            assert e.value.code == 429
            assert e.value.headers.get("Retry-After") == "1"
            assert json.loads(e.value.read())["code"] == "import-overloaded"
            assert _counter('import_shed_total{reason="inflight-bytes"}') - shed0 == 1
        finally:
            api.end_import(80)
        # Capacity freed: the same import is admitted and lands.
        out = _req(server, "POST", "/index/i/field/f/import",
                   {"rowIDs": [1], "columnIDs": [2]})
        assert out == {"success": True}
        got = _req(server, "POST", "/index/i/query", b"Count(Row(f=1))")
        assert got["results"] == [1]

    def test_wal_backlog_cap_sheds_503(self, server):
        self._schema(server)
        api = server.api
        # Land enough acknowledged writes to push the live backlog past
        # a cap anchored at the CURRENT level (the gauge is process-
        # wide; anchoring makes the test independent of neighbors).
        api.max_pending_wal = WAL_BACKLOG.ops + 10
        out = _req(server, "POST", "/index/i/field/f/import",
                   {"rowIDs": [0] * 32, "columnIDs": list(range(32))})
        assert out == {"success": True}
        assert WAL_BACKLOG.ops > api.max_pending_wal
        shed0 = _counter('import_shed_total{reason="wal-backlog"}')
        with pytest.raises(urllib.error.HTTPError) as e:
            _req(server, "POST", "/index/i/field/f/import",
                 {"rowIDs": [1], "columnIDs": [2]})
        assert e.value.code == 503
        assert e.value.headers.get("Retry-After") == "1"
        assert json.loads(e.value.read())["code"] == "wal-backlog"
        assert _counter('import_shed_total{reason="wal-backlog"}') - shed0 == 1
        # Snapshots draining the backlog reopen the gate.
        api.max_pending_wal = 0
        out = _req(server, "POST", "/index/i/field/f/import",
                   {"rowIDs": [1], "columnIDs": [2]})
        assert out == {"success": True}

    def test_unbounded_by_default(self, server):
        assert server.api.max_import_bytes == 0
        assert server.api.max_pending_wal == 0
        assert server.api.begin_import(1 << 30) is None
        server.api.end_import(1 << 30)

    def test_peer_shed_propagates_to_origin(self):
        """A fanned-out import leg refused by the owning peer's gate
        surfaces at the originating node as the peer's 429 + code —
        never an opaque 500 (the budget-propagation satellite)."""
        from tests.cluster_harness import TestCluster

        with TestCluster(2) as tc:
            tc.create_index("bp")
            tc.create_field("bp", "f")
            topo = tc[0].cluster.topology
            # A shard primaried on node1, so node0 must fan out.
            shard = next(
                s for s in range(64)
                if topo.shard_nodes("bp", s)[0].id == "node1"
            )
            tc[1].api.max_import_bytes = 8
            assert tc[1].api.begin_import(100) is None  # saturate node1
            try:
                with pytest.raises(APIError) as e:
                    tc[0].api.import_bits(
                        "bp", "f", [1], [shard * SHARD_WIDTH + 3]
                    )
                assert e.value.status == 429
                assert e.value.code == "import-overloaded"
            finally:
                tc[1].api.end_import(100)
            # Gate cleared: the same routed import lands on the peer.
            tc[0].api.import_bits("bp", "f", [1], [shard * SHARD_WIDTH + 3])
            res = tc.query(0, "bp", "Count(Row(f=1))")
            assert res["results"] == [1]


# ---------------------------------------------------------------------------
# SLO-adaptive ingest derating (ISSUE r19 tentpole 4)
# ---------------------------------------------------------------------------


class _StubMonitor:
    """A monitor pinned at one derate level: the admission gate's unit
    tests need the ladder position, not the burn-rate machinery."""

    def __init__(self, level: int = 0):
        self.level = level

    def derate_level(self) -> int:
        return self.level


class TestIngestDerating:
    def _schema(self, srv):
        _req(srv, "POST", "/index/i", {})
        _req(srv, "POST", "/index/i/field/f", {})

    def test_gate_admits_one_in_two_pow_level(self, server):
        api = server.api
        api.monitor = _StubMonitor(2)
        try:
            d0 = _counter('import_derated_total{reason="read-slo"}')
            admitted = 0
            for _ in range(16):
                refuse = api.begin_import(8)
                if refuse is None:
                    admitted += 1
                    api.end_import(8)
                else:
                    # 4-tuple: the scaled Retry-After rides along.
                    assert refuse == (429, "import-derated", "read-slo", 2.0)
            assert admitted == 4  # level 2 admits 1-in-4
            assert (
                _counter('import_derated_total{reason="read-slo"}') - d0 == 12
            )
        finally:
            api.monitor = None

    def test_http_shed_scales_retry_after(self, server):
        self._schema(server)
        api = server.api
        api.monitor = _StubMonitor(3)
        try:
            shed = None
            for _ in range(8):  # level 3 admits 1-in-8: a shed must land
                try:
                    _req(server, "POST", "/index/i/field/f/import",
                         {"rowIDs": [1], "columnIDs": [2]})
                except urllib.error.HTTPError as e:
                    shed = e
                    break
            assert shed is not None and shed.code == 429
            assert shed.headers.get("Retry-After") == "4"  # 2^(level-1)
            assert json.loads(shed.read())["code"] == "import-derated"
        finally:
            api.monitor = None
        # Ladder released (SLO recovered): the same import lands.
        out = _req(server, "POST", "/index/i/field/f/import",
                   {"rowIDs": [1], "columnIDs": [2]})
        assert out == {"success": True}

    def test_disabled_knob_bypasses_gate(self, server):
        api = server.api
        api.monitor = _StubMonitor(4)
        api.ingest_derate = False
        try:
            assert api.begin_import(8) is None
            api.end_import(8)
        finally:
            api.ingest_derate = True
            api.monitor = None

    def test_monitor_ladder_ramps_and_decays(self):
        """The burn ladder steps +1 per burning evaluation (capped) and
        -1 per clean one — driven through real histogram windows, not a
        stub: observations far over the threshold burn, then a raised
        threshold recovers."""
        from pilosa_tpu.utils.monitor import DERATE_MAX_LEVEL, RuntimeMonitor

        mon = RuntimeMonitor()
        mon.slo = [{
            "metric": "derate_probe_seconds",
            "quantile": 0.5,
            "threshold_s": 0.0001,
            "window_s": 60,
        }]
        for step in (1, 2, 3, 4, 4):
            # Fresh over-threshold observations each round: the windows
            # diff against the retained snapshot, so a silent round
            # would read as recovered.
            for _ in range(20):
                global_stats.timing("derate_probe_seconds", 0.05)
            mon.evaluate_slos()
            assert mon.derate_level() == min(step, DERATE_MAX_LEVEL)
        mon.slo[0]["threshold_s"] = 100.0  # objective satisfied
        for want in (3, 2, 1, 0, 0):
            mon.evaluate_slos()
            assert mon.derate_level() == want

    def test_adhoc_objectives_never_move_the_ladder(self):
        """evaluate_slos(objectives=[...]) is the /debug/slo what-if
        probe: it must not step production admission."""
        from pilosa_tpu.utils.monitor import RuntimeMonitor

        mon = RuntimeMonitor()
        for _ in range(10):
            global_stats.timing("derate_probe_seconds", 0.05)
        mon.evaluate_slos(objectives=[{
            "metric": "derate_probe_seconds",
            "quantile": 0.5,
            "threshold_s": 0.0001,
            "window_s": 60,
        }])
        assert mon.derate_level() == 0


# ---------------------------------------------------------------------------
# Chaos: crash recovery
# ---------------------------------------------------------------------------


def _release_all_wal_fds(holder: Holder) -> None:
    """The in-process SIGKILL simulation: abruptly drop every WAL fd
    with NO close() — no cache flush, no snapshot, exactly the state a
    killed process leaves on disk (the WAL is unbuffered, so every
    acknowledged record is already there)."""
    for idx in holder.indexes.values():
        for fld in idx.fields.values():
            for vw in fld.views.values():
                for fr in vw.fragments.values():
                    fr.await_snapshot()
                    if fr._file is not None:
                        fr._file.release()


@pytest.mark.chaos
class TestCrashRecoveryInProcess:
    """Tier-1-safe SIGKILL simulation (ISSUE r8 CI satellite): runs
    where subprocess networking is restricted."""

    def test_acknowledged_writes_survive_fd_drop_and_torn_tail(self, tmp_path):
        data_dir = str(tmp_path / "node")
        holder = Holder(data_dir).open()
        api = API(holder, Executor(holder))
        api.create_index("i", {"trackExistence": False})
        api.create_field("i", "f", {"type": "set"})
        api.create_field("i", "v", {"type": "int", "min": -1000, "max": 1000})
        rng = np.random.default_rng(17)
        shadow_rows: dict[int, set] = {}
        shadow_vals: dict[int, int] = {}
        for _ in range(30):
            rows = rng.integers(0, 5, 40).tolist()
            cols = rng.integers(0, 3 * SHARD_WIDTH, 40).tolist()
            api.import_bits("i", "f", rows, cols)  # acknowledged
            for r, c in zip(rows, cols):
                shadow_rows.setdefault(r, set()).add(c)
            vcols = rng.integers(0, 2 * SHARD_WIDTH, 20).tolist()
            vals = rng.integers(-1000, 1000, 20).tolist()
            api.import_values("i", "v", vcols, vals)
            for c, val in zip(vcols, vals):
                shadow_vals[c] = val
        # -- SIGKILL simulation ------------------------------------------
        _release_all_wal_fds(holder)
        frag_path = os.path.join(
            data_dir, "i", "f", "views", "standard", "fragments", "0"
        )
        assert os.path.exists(frag_path)
        with open(frag_path, "ab", buffering=0) as fh:
            # The in-flight, UNacknowledged record the kill tore.
            fh.write(encode_op(OP_ADD, value=4 * SHARD_WIDTH - 1)[:9])
        # -- restart on the same data dir --------------------------------
        recov0 = _counter("fragment_recovery_total")
        h2 = Holder(data_dir).open()
        try:
            assert _counter("fragment_recovery_total") > recov0
            ex = Executor(h2)
            for r, cols in shadow_rows.items():
                got = ex.execute("i", f"Count(Row(f={r}))")[0]
                assert got == len(cols), r
            top = ex.execute("i", "TopN(f)")[0]
            want_top = sorted(
                ((len(cs), -r) for r, cs in shadow_rows.items()),
                reverse=True,
            )
            got_top = [(p.count, -p.id) for p in top.pairs]
            assert got_top == want_top
            vc = ex.execute("i", "Sum(field=v)")[0]
            assert vc.count == len(shadow_vals)
            assert vc.val == sum(shadow_vals.values())
        finally:
            h2.close()
            holder.close()


@pytest.mark.chaos
class TestPacedSnapshotCrash:
    """SIGKILL mid-paced-snapshot (ISSUE r19 satellite): a kill landing
    inside the token-bucket wait leaves the live file complete (every
    acked record is in the WAL — phase 2 only ever writes the temp) plus
    an orphaned `.snapshotting` temp. Restart must recover every
    acknowledged write via the torn-tail contract and sweep the orphan.
    Tier-1-safe: the crash is simulated by copying the exact on-disk
    state while the rewrite is parked mid-pacing."""

    def test_kill_mid_token_bucket_wait_loses_nothing(self, tmp_path):
        import shutil

        from pilosa_tpu.core.fragment import SNAPSHOT_SCHEDULER

        base = str(tmp_path / "live" / "0")
        f = _fragment(base).open()
        rng = np.random.default_rng(23)
        cols = np.unique(rng.integers(0, SHARD_WIDTH, 4000, dtype=np.uint64))
        f.bulk_import(np.full(cols.size, 1, dtype=np.uint64), cols)  # acked
        # 1 KiB/s: the rewrite parks in the token-bucket wait before its
        # first chunk, with the temp already created — the exact window
        # the satellite names (mid-token-bucket-wait included).
        SNAPSHOT_SCHEDULER.configure(bandwidth=1024)
        crash_dir = str(tmp_path / "crash")
        os.makedirs(crash_dir)
        try:
            f.storage.op_n = MAX_OP_N
            f.set_bit(1, SHARD_WIDTH - 1)  # acked; crosses the bound
            tmp_file = base + ".snapshotting"
            deadline = time.monotonic() + 10
            while not os.path.exists(tmp_file):
                assert time.monotonic() < deadline, "rewrite never started"
                time.sleep(0.005)
            # -- the SIGKILL: freeze the on-disk state as the kill
            # would leave it (live WAL + partial temp, no close).
            shutil.copyfile(base, os.path.join(crash_dir, "0"))
            shutil.copyfile(
                tmp_file, os.path.join(crash_dir, "0.snapshotting")
            )
        finally:
            # Uncap: the parked rewrite's next 50 ms slice sees rate 0
            # and the ORIGINAL fragment finishes cleanly.
            SNAPSHOT_SCHEDULER.configure(bandwidth=0)
        f.await_snapshot()
        f.close()
        # -- restart on the crash copy -----------------------------------
        swept0 = _counter("snapshot_orphans_swept_total")
        f2 = _fragment(os.path.join(crash_dir, "0")).open()
        try:
            assert not os.path.exists(os.path.join(crash_dir, "0.snapshotting"))
            assert _counter("snapshot_orphans_swept_total") - swept0 == 1
            got = set(f2.row(1).columns().tolist())
            assert got == set(cols.tolist()) | {SHARD_WIDTH - 1}
        finally:
            f2.close()


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _http(port, method, path, body=None, timeout=10):
    data = body if isinstance(body, (bytes, type(None))) else json.dumps(body).encode()
    r = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=data, method=method
    )
    with urllib.request.urlopen(r, timeout=timeout) as resp:
        raw = resp.read()
    return json.loads(raw) if raw else {}


@pytest.mark.chaos
class TestCrashRecoverySubprocess:
    """The real thing: a server PROCESS, acknowledged imports, SIGKILL
    mid-churn, restart on the same data dir (extends the PR 4 chaos
    pattern to the write plane). Skips where subprocess networking is
    restricted — the in-process simulation above covers tier-1 there."""

    def _spawn(self, port, data_dir):
        env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
        return subprocess.Popen(
            [sys.executable, "-m", "pilosa_tpu.cli", "server",
             "-d", data_dir, "-b", f"127.0.0.1:{port}", "--executor", "cpu"],
            env=env, cwd=REPO,
            stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT,
        )

    def _wait_ready(self, proc, port, timeout=20) -> bool:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if proc.poll() is not None:
                return False
            try:
                _http(port, "GET", "/status", timeout=2)
                return True
            except (urllib.error.URLError, OSError):
                time.sleep(0.2)
        return False

    def test_sigkill_mid_ingest_recovers_acknowledged_writes(self, tmp_path):
        port = _free_port()
        data_dir = str(tmp_path / "node")
        proc = self._spawn(port, data_dir)
        try:
            if not self._wait_ready(proc, port):
                proc.kill()
                pytest.skip(
                    "subprocess server unavailable in this environment"
                )
            _http(port, "POST", "/index/i", {})
            _http(port, "POST", "/index/i/field/f", {})
            _http(port, "POST", "/index/i/field/v",
                  {"options": {"type": "int", "min": -1000, "max": 1000}})
            shadow_rows: dict[int, set] = {}
            shadow_vals: dict[int, int] = {}
            stop = threading.Event()
            rng = np.random.default_rng(31)

            def churn():
                while not stop.is_set():
                    rows = rng.integers(0, 4, 16).tolist()
                    cols = rng.integers(0, 2 * SHARD_WIDTH, 16).tolist()
                    vcols = rng.integers(0, SHARD_WIDTH, 8).tolist()
                    vals = rng.integers(-500, 500, 8).tolist()
                    try:
                        _http(port, "POST", "/index/i/field/f/import",
                              {"rowIDs": rows, "columnIDs": cols}, timeout=5)
                    except (urllib.error.URLError, OSError, ConnectionError):
                        return  # in-flight at the kill: unacknowledged
                    for r, c in zip(rows, cols):
                        shadow_rows.setdefault(r, set()).add(c)
                    try:
                        _http(port, "POST", "/index/i/field/v/import",
                              {"columnIDs": vcols, "values": vals}, timeout=5)
                    except (urllib.error.URLError, OSError, ConnectionError):
                        return
                    for c, val in zip(vcols, vals):
                        shadow_vals[c] = val

            t = threading.Thread(target=churn, daemon=True)
            t.start()
            time.sleep(2.0)  # real mid-churn kill, not a quiesced one
            proc.send_signal(signal.SIGKILL)
            proc.wait(timeout=10)
            stop.set()
            t.join(timeout=10)
            assert shadow_rows, "no acknowledged imports before the kill"
            # -- restart on the same data dir ----------------------------
            proc = self._spawn(port, data_dir)
            assert self._wait_ready(proc, port), "restart never became ready"
            for r, cols in shadow_rows.items():
                got = _http(port, "POST", "/index/i/query",
                            f"Count(Row(f={r}))".encode())
                assert got["results"][0] >= len(cols), r
                # >=: a batch acknowledged between the shadow update and
                # the kill can add bits; the acknowledged set is the
                # floor. Exact agreement for TopN ids below.
            got = _http(port, "POST", "/index/i/query", b"TopN(f)")
            assert {p["id"] for p in got["results"][0]} == set(shadow_rows)
            got = _http(port, "POST", "/index/i/query", b"Sum(field=v)")
            # The value shadow is last-write-wins per column; the count
            # must cover at least every acknowledged column.
            assert got["results"][0]["count"] >= len(shadow_vals)
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=10)
