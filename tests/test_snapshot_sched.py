"""Read/write plane isolation (ISSUE r19): the global snapshot
scheduler, paced (token-bucket) snapshot writes, orphaned-temp sweeping,
and the group-commit WAL drain that moves file I/O off the fragment
lock.

- Scheduler: a churn burst across 64 fragments never holds more than
  `snapshot-concurrency` rewrites in flight (the satellite regression),
  and the queue drains oldest-backlog-first.
- Pacing: the token bucket actually shapes write timing, uncapped is a
  no-op, and the abort probe breaks a mid-bucket wait promptly.
- Orphan sweep: Fragment.open() removes a `.snapshotting` temp a killed
  process left behind, counted and logged.
- Group commit: every mutator's staged WAL records are on disk before
  the mutator returns (ack-implies-on-disk survives the lock split).
"""

import os
import threading
import time

import numpy as np
import pytest

from pilosa_tpu.core.fragment import (
    MAX_OP_N,
    SNAPSHOT_SCHEDULER,
    Fragment,
    SnapshotScheduler,
)
from pilosa_tpu.utils.stats import global_stats


def _counter(name: str) -> float:
    snap = global_stats.snapshot()["counters"]
    return sum(v for k, v in snap.items() if k.startswith(name))


def _fragment(path: str, **kw) -> Fragment:
    return Fragment(path, "i", "f", "standard", 0, **kw)


@pytest.fixture(autouse=True)
def _restore_scheduler():
    """SNAPSHOT_SCHEDULER is process-global state: every test leaves it
    back at the defaults (concurrency 2, uncapped) no matter what it
    reconfigured."""
    yield
    SNAPSHOT_SCHEDULER.configure(concurrency=2, bandwidth=0)


class TestSnapshotScheduler:
    def test_churn_burst_never_exceeds_concurrency(self, tmp_path, monkeypatch):
        """The satellite regression: 64 fragments crossing MAX_OP_N at
        once must run at most `snapshot-concurrency` rewrites in flight
        — and every one of them must still run."""
        state = {"running": 0, "max": 0, "total": 0}
        gate = threading.Lock()

        def tracked_snapshot(self):
            with gate:
                state["running"] += 1
                state["max"] = max(state["max"], state["running"])
                state["total"] += 1
            time.sleep(0.002)
            with gate:
                state["running"] -= 1

        monkeypatch.setattr(Fragment, "_snapshot_once", tracked_snapshot)
        SNAPSHOT_SCHEDULER.configure(concurrency=2, bandwidth=0)
        frags = [
            _fragment(str(tmp_path / str(i) / "0")).open() for i in range(64)
        ]
        try:
            for f in frags:
                f.storage.op_n = MAX_OP_N  # the next write crosses the bound
                f.set_bit(1, 1)
            for f in frags:
                f.await_snapshot()
            assert state["total"] == 64
            assert state["max"] <= 2, state
        finally:
            for f in frags:
                f.close()

    def test_oldest_backlog_first(self, tmp_path, monkeypatch):
        """FIFO drain: with one worker parked on the first rewrite, the
        fragments queued behind it run in enqueue order."""
        order: list[int] = []
        started = threading.Event()
        release = threading.Event()

        def tracked_snapshot(self):
            order.append(self.uid)
            started.set()
            release.wait(5)

        monkeypatch.setattr(Fragment, "_snapshot_once", tracked_snapshot)
        SNAPSHOT_SCHEDULER.configure(concurrency=1, bandwidth=0)
        frags = [
            _fragment(str(tmp_path / str(i) / "0")).open() for i in range(4)
        ]
        try:
            frags[0].storage.op_n = MAX_OP_N
            frags[0].set_bit(1, 1)
            assert started.wait(5)  # worker is inside fragment 0's rewrite
            for f in frags[1:]:
                f.storage.op_n = MAX_OP_N
                f.set_bit(1, 1)
            release.set()
            for f in frags:
                f.await_snapshot()
            assert order == [f.uid for f in frags]
        finally:
            for f in frags:
                f.close()

    def test_close_cancels_queued_rewrite(self, tmp_path, monkeypatch):
        """close() on a fragment whose rewrite is still queued behind a
        busy worker dequeues it instead of waiting out the backlog."""
        ran: list[int] = []
        started = threading.Event()
        release = threading.Event()

        def tracked_snapshot(self):
            ran.append(self.uid)
            started.set()
            release.wait(5)

        monkeypatch.setattr(Fragment, "_snapshot_once", tracked_snapshot)
        SNAPSHOT_SCHEDULER.configure(concurrency=1, bandwidth=0)
        busy = _fragment(str(tmp_path / "busy" / "0")).open()
        queued = _fragment(str(tmp_path / "queued" / "0")).open()
        try:
            busy.storage.op_n = MAX_OP_N
            busy.set_bit(1, 1)
            assert started.wait(5)
            queued.storage.op_n = MAX_OP_N
            queued.set_bit(1, 1)
            t0 = time.monotonic()
            queued.close()  # must not wait for the parked worker
            assert time.monotonic() - t0 < 2.0
            assert queued.uid not in ran
        finally:
            release.set()
            busy.await_snapshot()
            busy.close()


class TestTokenBucketPacing:
    def test_bucket_paces_writes(self):
        s = SnapshotScheduler(concurrency=1, bandwidth=10 << 20)
        t0 = time.monotonic()
        s.throttle(512 << 10)
        s.throttle(512 << 10)
        dt = time.monotonic() - t0
        # 1 MiB at 10 MiB/s is ~0.1 s of bucket refill (loose bounds:
        # CI jitter must not flake this, but uncapped would be ~0).
        assert dt >= 0.06, dt
        assert dt < 3.0, dt

    def test_uncapped_is_immediate(self):
        s = SnapshotScheduler(concurrency=1, bandwidth=0)
        t0 = time.monotonic()
        s.throttle(100 << 20)
        assert time.monotonic() - t0 < 0.05

    def test_abort_probe_breaks_wait(self):
        # 1 KiB/s against a 1 MiB chunk is a ~17 min wait; the abort
        # probe (close()/shutdown) must break it at the next 50 ms slice.
        s = SnapshotScheduler(concurrency=1, bandwidth=1024)
        t0 = time.monotonic()
        s.throttle(1 << 20, aborted=lambda: True)
        assert time.monotonic() - t0 < 1.0

    def test_live_reconfigure_uncaps_mid_wait(self):
        s = SnapshotScheduler(concurrency=1, bandwidth=1024)
        done = threading.Event()

        def waiter():
            s.throttle(1 << 20)
            done.set()

        threading.Thread(target=waiter, daemon=True).start()
        time.sleep(0.05)
        s.configure(bandwidth=0)
        assert done.wait(2.0)


class TestOrphanSweep:
    def test_open_sweeps_orphaned_snapshot_temp(self, tmp_path):
        base = str(tmp_path / "frag" / "0")
        f = _fragment(base).open()
        f.set_bit(3, 7)
        f.close()
        orphan = base + ".snapshotting"
        with open(orphan, "wb") as fh:
            fh.write(b"torn partial snapshot left by a SIGKILL")
        swept0 = _counter("snapshot_orphans_swept_total")
        f2 = _fragment(base).open()
        try:
            assert not os.path.exists(orphan)
            assert _counter("snapshot_orphans_swept_total") - swept0 == 1
            # The sweep never touches the live file.
            assert f2.row(3).columns().tolist() == [7]
        finally:
            f2.close()


class TestWalGroupCommit:
    def test_mutators_drain_before_return(self, tmp_path):
        """The lock split stages WAL records under Fragment.lock and
        writes them after release — but still before the mutator
        returns, so an acknowledged write is always on disk."""
        base = str(tmp_path / "frag" / "0")
        f = _fragment(base).open()
        try:
            f.set_bit(1, 2)
            assert f._wal_pending == []
            size1 = os.path.getsize(base)
            assert size1 > 0
            cols = np.arange(10, dtype=np.uint64)
            f.bulk_import(np.full(cols.size, 2, dtype=np.uint64), cols)
            assert f._wal_pending == []
            assert os.path.getsize(base) > size1
        finally:
            f.close()

    def test_acked_writes_survive_fd_drop_without_close(self, tmp_path):
        """Durability proof for the staged path: drop the WAL fd with no
        close()/flush (the SIGKILL shape) right after the mutators
        return — every acknowledged record must already be on disk."""
        base = str(tmp_path / "frag" / "0")
        f = _fragment(base).open()
        cols = np.unique(
            np.random.default_rng(7).integers(0, 1 << 16, 500, dtype=np.uint64)
        )
        f.bulk_import(np.full(cols.size, 1, dtype=np.uint64), cols)
        f.set_bit(1, 1 << 17)
        f._file.release()  # abrupt: no drain, no flush, no close
        f2 = _fragment(base).open()
        try:
            got = set(f2.row(1).columns().tolist())
            assert got == set(cols.tolist()) | {1 << 17}
        finally:
            f2.close()
            f.close()
