"""In-process multi-node cluster harness (reference test/pilosa.go:88
MustRunCluster): n real servers in one process on ephemeral ports, static
topology (no gossip), deterministic ModHasher placement available for
tests that assert specific owners. FaultProxy + RewriteClient build
ASYMMETRIC network partitions (one node's outbound to one peer routed
through a refusable/blackholable real TCP proxy — the socket-level
analog of the reference's pumba container-pause harness,
internal/clustertests/cluster_test.go:68-92)."""

from __future__ import annotations

import shutil
import socket
import struct
import tempfile
import threading
import time

from pilosa_tpu.cluster import Cluster, InternalClient, Node, Topology, URI
from pilosa_tpu.cluster.topology import JmpHasher
from pilosa_tpu.core.holder import Holder
from pilosa_tpu.exec.executor import Executor
from pilosa_tpu.server.api import API
from pilosa_tpu.server.http import Server


class FaultProxy:
    """Real-TCP forwarder with injectable faults, per-connection:

    - mode 'pass': pipe bytes both ways to the target
    - mode 'refuse': close incoming connections immediately (RST-ish —
      the dialer sees an instant transport error)
    - mode 'blackhole': accept, read, never answer (the dialer blocks
      until its timeout — the one-sided-silence failure shape)
    - mode 'reset_once': hard-RST exactly ONE incoming connection
      (SO_LINGER 0 close — the client sees ConnectionResetError /
      BadStatusLine mid-exchange), then auto-revert to 'pass' so a
      retry with a fresh connection succeeds. The single-transient
      fault shape bench.py's capture-proof post() retry covers.
    - mode 'latency': pass, but delay the connection by latency_s
      before the first byte moves — the slow-but-healthy replica shape
      hedged reads exist for (ISSUE r9).
    - mode 'drop': each connection independently dies with probability
      drop_p (instant close), else passes — flaky-link shape for the
      client's idempotent-GET retry.

    close() joins the accept loop and closes every piped connection it
    spawned, so a chaos suite cycling many proxies cannot exhaust fds
    (ISSUE r9 satellite — the old close leaked established pipes until
    their peers hung up).
    """

    def __init__(self, target_host: str, target_port: int):
        self.target = (target_host, target_port)
        self.mode = "pass"
        self.latency_s = 0.2  # mode 'latency' delay
        self.drop_p = 0.5  # mode 'drop' per-connection death probability
        self._srv = socket.socket()
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind(("127.0.0.1", 0))
        self._srv.listen(32)
        self.port = self._srv.getsockname()[1]
        self._stop = threading.Event()
        # Every socket this proxy owns (accepted + upstream), so close()
        # can tear them down instead of leaking them to the peers' whim.
        self._conns: set = set()
        self._conns_lock = threading.Lock()
        self._thread = threading.Thread(target=self._accept_loop, daemon=True)
        self._thread.start()

    def _track(self, sock) -> None:
        with self._conns_lock:
            self._conns.add(sock)

    def _untrack(self, sock) -> None:
        with self._conns_lock:
            self._conns.discard(sock)

    def _accept_loop(self) -> None:
        import random as _random

        while not self._stop.is_set():
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            mode = self.mode
            if mode == "refuse" or (
                mode == "drop" and _random.random() < self.drop_p
            ):
                conn.close()
                continue
            if mode == "reset_once":
                # SO_LINGER(on, 0): close sends RST, not FIN — the
                # client's in-flight request dies with a reset instead
                # of a clean EOF. One-shot: revert before closing so
                # the retry's connection races nothing.
                self.mode = "pass"
                conn.setsockopt(
                    socket.SOL_SOCKET, socket.SO_LINGER,
                    struct.pack("ii", 1, 0),
                )
                conn.close()
                continue
            self._track(conn)
            threading.Thread(
                target=self._serve, args=(conn, mode), daemon=True
            ).start()

    def _serve(self, conn: socket.socket, mode: str) -> None:
        up = None
        try:
            if mode == "blackhole":
                conn.settimeout(0.2)
                while not self._stop.is_set():
                    try:
                        if not conn.recv(65536):
                            return  # peer gave up
                    except socket.timeout:
                        continue
                    except OSError:
                        return
                return
            if mode == "latency":
                # Hold the whole connection before any byte moves: the
                # dialer's connect() already succeeded, so this reads as
                # a slow peer, not a dead one.
                if self._stop.wait(self.latency_s):
                    return
            try:
                up = socket.create_connection(self.target, timeout=5)
            except OSError:
                return  # target gone: behaves like refuse
            self._track(up)

            def pipe(src, dst):
                try:
                    while True:
                        data = src.recv(65536)
                        if not data:
                            break
                        dst.sendall(data)
                except OSError:
                    pass
                finally:
                    for s in (src, dst):
                        try:
                            s.shutdown(socket.SHUT_RDWR)
                        except OSError:
                            pass

            t = threading.Thread(target=pipe, args=(up, conn), daemon=True)
            t.start()
            pipe(conn, up)
            t.join(timeout=5)
        finally:
            if up is not None:
                self._untrack(up)
                up.close()
            self._untrack(conn)
            conn.close()

    def close(self) -> None:
        self._stop.set()
        # shutdown() before close(): closing a listening socket from
        # another thread does NOT unblock a thread parked in accept() on
        # Linux — shutdown does, so the accept loop exits immediately
        # instead of the join below eating its whole timeout.
        try:
            self._srv.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._srv.close()
        except OSError:
            pass
        # Shut down (then close) the piped sockets: like the listener
        # above, close() alone leaves a pipe() thread parked in recv()
        # forever — shutdown unblocks it so it runs its cleanup path and
        # untracks itself.
        with self._conns_lock:
            conns = list(self._conns)
        for s in conns:
            for op in (lambda: s.shutdown(socket.SHUT_RDWR), s.close):
                try:
                    op()
                except OSError:
                    pass
        self._thread.join(timeout=5)


class SymmetricPartition:
    """Both directions between two TestCluster nodes blackholed with ONE
    call (ISSUE r15 satellite): a FaultProxy per direction plus
    RewriteClients installed on both nodes, so the chaos harness and the
    bench's partition_heal leg share one primitive. partition() flips
    both proxies to blackhole, heal() restores pass-through, close()
    tears both proxies down leak-proof (FaultProxy.close joins the
    accept loops and shuts every piped socket)."""

    def __init__(self, tc: "TestCluster", i: int = 0, j: int = 1,
                 timeout: float = 0.5):
        self.proxies = []
        self._restore = []
        for src, dst in ((tc[i], tc[j]), (tc[j], tc[i])):
            target = dst.node.uri
            proxy = FaultProxy(target.host, target.port)
            rc = RewriteClient(
                {f"{target.host}:{target.port}": f"127.0.0.1:{proxy.port}"},
                timeout=timeout,
            )
            self._restore.append(
                (src.cluster, src.cluster.client,
                 src.cluster.broadcaster.client)
            )
            src.cluster.client = rc
            src.cluster.broadcaster.client = rc
            # Piggyback folds keep working through the proxy: the
            # rewrite is at the dial hook, identity untouched.
            rc.on_peer_epochs = src.cluster.fold_peer_epochs
            self.proxies.append(proxy)

    def partition(self) -> None:
        for p in self.proxies:
            p.mode = "blackhole"

    def heal(self) -> None:
        for p in self.proxies:
            p.mode = "pass"

    def close(self) -> None:
        # Restore the clients we replaced BEFORE tearing the proxies
        # down: cross-node RPCs after the `with` block (post-heal
        # convergence waits, later fan-outs) must not dial dead proxy
        # ports — that reads as connection-refused far from its cause
        # and trips breakers.
        for cluster, client, bclient in self._restore:
            cluster.client = client
            cluster.broadcaster.client = bclient
        self._restore = []
        for p in self.proxies:
            p.close()

    def __enter__(self) -> "SymmetricPartition":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class RewriteClient(InternalClient):
    """InternalClient that dials selected peers through a FaultProxy:
    rewrites is the {'host:port': 'host:proxyport'} connection map. Node
    identity (URIs, ids) is untouched — only THIS node's outbound
    connections move, which is what makes the partition asymmetric.
    Rewrites happen at the dial hook, so peer_rpc_* tags and the circuit
    breaker stay keyed by the peer's REAL host:port — exactly what the
    routing layers (_routable_nodes, route_write*) look up."""

    def __init__(self, rewrites: dict, timeout: float = 0.5, **kw):
        super().__init__(timeout=timeout, **kw)
        self.rewrites = rewrites

    def _connect_uri(self, uri) -> str:
        u = super()._connect_uri(uri)
        scheme, _, hostport = u.partition("://")
        mapped = self.rewrites.get(hostport)
        if mapped is not None:
            return f"{scheme}://{mapped}"
        return u


class ClusterNode:
    def __init__(self, i: int, data_dir: str, backend_factory=None, tls=None):
        self.i = i
        self.data_dir = data_dir
        self.holder = Holder(data_dir).open()
        backend = backend_factory(i, self.holder) if backend_factory else None
        self.executor = Executor(self.holder, backend=backend)
        self.api = API(self.holder, self.executor)
        self.server = Server(self.api, host="127.0.0.1", port=0, tls=tls).open()
        self.node = Node(
            id=f"node{i}",
            uri=URI(scheme=self.server.scheme, host="127.0.0.1",
                    port=self.server.port),
            is_coordinator=(i == 0),
        )
        self.cluster = None  # attached by TestCluster

    def close(self) -> None:
        self.server.close()
        self.holder.close()


class TestCluster:
    """n wired nodes sharing one static topology."""

    __test__ = False  # not a pytest class

    def __init__(self, n: int, replica_n: int = 1, hasher=None,
                 backend_factory=None, tls=None, client_ssl=None):
        self._tmp = tempfile.mkdtemp(prefix="pilosa-tpu-cluster-")
        self._replica_n = replica_n
        self._hasher = hasher or JmpHasher()
        self._backend_factory = backend_factory
        self._tls = tls  # TLSConfig/SSLContext for every node's listener
        self._client_ssl = client_ssl  # peers' outbound ssl context
        self._next_i = n
        self.nodes: list[ClusterNode] = [
            ClusterNode(i, f"{self._tmp}/node{i}",
                        backend_factory=backend_factory, tls=tls)
            for i in range(n)
        ]
        members = [cn.node for cn in self.nodes]
        for cn in self.nodes:
            self._wire(cn, members)

    def _wire(self, cn: ClusterNode, members) -> None:
        from pilosa_tpu.cluster import InternalClient

        topo = Topology(
            nodes=[Node(m.id, m.uri, m.is_coordinator) for m in members],
            replica_n=self._replica_n,
            hasher=self._hasher,
        )
        cn.cluster = Cluster(
            local_node=topo.node_by_id(cn.node.id),
            topology=topo,
            holder=cn.holder,
            client=InternalClient(ssl_context=self._client_ssl)
            if self._client_ssl is not None
            else None,
        )
        cn.cluster.attach(cn.executor, cn.api)
        cn.api.cluster = cn.cluster
        cn.cluster.attach_resizer()

    def spawn_node(self) -> ClusterNode:
        """Boot a fresh empty node wired to see only itself (it learns the
        real topology from the resize instruction)."""
        i = self._next_i
        self._next_i += 1
        cn = ClusterNode(
            i, f"{self._tmp}/node{i}", backend_factory=self._backend_factory,
            tls=self._tls,
        )
        cn.node.is_coordinator = False
        self._wire(cn, [cn.node])
        self.nodes.append(cn)
        return cn

    def add_node_via_resize(self, timeout: float = 10.0) -> ClusterNode:
        """Grow the cluster through the coordinator's resize job and wait
        for the topology to converge everywhere."""
        cn = self.spawn_node()
        self.nodes[0].cluster.resizer.add_node(
            Node(cn.node.id, cn.node.uri, False)
        )
        deadline = time.time() + timeout
        while time.time() < deadline:
            if all(
                len(x.cluster.topology.nodes) == len(self.nodes)
                and x.cluster.state() == "NORMAL"
                for x in self.nodes
            ):
                return cn
            time.sleep(0.02)
        states = [(x.node.id, x.cluster.state(), len(x.cluster.topology.nodes)) for x in self.nodes]
        raise TimeoutError(f"resize never converged: {states}")

    def sync_all(self) -> int:
        """One synchronous anti-entropy pass on every node."""
        from pilosa_tpu.cluster.sync import HolderSyncer

        repaired = 0
        for cn in self.nodes:
            syncer = HolderSyncer(cn.cluster)
            repaired += syncer.sync_holder()
            syncer._sync_translation()
        return repaired

    def __getitem__(self, i: int) -> ClusterNode:
        return self.nodes[i]

    def __len__(self) -> int:
        return len(self.nodes)

    def create_index(self, name: str, options=None) -> None:
        self.nodes[0].api.create_index(name, options)

    def create_field(self, index: str, field: str, options=None) -> None:
        self.nodes[0].api.create_field(index, field, options)

    def query(self, i: int, index: str, pql: str) -> dict:
        return self.nodes[i].api.query(index, pql)

    def await_shard_convergence(self, index: str, timeout: float = 5.0) -> None:
        """Wait until every node reports the same available-shard set."""
        deadline = time.time() + timeout
        while time.time() < deadline:
            sets = []
            for cn in self.nodes:
                idx = cn.holder.index(index)
                sets.append(
                    tuple(idx.available_shards().to_array().tolist()) if idx else ()
                )
            if len(set(sets)) == 1:
                return
            time.sleep(0.02)
        raise TimeoutError(f"shards never converged: {sets}")

    def close(self) -> None:
        for cn in self.nodes:
            try:
                cn.close()
            except Exception:
                pass
        shutil.rmtree(self._tmp, ignore_errors=True)

    def __enter__(self) -> "TestCluster":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
