"""error-code fixture: JSON error replies bypassing the code contract."""


class Handler:
    def _reply(self, obj, status=200, content_type="application/json",
               headers=None):
        pass

    def handle_no_code(self):
        # BAD: 500 JSON body without a literal "code" field.
        self._reply({"error": "boom"}, status=500)

    def handle_retryable_bypass(self):
        # BAD: 503 outside _error loses the Retry-After contract.
        self._reply({"error": "down", "code": "unavailable"}, status=503)

    def handle_ok_proto(self):
        # fine: non-JSON content type is exempt.
        self._reply(b"\x00", status=500, content_type="application/x-protobuf")

    def handle_ok(self):
        self._reply({"ok": True})
