"""Seeded durable-write violations (tests/test_lint.py asserts the
checker fires on each): a truncating rewrite with no os.replace, and a
buffered append outside the OpWriter idiom. The waivered site and the
two compliant functions must NOT fire."""

import json
import os


def bad_truncating_write(path, meta):
    # VIOLATION: a crash mid-write leaves a torn file the next open
    # refuses — no tmp + os.replace.
    with open(path, "w") as f:
        json.dump(meta, f)


def bad_buffered_append(path, record):
    # VIOLATION: a buffered append can tear a record across the crash
    # boundary in ways torn-tail recovery was never specified for.
    with open(path, "ab") as f:
        f.write(record)


def waivered_write(path, data):
    # lint: allow-durable-write(fixture: demonstrates a consumed waiver)
    with open(path, "wb") as f:
        f.write(data)


def good_atomic_rewrite(path, meta):
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(meta, f)
    os.replace(tmp, path)


def good_wal_append(path, record):
    with open(path, "ab", buffering=0) as f:
        f.write(record)
