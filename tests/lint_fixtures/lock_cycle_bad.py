"""lock-discipline fixture: a seeded AB/BA deadlock + sleep under lock.

Thread 1 runs ab() (holds X, wants Y); thread 2 runs ba() (holds Y,
wants X) — the classic interleaving deadlock the static graph must
flag as a cycle.
"""

import threading
import time

_lock_x = threading.Lock()
_lock_y = threading.Lock()


def ab():
    with _lock_x:
        with _lock_y:
            return 1


def ba():
    with _lock_y:
        with _lock_x:
            return 2


def slow_under_lock():
    with _lock_x:
        time.sleep(0.1)  # BAD: every other acquirer stalls behind this
