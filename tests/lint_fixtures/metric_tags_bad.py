"""metric-tags fixture: cardinality bombs in with_tags arguments."""


def emit(stats, query: str, url: str, peer: str):
    # BAD: unknown tag key (not in the documented vocabulary).
    stats.with_tags("shardset:everything").count("fixture_total")
    # BAD: raw request content as a tag value.
    stats.with_tags(f"node:{url}").count("fixture_total")
    # fine: documented key, bounded value.
    stats.with_tags(f"peer:{peer}").count("fixture_total")
