"""Known-bad fixture for the config-drift rule (never lint-gated).

A miniature server/config.py shape: `wired` round-trips every surface,
`broken` is parseable from TOML but misses the env var, to_dict,
toml_text, cli wiring and the docs row — one finding per missing
surface. tests/test_lint.py feeds this text through
config_drift_findings() with a stub cli/doc.
"""


class Config:
    wired: int = 0
    broken: str = ""

    def _apply_toml(self, data):
        simple = {"wired": "wired", "broken": "broken"}
        for key, attr in simple.items():
            if key in data:
                setattr(self, attr, data[key])

    def _apply_env(self, env):
        mapping = {"PILOSA_TPU_WIRED": ("wired", int)}
        for key, (attr, conv) in mapping.items():
            if key in env:
                setattr(self, attr, conv(env[key]))

    def to_dict(self):
        return {"wired": self.wired}

    def toml_text(self):
        c = self
        return f"wired = {c.wired}\n"
