"""jax-dispatch fixture: host-sync and recompile hazards."""

import jax
import jax.numpy as jnp

# BAD: jnp work at module import time.
_TABLE = jnp.arange(128)


def hot_path(x):
    # BAD: jit compiled and invoked inline — re-traces every call.
    y = jax.jit(lambda a: a + 1)(x)
    # BAD: per-element host sync.
    return y[0].item()


def serve_batch(backend, calls):
    # BAD: raw occupancy shape into a batched entry point.
    return backend.count_batch_async(calls, len(calls))


def good_builder(body):
    # fine: builder returns the program; callers memoize.
    return jax.jit(body)
