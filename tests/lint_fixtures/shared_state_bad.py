"""Known-bad fixture for the shared-state rule (never lint-gated).

Two real races the rule must fire on:
- `Daemon.counter`: an unlocked `+=` reached from both the daemon
  thread root (_run) and the http-request root (do_GET -> bump).
- module global `_hits`: an unlocked RMW from the same two roots.

Two blessed patterns it must NOT fire on:
- `Daemon.published`: assigned once in start() BEFORE the thread
  starts (setup code no root reaches) and only read afterwards.
- `Daemon.guarded`: every access path holds self._lock.
"""

import threading

_hits = 0


def count_hit():
    global _hits
    _hits = _hits + 1  # BAD: two-root RMW on a module global


class Daemon:
    def __init__(self):
        self._lock = threading.Lock()
        self.counter = 0
        self.guarded = 0
        self.published = ()
        self._thread = threading.Thread(target=self._run, daemon=True)

    def start(self):
        # Blessed: assign-once before thread start (publication).
        self.published = ("a", "b")
        self._thread.start()

    def _run(self):
        while True:
            self.counter += 1  # BAD: unlocked RMW, also written by bump()
            count_hit()
            with self._lock:
                self.guarded += 1  # OK: same lock on every access path
            for item in self.published:  # OK: immutable publish
                str(item)

    def bump(self):
        self.counter += 1
        count_hit()
        with self._lock:
            self.guarded += 1


_DAEMON = Daemon()


class Handler:
    def do_GET(self):
        _DAEMON.bump()
