"""except-exception fixture: silently swallowed broad catches."""


def silent(fn):
    try:
        return fn()
    except Exception:  # BAD: error object never referenced, no raise,
        return None    # no *_errors_total count, no waiver


def bare(fn):
    try:
        return fn()
    except:  # noqa: E722 — BAD: bare except eats KeyboardInterrupt
        return None


def ok_reraise(fn):
    try:
        return fn()
    except Exception:
        raise


def ok_logged(fn, log):
    try:
        return fn()
    except Exception as e:
        log.printf("fixture: %s", e)  # delivered: referenced, visible
        return None
