"""monotonic-time fixture: wall clock fed into duration arithmetic."""

import time


def elapsed(t0: float) -> float:
    return time.time() - t0  # BAD: NTP step changes the "duration"


def deadline_in(seconds: float) -> float:
    return time.time() + seconds  # BAD: wall-clock deadline


def stamped() -> float:
    # GOOD: a reasoned waiver — test_lint asserts it is consumed.
    return time.time()  # lint: allow-monotonic-time(fixture epoch stamp)
