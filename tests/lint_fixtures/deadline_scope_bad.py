"""Known-bad fixture for the deadline-scope rule (never lint-gated).

A daemon thread root reaches an InternalClient method two ways: one
call is wrapped in `with deadline_scope(...)` (compliant), the other is
bare (the finding the rule must fire on).
"""

import threading


class Deadline:
    def __init__(self, seconds):
        self.seconds = seconds


class deadline_scope:
    def __init__(self, deadline):
        self.deadline = deadline

    def __enter__(self):
        return self.deadline

    def __exit__(self, *exc):
        return False


class InternalClient:
    def _do(self, method, uri, path):
        return {}

    def status(self, uri):
        return self._do("GET", uri, "/status")


class Prober:
    def __init__(self, client):
        self.client = client
        self._thread = threading.Thread(target=self._run, daemon=True)

    def _run(self):
        self.client.status("peer:1")  # BAD: no deadline scope on the path
        self._covered()

    def _covered(self):
        with deadline_scope(Deadline(1.0)):
            return self.client.status("peer:1")  # OK: budgeted
