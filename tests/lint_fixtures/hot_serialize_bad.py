"""Seeded hot-serialize violations (tests/test_lint.py asserts the
checker fires on each): a .tolist() in a result path, and a per-element
int(...) comprehension over array data. The waivered site and the
vectorized/scalar-source functions must NOT fire."""

import json


def bad_tolist(row):
    # VIOLATION: one PyLong boxed per column, then json walks them all.
    return json.dumps({"columns": row.columns().tolist()})


def bad_int_loop(row):
    # VIOLATION: per-element re-boxing of array data.
    return [int(c) for c in row.columns()]


def waivered_inventory(idx):
    # lint: allow-hot-serialize(fixture: demonstrates a consumed waiver)
    return idx.available_shards().to_array().tolist()


def good_vectorized(row):
    from pilosa_tpu.utils.fastjson import encode_uints

    return b'{"columns": [' + encode_uints(row.columns()) + b"]}"


def good_scalar_source(raw):
    # Parsing a query string: the source is not array data.
    return [int(s) for s in raw.split(",")]
