"""waiver-syntax / unused-waiver fixture."""

import time


def fine() -> int:
    # BAD (waiver-syntax): waiver without a reason.
    x = 1  # lint: allow-monotonic-time
    # BAD (waiver-syntax): waiver naming an unknown rule.
    y = 2  # lint: allow-made-up-rule(whatever)
    # BAD (unused-waiver): nothing on this line violates the rule.
    z = 3  # lint: allow-except-exception(stale permission)
    return x + y + z


def used() -> float:
    return time.time()  # lint: allow-monotonic-time(consumed by design)
