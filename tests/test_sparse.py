"""Packed stack-upload wire format (ops/sparse.py, VERDICT r4 #1):
host compress vs the numpy fallback, device decompress round-trips, the
chunked streaming builder against a plain dense put, and the
_StackedBlocks integration differential (sparse-built stacks must serve
bit-identical query results)."""

import numpy as np
import pytest

import jax

from pilosa_tpu.core import Holder
from pilosa_tpu.ops import sparse
from pilosa_tpu.shardwidth import SHARD_WIDTH


@pytest.fixture
def small_chunks(monkeypatch):
    """Shrink the chunk geometry so tests exercise multi-chunk stacks in
    milliseconds. Program caches are keyed by CHUNK_WORDS, so shrunken
    programs never collide with full-size ones. Compiles (blocking) the
    small decompress programs so the builders' warm-gate passes and the
    sparse path is actually exercised."""
    monkeypatch.setattr(sparse, "CHUNK_WORDS", 1 << 12)
    monkeypatch.setattr(
        sparse, "BUCKETS",
        ((1 << 12) // 32, (1 << 12) // 16, (1 << 12) // 8, (1 << 12) // 4),
    )
    monkeypatch.setattr(sparse, "MIN_CHUNKED_WORDS", 2 * (1 << 12))
    for b in sparse.BUCKETS:
        sparse._chunk_prog(None, b)
    # Container-tier expansion programs (ISSUE r7): compiled so the
    # warm-gate opens and feed_fragment actually ships containers.
    sparse._chunk_zeros_prog(None)
    sparse._or_prog(None)
    sparse._pos_prog(None)
    sparse._run_prog(None)
    assert sparse.container_progs_ready(None)


class TestCompressChunk:
    def _chunk(self, rng, density, n=1 << 12):
        chunk = np.zeros(n, dtype=np.uint32)
        nnz = int(n * density)
        if nnz:
            pos = rng.choice(n, size=nnz, replace=False)
            chunk[pos] = rng.integers(1, 2**32, size=nnz, dtype=np.uint32)
        return chunk

    def test_native_matches_fallback(self, rng, small_chunks):
        from pilosa_tpu import native

        orig = native.compress_words
        for density in (0.0, 0.01, 0.2, 0.9):
            chunk = self._chunk(rng, density)
            m1, v1, n1 = sparse.compress_chunk(chunk)
            try:
                native.compress_words = lambda *a: None
                m2, v2, n2 = sparse.compress_chunk(chunk)
            finally:
                native.compress_words = orig
            np.testing.assert_array_equal(m1, m2)
            np.testing.assert_array_equal(v1[:n1], v2[:n2])
            assert n1 == n2 == int((chunk != 0).sum())

    def test_mask_bit_order(self, small_chunks):
        chunk = np.zeros(1 << 12, dtype=np.uint32)
        chunk[0] = 7       # word 0 -> bit 0 of mask[0]
        chunk[33] = 9      # word 33 -> bit 1 of mask[1]
        mask, vals, nnz = sparse.compress_chunk(chunk)
        assert nnz == 2
        assert mask[0] == 1 and mask[1] == 2
        np.testing.assert_array_equal(vals[:2], [7, 9])

    def test_device_roundtrip(self, rng, small_chunks):
        dev = None
        for density in (0.005, 0.1, 0.24):
            chunk = self._chunk(rng, density)
            mask, vals, nnz = sparse.compress_chunk(chunk)
            bucket = sparse.pick_bucket(nnz)
            assert bucket is not None
            pv = np.zeros(bucket, dtype=np.uint32)
            pv[:nnz] = vals[:nnz]
            out = sparse._chunk_prog(dev, bucket)(
                jax.device_put(mask, dev), jax.device_put(pv, dev)
            )
            np.testing.assert_array_equal(np.asarray(out), chunk)

    def test_pick_bucket_menu(self, small_chunks):
        c = sparse.CHUNK_WORDS
        assert sparse.pick_bucket(0) == c // 32
        assert sparse.pick_bucket(c // 32) == c // 32
        assert sparse.pick_bucket(c // 32 + 1) == c // 16
        assert sparse.pick_bucket(c // 4) == c // 4
        assert sparse.pick_bucket(c // 4 + 1) is None  # dense fallback


class TestChunkedStackBuilder:
    def _roundtrip(self, host):
        b = sparse.ChunkedStackBuilder(None, host.shape)
        flat = host.reshape(-1)
        # ragged feeds: the builder must handle arbitrary slab sizes
        step = max(1, flat.size // 7)
        for i in range(0, flat.size, step):
            b.feed(flat[i : i + step])
        out = b.finish()
        assert out.shape == host.shape
        np.testing.assert_array_equal(np.asarray(out), host)

    def test_sparse_stack(self, rng, small_chunks):
        host = np.zeros((4, 8, 512), dtype=np.uint32)
        pos = rng.choice(host.size, size=host.size // 20, replace=False)
        host.reshape(-1)[pos] = 1 + pos.astype(np.uint32)
        b = sparse.ChunkedStackBuilder(None, host.shape)
        b.feed(host.reshape(-1))
        out = b.finish()
        np.testing.assert_array_equal(np.asarray(out), host)
        # The warm-gate was open (fixture compiled the programs), so the
        # wire really was packed: mask + smallest-bucket values per
        # chunk, well under the dense bytes.
        assert 0 < b._wire_bytes < b._dense_bytes // 2

    def test_dense_stack_falls_back_per_chunk(self, rng, small_chunks):
        host = rng.integers(0, 2**32, size=(3, 8, 512), dtype=np.uint32)
        self._roundtrip(host)

    def test_all_zero_stack_ships_nothing(self, small_chunks):
        host = np.zeros((4, 8, 512), dtype=np.uint32)
        b = sparse.ChunkedStackBuilder(None, host.shape)
        b.feed(host.reshape(-1))
        out = b.finish()
        assert b._wire_bytes == 0
        np.testing.assert_array_equal(np.asarray(out), host)

    def test_partial_tail_chunk(self, rng, small_chunks):
        # 4*8*512 = 16384 words = 4 chunks exactly; (5, 8, 400) is not
        # chunk-aligned -> exercises the padded tail.
        host = np.zeros((5, 8, 400), dtype=np.uint32)
        host[4, 7, 399] = 0xDEADBEEF
        host[0, 0, 0] = 3
        self._roundtrip(host)

    def test_mixed_density_chunks(self, rng, small_chunks):
        # one dense region, one sparse, one empty -> per-chunk decisions
        host = np.zeros((6, 8, 512), dtype=np.uint32)
        host[0] = rng.integers(0, 2**32, size=(8, 512), dtype=np.uint32)
        host[3, 2, 17] = 42
        self._roundtrip(host)


def _counter(name: str) -> float:
    from pilosa_tpu.utils.stats import global_stats

    return global_stats._counters.get((name, ()), 0)


class TestContainerWire:
    """Roaring-container wire tier (ISSUE r7): feed_fragment must build
    bit-identical stacks to the dense pack while shipping 16-bit
    positions / run spans instead of dense words."""

    def _fragment(self, rng, n_rows=4, density_bits=3000, runs=False,
                  bitmap=False):
        from pilosa_tpu.core import Holder
        from pilosa_tpu.core.view import VIEW_STANDARD
        from pilosa_tpu.roaring.bitmap import Container

        h = Holder(None).open()
        f = h.create_index("i").create_field("f")
        cols = np.unique(
            rng.integers(0, SHARD_WIDTH, density_bits, dtype=np.uint64)
        )
        f.import_bits(
            rng.integers(0, n_rows, cols.size, dtype=np.uint64), cols
        )
        fr = f.view(VIEW_STANDARD).fragment(0)
        if runs:
            # plant a run container directly (the time-quantum shape)
            fr.storage.put_container(
                1, Container.from_runs(
                    np.array([[0, 5000], [5002, 5002], [60000, 65535]],
                             dtype=np.int64)
                )
            )
        if bitmap:
            # force a bitmap container: > 4096 positions in one slot
            pos = np.unique(
                rng.integers(0, 65536, 9000).astype(np.uint16)
            )
            fr.storage.put_container(2, Container.from_positions(pos))
        return h, fr

    def _build(self, fr, rows_p, n_shards=4):
        from pilosa_tpu.ops.blocks import WORDS_PER_SHARD, pack_fragment

        shape = (n_shards, rows_p, WORDS_PER_SHARD)
        b = sparse.ChunkedStackBuilder(None, shape)
        b.feed_fragment(fr, rows_p)
        b.skip((n_shards - 1) * rows_p * WORDS_PER_SHARD)
        out = np.asarray(b.finish())
        ref = np.zeros(shape, dtype=np.uint32)
        ref[0] = pack_fragment(fr, n_rows=rows_p)
        return b, out, ref

    def test_array_containers_roundtrip(self, rng, small_chunks):
        h, fr = self._fragment(rng)
        try:
            before = _counter("stack_container_chunks_total")
            b, out, ref = self._build(fr, 8)
            np.testing.assert_array_equal(out, ref)
            assert _counter("stack_container_chunks_total") > before
            assert 0 < b._wire_bytes < b._dense_bytes
        finally:
            h.close()

    def test_run_and_bitmap_containers_roundtrip(self, rng, small_chunks):
        h, fr = self._fragment(rng, runs=True, bitmap=True)
        try:
            runs_before = _counter("stack_container_runs_total")
            b, out, ref = self._build(fr, 8)
            np.testing.assert_array_equal(out, ref)
            assert _counter("stack_container_runs_total") > runs_before
        finally:
            h.close()

    def test_disabled_tier_matches_and_ships_dense(self, rng, small_chunks,
                                                   monkeypatch):
        h, fr = self._fragment(rng, runs=True)
        try:
            monkeypatch.setattr(sparse, "CONTAINER_TIER_ENABLED", False)
            before = _counter("stack_container_chunks_total")
            _, out, ref = self._build(fr, 8)
            np.testing.assert_array_equal(out, ref)
            assert _counter("stack_container_chunks_total") == before
        finally:
            h.close()

    def test_not_warm_falls_back_dense(self, rng, small_chunks, monkeypatch):
        # Close the warm-gate: container chunks must materialize dense
        # (correct, just not container-wired) instead of compiling
        # inline on the cold path.
        h, fr = self._fragment(rng)
        try:
            monkeypatch.setattr(sparse, "container_progs_ready",
                                lambda device: False)
            before = _counter("stack_container_chunks_total")
            _, out, ref = self._build(fr, 8)
            np.testing.assert_array_equal(out, ref)
            assert _counter("stack_container_chunks_total") == before
        finally:
            h.close()

    def test_pending_bytes_bound_drains_early(self, rng, small_chunks,
                                              monkeypatch):
        # ADVICE r5 #2: with a tiny in-flight bound, the builder must
        # fold pending chunks into the accumulator mid-build instead of
        # holding every chunk's buffers until finish().
        monkeypatch.setattr(sparse, "MAX_PENDING_BYTES", 1 << 12)
        host = rng.integers(0, 2**32, size=(6, 8, 512), dtype=np.uint32)
        drains_before = _counter("stack_pending_drains_total")
        b = sparse.ChunkedStackBuilder(None, host.shape)
        b.feed(host.reshape(-1))
        assert _counter("stack_pending_drains_total") > drains_before
        assert b._pending_bytes <= (1 << 12) + sparse.CHUNK_WORDS * 4
        out = b.finish()
        np.testing.assert_array_equal(np.asarray(out), host)

    def test_skip_regions_are_zero(self, rng, small_chunks):
        from pilosa_tpu.ops.blocks import WORDS_PER_SHARD

        shape = (3, 8, 512)
        b = sparse.ChunkedStackBuilder(None, shape)
        slab = rng.integers(0, 2**32, size=8 * 512, dtype=np.uint32)
        b.feed(slab)
        b.skip(8 * 512)
        b.feed(slab)
        out = np.asarray(b.finish())
        np.testing.assert_array_equal(out[0].reshape(-1), slab)
        assert not out[1].any()
        np.testing.assert_array_equal(out[2].reshape(-1), slab)


class TestStackedBlocksSparseBuild:
    def test_query_differential_through_chunked_build(self, rng, small_chunks,
                                                      monkeypatch, tmp_path):
        """A backend whose stacks went through the chunked sparse path
        must answer bit-identically to the CPU oracle."""
        from pilosa_tpu.exec import Executor
        from pilosa_tpu.exec import tpu as tpu_mod
        from pilosa_tpu.exec.tpu import TPUBackend
        from pilosa_tpu.utils.stats import global_stats

        monkeypatch.setattr(tpu_mod, "MIN_CHUNKED_WORDS",
                            sparse.MIN_CHUNKED_WORDS)
        h = Holder(str(tmp_path / "d")).open()
        try:
            idx = h.create_index("i")
            for fn in ("f", "g"):
                f = idx.create_field(fn)
                for s in range(3):
                    cols = np.unique(
                        rng.integers(0, SHARD_WIDTH, 2000, dtype=np.uint64)
                    ) + s * SHARD_WIDTH
                    f.import_bits(
                        rng.integers(0, 4, cols.size, dtype=np.uint64), cols
                    )
            n0 = global_stats._counters.get(
                ("stack_sparse_uploads_total", ()), 0
            )
            dev = Executor(h, backend=TPUBackend(h))
            host = Executor(h)
            queries = [
                "Count(Row(f=1))",
                "Count(Intersect(Row(f=0), Row(g=2)))",
                "Count(Union(Row(f=3), Row(g=1)))",
                "TopN(f, n=4)",
                "GroupBy(Rows(f), Rows(g))",
            ]
            for q in queries:
                assert dev.execute("i", q) == host.execute("i", q), q
            # The stacks really went through the chunked path.
            assert global_stats._counters.get(
                ("stack_sparse_uploads_total", ()), 0
            ) > n0
        finally:
            h.close()
