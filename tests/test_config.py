"""Server config: three sources with later-wins precedence, TOML
round-trip through generate-config, and option wiring (reference
server/config.go + docs/configuration.md)."""

import pytest

from pilosa_tpu.server.config import Config

try:  # py3.11+; the env/flag tests below still run on 3.10 (the module
    import tomllib  # import is gated the same way in server/config.py)
except ModuleNotFoundError:
    tomllib = None

needs_tomllib = pytest.mark.skipif(
    tomllib is None, reason="tomllib needs Python 3.11+"
)


class TestSources:
    def test_defaults(self):
        cfg = Config.from_sources(env={})
        assert cfg.bind == "localhost:10101"
        assert cfg.executor == "tpu"
        assert cfg.max_hbm_bytes == 0
        assert cfg.client_timeout == 30.0

    @needs_tomllib
    def test_toml_then_env_then_flags(self, tmp_path):
        p = tmp_path / "c.toml"
        p.write_text(
            'bind = "host1:1"\nexecutor = "cpu"\nmax-hbm-bytes = 123\n'
            "[cluster]\nreplicas = 2\n"
        )
        cfg = Config.from_sources(
            toml_path=str(p),
            env={"PILOSA_TPU_BIND": "host2:2", "PILOSA_TPU_MAX_HBM_BYTES": "456"},
            args={"bind": "host3:3"},
        )
        assert cfg.bind == "host3:3"  # flag beats env beats toml
        assert cfg.max_hbm_bytes == 456  # env beats toml
        assert cfg.executor == "cpu"  # toml beats default
        assert cfg.cluster.replicas == 2

    def test_env_cluster_hosts(self):
        cfg = Config.from_sources(env={"PILOSA_TPU_CLUSTER_HOSTS": "a:1,b:2"})
        assert cfg.cluster.hosts == ["a:1", "b:2"]

    def test_bind_forms(self):
        for bind, want in [
            ("h:9", ("h", 9)),
            (":9", ("localhost", 9)),
            ("h", ("h", 10101)),
            ("[::1]:9", ("::1", 9)),
            ("::1", ("::1", 10101)),
        ]:
            cfg = Config.from_sources(env={}, args={"bind": bind})
            assert (cfg.host, cfg.port) == want, bind


class TestRoundTrip:
    @needs_tomllib
    def test_generate_config_reparses_to_same_values(self, tmp_path):
        cfg = Config.from_sources(env={})
        cfg.max_hbm_bytes = 789
        cfg.long_query_time = 1.5
        text = cfg.toml_text()
        data = tomllib.loads(text)
        assert data["max-hbm-bytes"] == 789
        p = tmp_path / "gen.toml"
        p.write_text(text)
        cfg2 = Config.from_sources(toml_path=str(p), env={})
        assert cfg2.max_hbm_bytes == 789
        assert cfg2.long_query_time == 1.5
        assert cfg2.to_dict() == cfg.to_dict()


class TestPlaneIsolationKnobs:
    """ISSUE r19 knobs (snapshot-bandwidth / snapshot-concurrency /
    refresh-window-ms / ingest-derate): every source and sink agrees —
    the config-drift contract, pinned per-knob here."""

    def test_defaults(self):
        cfg = Config.from_sources(env={})
        assert cfg.snapshot_bandwidth == 0       # uncapped
        assert cfg.snapshot_concurrency == 2
        assert cfg.refresh_window_ms == 0        # windowing off
        assert cfg.ingest_derate is True

    def test_env(self):
        cfg = Config.from_sources(env={
            "PILOSA_TPU_SNAPSHOT_BANDWIDTH": "1048576",
            "PILOSA_TPU_SNAPSHOT_CONCURRENCY": "4",
            "PILOSA_TPU_REFRESH_WINDOW_MS": "50",
            "PILOSA_TPU_INGEST_DERATE": "false",
        })
        assert cfg.snapshot_bandwidth == 1 << 20
        assert cfg.snapshot_concurrency == 4
        assert cfg.refresh_window_ms == 50
        assert cfg.ingest_derate is False
        d = cfg.to_dict()
        assert d["snapshot-bandwidth"] == 1 << 20
        assert d["snapshot-concurrency"] == 4
        assert d["refresh-window-ms"] == 50
        assert d["ingest-derate"] is False

    @needs_tomllib
    def test_toml_text_round_trip(self, tmp_path):
        cfg = Config.from_sources(env={})
        cfg.snapshot_bandwidth = 8 << 20
        cfg.snapshot_concurrency = 3
        cfg.refresh_window_ms = 25
        cfg.ingest_derate = False
        p = tmp_path / "gen.toml"
        p.write_text(cfg.toml_text())
        cfg2 = Config.from_sources(toml_path=str(p), env={})
        assert cfg2.snapshot_bandwidth == 8 << 20
        assert cfg2.snapshot_concurrency == 3
        assert cfg2.refresh_window_ms == 25
        assert cfg2.ingest_derate is False
        assert cfg2.to_dict() == cfg.to_dict()
