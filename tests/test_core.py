"""Core storage tests: fragment bit/BSI ops, durability, field/view/index
hierarchy, time quantum views. Mirrors the layered strategy of the
reference's fragment_internal_test.go / field_internal_test.go (SURVEY §4)
with numpy oracles for BSI differential checks."""

import datetime as dt

import numpy as np
import pytest

from pilosa_tpu.core import Field, FieldOptions, Fragment, Holder, Index, Row
from pilosa_tpu.core.field import (
    options_for_bool,
    options_for_int,
    options_for_mutex,
    options_for_time,
)
from pilosa_tpu.core.fragment import MAX_OP_N
from pilosa_tpu.core.timequantum import views_by_time, views_by_time_range
from pilosa_tpu.shardwidth import SHARD_WIDTH


def mem_fragment(**kw):
    return Fragment(None, "i", "f", "standard", 0, **kw)


class TestFragmentBits:
    def test_set_clear_row(self):
        f = mem_fragment()
        assert f.set_bit(0, 100)
        assert not f.set_bit(0, 100)
        assert f.set_bit(3, 100)
        assert f.set_bit(3, 200)
        np.testing.assert_array_equal(f.row(3).columns(), [100, 200])
        assert f.clear_bit(3, 100)
        np.testing.assert_array_equal(f.row(3).columns(), [200])
        assert f.row_count(3) == 1
        assert f.max_row_id == 3
        assert f.row_ids() == [0, 3]

    def test_shard_relative_columns(self):
        f = Fragment(None, "i", "f", "standard", 2)
        col = 2 * SHARD_WIDTH + 5
        f.set_bit(1, col)
        np.testing.assert_array_equal(f.row(1).columns(), [col])

    def test_mutex(self):
        f = mem_fragment(mutex=True)
        f.set_bit(1, 50)
        f.set_bit(2, 50)  # must clear row 1's bit
        assert f.row(1).count() == 0
        np.testing.assert_array_equal(f.row(2).columns(), [50])

    def test_clear_row_and_set_row(self):
        f = mem_fragment()
        f.bulk_import(np.array([1, 1, 1]), np.array([10, 20, 30]))
        assert f.clear_row(1)
        assert f.row(1).count() == 0
        r = Row([5, 6])
        f.set_row(r, 2)
        np.testing.assert_array_equal(f.row(2).columns(), [5, 6])

    def test_bulk_import_and_cache(self):
        f = mem_fragment()
        rows = np.array([7] * 1000 + [8] * 500, dtype=np.uint64)
        cols = np.arange(1500, dtype=np.uint64)
        f.bulk_import(rows, cols)
        assert f.row_count(7) == 1000
        assert f.row_count(8) == 500
        top = f.top(n=2)
        assert [(p.id, p.count) for p in top] == [(7, 1000), (8, 500)]

    def test_bulk_import_mutex(self):
        f = mem_fragment(mutex=True)
        f.bulk_import(np.array([1, 2]), np.array([9, 9]))  # last wins
        assert f.row(1).count() == 0
        np.testing.assert_array_equal(f.row(2).columns(), [9])

    def test_import_roaring(self):
        from pilosa_tpu.roaring import Bitmap, serialize

        f = mem_fragment()
        bm = Bitmap(np.array([5, 10, SHARD_WIDTH + 3], dtype=np.uint64))  # rows 0 and 1
        changed = f.import_roaring(serialize(bm))
        assert changed == 3
        np.testing.assert_array_equal(f.row(0).columns(), [5, 10])
        np.testing.assert_array_equal(f.row(1).columns(), [3])


class TestFragmentBSI:
    @pytest.mark.parametrize("seed", [1, 2])
    def test_differential_vs_numpy(self, seed):
        rng = np.random.default_rng(seed)
        n = 500
        cols = np.unique(rng.integers(0, 100_000, n, dtype=np.uint64))
        vals = rng.integers(-(2**12), 2**12, cols.size, dtype=np.int64)
        depth = 13
        f = mem_fragment(cache_type="none")
        f.import_value(cols, vals, depth)

        # value() readback
        for i in range(0, cols.size, 37):
            v, ok = f.value(int(cols[i]), depth)
            assert ok and v == vals[i]

        # sum / min / max
        s, c = f.sum(None, depth)
        assert (s, c) == (int(vals.sum()), cols.size)
        mn, _ = f.min(None, depth)
        mx, _ = f.max(None, depth)
        assert mn == int(vals.min())
        assert mx == int(vals.max())

        # range ops vs numpy
        for op, npop in [
            ("==", np.equal), ("!=", np.not_equal),
            ("<", np.less), ("<=", np.less_equal),
            (">", np.greater), (">=", np.greater_equal),
        ]:
            for pred in [-5000, -37, 0, 1, 800, 5000]:
                got = f.range_op(op, depth, pred).columns()
                want = cols[npop(vals, pred)]
                np.testing.assert_array_equal(got, want, err_msg=f"{op} {pred}")

        # between
        for lo, hi in [(-100, 100), (-5000, -1), (0, 5000), (37, 38)]:
            got = f.range_between(depth, lo, hi).columns()
            want = cols[(vals >= lo) & (vals <= hi)]
            np.testing.assert_array_equal(got, want)

    def test_sum_with_filter(self):
        f = mem_fragment(cache_type="none")
        f.import_value(np.array([1, 2, 3]), np.array([10, 20, 30]), 6)
        filt = Row([1, 3])
        s, c = f.sum(filt, 6)
        assert (s, c) == (40, 2)

    def test_set_value_overwrite(self):
        f = mem_fragment(cache_type="none")
        f.set_value(42, 8, 100)
        f.set_value(42, 8, -3)
        assert f.value(42, 8) == (-3, True)
        f.clear_value(42, 8)
        assert f.value(42, 8) == (0, False)


class TestFragmentDurability:
    def test_reopen(self, tmp_path):
        p = str(tmp_path / "frag" / "0")
        f = Fragment(p, "i", "f", "standard", 0).open()
        f.set_bit(1, 10)
        f.bulk_import(np.array([2, 2]), np.array([20, 21]))
        f.close()
        f2 = Fragment(p, "i", "f", "standard", 0).open()
        np.testing.assert_array_equal(f2.row(1).columns(), [10])
        np.testing.assert_array_equal(f2.row(2).columns(), [20, 21])
        assert f2.max_row_id == 2
        f2.close()

    def test_snapshot_on_op_threshold(self, tmp_path):
        p = str(tmp_path / "frag" / "0")
        f = Fragment(p, "i", "f", "standard", 0).open()
        # A single large batch exceeds MAX_OP_N and triggers a snapshot —
        # now a BACKGROUND rewrite (ISSUE r8: off the ingest hot path),
        # so the import returns before op_n resets; await it.
        vals = np.arange(MAX_OP_N + 10, dtype=np.uint64)
        f.bulk_import(np.zeros(vals.size, dtype=np.uint64), vals)
        f.await_snapshot()
        assert f.storage.op_n == 0  # snapshot absorbed the whole log
        f.close()
        f2 = Fragment(p, "i", "f", "standard", 0).open()
        assert f2.row_count(0) == MAX_OP_N + 10
        f2.close()

    def test_checksum_blocks(self):
        f = mem_fragment()
        f.set_bit(5, 100)
        f.set_bit(150, 100)  # second block (rows 100-199)
        blocks = f.checksum_blocks()
        assert [b for b, _ in blocks] == [0, 1]
        # merge into an empty fragment reproduces the data
        g = mem_fragment()
        for bid, _ in blocks:
            g.merge_block(bid, f.block_data(bid))
        assert g.checksum_blocks() == blocks


class TestField:
    def test_set_field_basics(self, tmp_path):
        f = Field(str(tmp_path / "f"), "i", "f").open()
        assert f.set_bit(1, 100)
        assert f.set_bit(1, SHARD_WIDTH + 5)
        row = f.row(1, 0)
        np.testing.assert_array_equal(row.columns(), [100])
        row = f.row(1, 1)
        np.testing.assert_array_equal(row.columns(), [SHARD_WIDTH + 5])
        shards = f.available_shards()
        assert sorted(shards.to_array().tolist()) == [0, 1]
        f.close()

    def test_int_field(self, tmp_path):
        f = Field(str(tmp_path / "v"), "i", "v", options_for_int(-1000, 1000)).open()
        f.set_value(10, 250)
        f.set_value(11, -250)
        assert f.value(10) == (250, True)
        assert f.value(11) == (-250, True)
        s, c = f.sum(None, 0)
        assert (s, c) == (0, 2)
        with pytest.raises(ValueError, match="less than"):
            f.set_value(1, -2000)
        with pytest.raises(ValueError, match="greater than"):
            f.set_value(1, 2000)
        f.close()

    def test_int_field_base_offset(self, tmp_path):
        # min > 0 => base = min; stored values are base-relative.
        f = Field(str(tmp_path / "v"), "i", "v", options_for_int(100, 200)).open()
        f.set_value(1, 150)
        assert f.value(1) == (150, True)
        s, c = f.sum(None, 0)
        assert (s, c) == (150, 1)
        mn, _ = f.min(None, 0)
        mx, _ = f.max(None, 0)
        assert (mn, mx) == (150, 150)
        f.close()

    def test_bool_field(self, tmp_path):
        f = Field(str(tmp_path / "b"), "i", "b", options_for_bool()).open()
        f.set_bit(1, 7)  # true
        f.set_bit(0, 7)  # flips to false (mutex-like)
        assert f.row(1, 0).count() == 0
        np.testing.assert_array_equal(f.row(0, 0).columns(), [7])
        f.close()

    def test_time_field(self, tmp_path):
        f = Field(str(tmp_path / "t"), "i", "t", options_for_time("YMD")).open()
        ts = dt.datetime(2018, 3, 5, 10)
        f.set_bit(2, 9, timestamp=ts)
        assert set(f.views) >= {"standard", "standard_2018", "standard_201803", "standard_20180305"}
        got = f.row_time(2, 0, dt.datetime(2018, 1, 1), dt.datetime(2019, 1, 1))
        np.testing.assert_array_equal(got.columns(), [9])
        got = f.row_time(2, 0, dt.datetime(2017, 1, 1), dt.datetime(2018, 1, 1))
        assert got.count() == 0
        f.close()

    def test_field_reopen_meta(self, tmp_path):
        path = str(tmp_path / "v")
        f = Field(path, "i", "v", options_for_int(-100, 100)).open()
        f.save_meta()
        f.set_value(5, 42)
        f.close()
        f2 = Field(path, "i", "v").open()
        assert f2.options.type == "int"
        assert f2.value(5) == (42, True)
        f2.close()

    def test_mutex_field(self, tmp_path):
        f = Field(str(tmp_path / "m"), "i", "m", options_for_mutex()).open()
        f.set_bit(1, 3)
        f.set_bit(2, 3)
        assert f.row(1, 0).count() == 0
        assert f.row(2, 0).count() == 1
        f.close()


class TestTimeQuantum:
    def test_views_by_time(self):
        t = dt.datetime(2017, 9, 2, 12)
        assert views_by_time("standard", t, "YMDH") == [
            "standard_2017",
            "standard_201709",
            "standard_20170902",
            "standard_2017090212",
        ]

    def test_views_by_time_range_ymdh(self):
        # Mirrors reference time_internal_test.go expectations.
        got = views_by_time_range(
            "f",
            dt.datetime(2016, 7, 6, 13),
            dt.datetime(2016, 7, 8, 2),
            "YMDH",
        )
        assert got == [
            "f_2016070613", "f_2016070614", "f_2016070615", "f_2016070616",
            "f_2016070617", "f_2016070618", "f_2016070619", "f_2016070620",
            "f_2016070621", "f_2016070622", "f_2016070623",
            "f_20160707",
            "f_2016070800", "f_2016070801",
        ]

    def test_views_by_time_range_y(self):
        got = views_by_time_range("f", dt.datetime(2015, 1, 1), dt.datetime(2017, 1, 1), "Y")
        assert got == ["f_2015", "f_2016"]

    def test_views_by_time_range_partial_year(self):
        got = views_by_time_range("f", dt.datetime(2015, 11, 1), dt.datetime(2016, 2, 1), "YM")
        assert got == ["f_201511", "f_201512", "f_201601"]


class TestHierarchy:
    def test_holder_index_field_reopen(self, tmp_path):
        h = Holder(str(tmp_path / "data")).open()
        idx = h.create_index("myidx")
        f = idx.create_field("myfield")
        f.set_bit(1, 100)
        v = idx.create_field("vals", options_for_int(0, 1000))
        v.set_value(3, 500)
        h.close()

        h2 = Holder(str(tmp_path / "data")).open()
        idx2 = h2.index("myidx")
        assert idx2 is not None
        np.testing.assert_array_equal(idx2.field("myfield").row(1, 0).columns(), [100])
        assert idx2.field("vals").value(3) == (500, True)
        assert sorted(idx2.available_shards().to_array().tolist()) == [0]
        h2.close()

    def test_existence_field_created(self, tmp_path):
        h = Holder(str(tmp_path / "data")).open()
        idx = h.create_index("i")
        assert idx.existence_field() is not None
        h.close()

    def test_delete(self, tmp_path):
        h = Holder(str(tmp_path / "data")).open()
        idx = h.create_index("i")
        idx.create_field("f")
        idx.delete_field("f")
        assert idx.field("f") is None
        h.delete_index("i")
        assert h.index("i") is None
        h.close()

    def test_duplicate_create_rejected(self, tmp_path):
        h = Holder(str(tmp_path / "data")).open()
        h.create_index("i")
        with pytest.raises(ValueError, match="exists"):
            h.create_index("i")
        with pytest.raises(ValueError, match="invalid"):
            h.create_index("BAD_NAME!")
        h.close()

    def test_schema(self, tmp_path):
        h = Holder(str(tmp_path / "data")).open()
        idx = h.create_index("i")
        idx.create_field("f")
        schema = h.schema()
        assert schema[0]["name"] == "i"
        assert schema[0]["fields"][0]["name"] == "f"
        assert schema[0]["shardWidth"] == SHARD_WIDTH
        h.close()


class TestTranslateStoreBulk:
    """VERDICT r4 #5: translate_keys must be ONE transaction (chunked
    membership SELECT + executemany INSERT + re-SELECT), not a per-key
    SELECT+INSERT+commit loop through one lock."""

    def _store(self):
        from pilosa_tpu.store.translate import TranslateStore

        return TranslateStore(None)

    def test_bulk_matches_per_key_semantics(self):
        ts = self._store()
        a = ts.translate_key("a")
        got = ts.translate_keys(["b", "a", "c", "b", "b"])
        # existing key keeps its id; duplicates in one batch share one id
        assert got[1] == a
        assert got[0] == got[3] == got[4]
        assert len({got[0], got[1], got[2]}) == 3
        # ids are stable on re-query and visible per-key
        assert ts.translate_keys(["c", "b"]) == [got[2], got[0]]
        assert ts.translate_key("c") == got[2]

    def test_write_false_misses_stay_none(self):
        ts = self._store()
        ts.translate_key("x")
        assert ts.translate_keys(["x", "nope"], write=False) == [1, None]
        assert ts.translate_key("nope", write=False) is None

    def test_read_only_raises_on_miss_only(self):
        from pilosa_tpu.store.translate import (
            TranslateStore,
            TranslateStoreReadOnlyError,
        )

        ts = TranslateStore(None)
        ts.translate_key("x")
        ts.read_only = True
        assert ts.translate_keys(["x"]) == [1]
        import pytest as _pytest

        with _pytest.raises(TranslateStoreReadOnlyError):
            ts.translate_keys(["x", "fresh"])

    def test_chunking_over_variable_limit(self):
        ts = self._store()
        keys = [f"k{i}" for i in range(1301)]  # > 2 IN-clause chunks
        ids = ts.translate_keys(keys)
        assert sorted(ids) == list(range(1, 1302))
        assert ts.translate_ids(ids) == keys
        assert ts.translate_ids([99999, ids[7]]) == [None, "k7"]

    def test_bulk_is_order_of_magnitude_faster_than_loop(self, tmp_path):
        """The VERDICT done-bar, scaled to test time: a fresh keyed
        batch through translate_keys must beat the per-key loop by
        >=10x on a FILE-backed store (the loop pays a durable commit —
        an fsync — per key; the batch pays one. The ratio only grows
        with batch size)."""
        import time as _time

        from pilosa_tpu.store.translate import TranslateStore

        n = 400
        ts = TranslateStore(str(tmp_path / "loop" / "keys.db"))
        t0 = _time.perf_counter()
        for i in range(n):
            ts.translate_key(f"loop{i}")
        t_loop = _time.perf_counter() - t0
        ts2 = TranslateStore(str(tmp_path / "bulk" / "keys.db"))
        t0 = _time.perf_counter()
        ts2.translate_keys([f"bulk{i}" for i in range(n)])
        t_bulk = _time.perf_counter() - t0
        assert t_bulk * 10 <= t_loop, (t_bulk, t_loop)
