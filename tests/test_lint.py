"""Tier-1 wiring for tools/lint (ISSUE r12 tentpole): the repo tree must
lint clean, and every checker must prove it fires on its seeded
known-bad fixture — a rule that cannot demonstrate a catch is dead
weight. Mirrors the tests/test_metrics_docs.py pattern that established
the statically-checked-invariant convention.
"""

from __future__ import annotations

import ast
import pathlib
import time as _time

import pytest

from tools.lint import core
from tools.lint.checkers import make_checkers
from tools.lint.checkers.config_drift import config_drift_findings
from tools.lint.checkers.deadline_scope import DeadlineScopeChecker
from tools.lint.checkers.error_codes import ErrorCodeChecker
from tools.lint.checkers.shared_state import SharedStateChecker
from tools.lint.checkers.exceptions import ExceptDisciplineChecker
from tools.lint.checkers.jax_dispatch import JaxDispatchChecker
from tools.lint.checkers.lock_discipline import LockDisciplineChecker
from tools.lint.checkers.metrics import (
    TagCardinalityChecker,
    metrics_docs_drift,
)
from tools.lint.checkers.monotonic_time import MonotonicTimeChecker

FIXTURES = pathlib.Path(__file__).resolve().parent / "lint_fixtures"
ALL_RULES = {c.rule for c in make_checkers()}


def load_fixture(name: str) -> core.SourceFile:
    return core.SourceFile.load(FIXTURES / name, ALL_RULES)


# ---------------------------------------------------------------------------
# The gate: the shipped tree is clean (and fast enough for tier-1).
# ---------------------------------------------------------------------------


class TestRepoClean:
    def test_repo_tree_lints_clean(self):
        t0 = _time.monotonic()
        violations = core.run_lint(make_checkers())
        dt = _time.monotonic() - t0
        report = "\n".join(v.render() for v in violations)
        assert not violations, f"lint violations in the repo tree:\n{report}"
        # The suite must stay cheap enough to gate every PR.
        assert dt < 30.0, f"lint suite too slow for tier-1: {dt:.1f}s"

    def test_lock_graph_cycle_free_is_asserted(self):
        """The acceptance-criteria property specifically: zero
        lock-discipline findings over pilosa_tpu/ (cycles, re-entry,
        blocking-under-lock) modulo reasoned waivers."""
        violations = core.run_lint(
            make_checkers(), rules={"lock-discipline"}
        )
        report = "\n".join(v.render() for v in violations)
        assert not violations, report


# ---------------------------------------------------------------------------
# Each checker fires on its seeded fixture.
# ---------------------------------------------------------------------------


class TestCheckersFire:
    def test_monotonic_time_fixture(self):
        f = load_fixture("monotonic_bad.py")
        got = list(MonotonicTimeChecker().check_file(f))
        assert len(got) == 2  # two bad sites; the waivered one is not
        assert all(v.rule == "monotonic-time" for v in got)
        assert any(w.used for w in f.waivers)  # good waiver consumed

    def test_error_code_fixture(self):
        f = load_fixture("error_code_bad.py")
        got = list(ErrorCodeChecker().check_file(f))
        msgs = " | ".join(v.message for v in got)
        assert len(got) == 2
        assert "without a literal" in msgs      # codeless 500
        assert "bypasses _error" in msgs        # direct 503

    def test_error_code_funnel_structural(self):
        """A server/http.py whose _error lost Retry-After is flagged."""
        src = (
            "class H:\n"
            "    def _error(self, msg, status=400, code=''):\n"
            "        self._reply({'error': msg, 'code': code},\n"
            "                    status=status)\n"
        )
        f = core.SourceFile(
            path=pathlib.Path("http.py"),
            rel="pilosa_tpu/server/http.py",
            text=src, tree=ast.parse(src),
        )
        got = list(ErrorCodeChecker().check_file(f))
        assert any("Retry-After" in v.message for v in got)

    def test_jax_dispatch_fixture(self):
        f = load_fixture("jax_dispatch_bad.py")
        got = list(JaxDispatchChecker().check_file(f))
        msgs = " | ".join(v.message for v in got)
        assert "module import time" in msgs
        assert ".item()" in msgs
        assert "compiled and called inline" in msgs
        assert "raw len(...)" in msgs
        assert len(got) == 4  # the returned-builder pattern is NOT flagged

    def test_lock_cycle_fixture(self):
        """The seeded AB/BA cycle — the acceptance-criteria fixture."""
        f = load_fixture("lock_cycle_bad.py")
        got = list(LockDisciplineChecker().finalize([f]))
        msgs = " | ".join(v.message for v in got)
        assert "lock-order cycle" in msgs
        assert "_lock_x" in msgs and "_lock_y" in msgs
        assert "time.sleep" in msgs  # blocking under lock, same fixture

    def test_except_fixture(self):
        f = load_fixture("except_bad.py")
        got = list(ExceptDisciplineChecker().check_file(f))
        assert len(got) == 2  # silent broad catch + bare except
        msgs = " | ".join(v.message for v in got)
        assert "swallows" in msgs
        assert "bare `except:`" in msgs

    def test_durable_write_fixture(self):
        from tools.lint.checkers.durable_write import DurableWriteChecker

        f = load_fixture("durable_write_bad.py")
        got = list(DurableWriteChecker().check_file(f))
        assert len(got) == 2  # truncating write + buffered append
        msgs = " | ".join(v.message for v in got)
        assert "'w'" in msgs
        assert "'ab'" in msgs
        # The waivered site is consumed; the tmp+replace and unbuffered-
        # append functions do not fire.
        assert any(w.used for w in f.waivers)

    def test_hot_serialize_fixture(self):
        """The seeded .tolist() + int-comprehension fire; the waivered
        inventory, vectorized encode, and scalar-source comprehension
        do not (ISSUE r14 satellite)."""
        from tools.lint.checkers.hot_serialize import HotSerializeChecker

        f = load_fixture("hot_serialize_bad.py")
        got = list(HotSerializeChecker().check_file(f))
        msgs = " | ".join(v.message for v in got)
        assert len(got) == 2
        assert ".tolist()" in msgs
        assert "per-element int(...)" in msgs
        assert any(w.used for w in f.waivers)

    def test_metric_tags_fixture(self):
        f = load_fixture("metric_tags_bad.py")
        got = list(TagCardinalityChecker().check_file(f))
        assert len(got) == 2
        msgs = " | ".join(v.message for v in got)
        assert "unknown tag key" in msgs
        assert "unbounded cardinality" in msgs

    def test_shared_state_fixture(self):
        """The seeded two-root unlocked writes fire; the blessed
        assign-once-before-start publish and the fully-locked counter
        do not (ISSUE r13 tentpole 1)."""
        f = load_fixture("shared_state_bad.py")
        got = list(SharedStateChecker().finalize([f]))
        msgs = " | ".join(v.message for v in got)
        assert len(got) == 2
        assert "Daemon.counter" in msgs          # unlocked self-attr RMW
        assert "_hits" in msgs                   # unlocked module global
        assert "http-request" in msgs            # both roots named
        assert "published" not in msgs           # blessed immutable publish
        assert "guarded" not in msgs             # common lock on every path

    def test_deadline_scope_fixture(self):
        """The bare client call from a thread root fires; the call
        under `with deadline_scope(...)` does not (tentpole 2)."""
        f = load_fixture("deadline_scope_bad.py")
        got = list(DeadlineScopeChecker().finalize([f]))
        assert len(got) == 1
        assert got[0].rule == "deadline-scope"
        assert "status()" in got[0].message
        # The flagged line is the UNcovered call, not the covered one.
        assert "# BAD" in f.text.splitlines()[got[0].line - 1]

    def test_config_drift_fixture(self):
        """The drifted knob yields one finding per missing surface; the
        fully-wired knob yields none (tentpole 3)."""
        text = (FIXTURES / "config_drift_bad.py").read_text()
        got = config_drift_findings(
            text,
            cli_text="def f(cfg): return cfg.wired",
            doc_text="| `wired` | PILOSA_TPU_WIRED |",
        )
        assert [a for a, _l, _m in got] == ["broken"] * 5
        surfaces = " | ".join(m for _a, _l, m in got)
        assert "env var" in surfaces
        assert "to_dict" in surfaces
        assert "toml_text" in surfaces
        assert "cli.py" in surfaces
        assert "docs/configuration.md" in surfaces

    def test_config_drift_doc_env_mismatch(self):
        """A docs row whose env cell lost the variable is drift too."""
        text = (FIXTURES / "config_drift_bad.py").read_text()
        got = config_drift_findings(
            text,
            cli_text="def f(cfg): return cfg.wired",
            doc_text="| `wired` | — |",  # row exists, env cell dropped
        )
        assert any("omits the env var" in m for a, _l, m in got
                   if a == "wired")

    def test_repo_config_is_drift_free(self):
        """The real config.py/cli.py/docs row set round-trips — the
        acceptance property, asserted without the whole lint run."""
        assert config_drift_findings(
            core.REPO_ROOT.joinpath(
                "pilosa_tpu", "server", "config.py").read_text(),
            cli_text=core.REPO_ROOT.joinpath(
                "pilosa_tpu", "cli.py").read_text(),
            doc_text=core.REPO_ROOT.joinpath(
                "docs", "configuration.md").read_text(),
        ) == []

    def test_metric_docs_drift_detects_both_directions(self):
        doc = "catalogue: `real_total` and `phantom_total`."
        findings = metrics_docs_drift(
            src={"real_total", "undocumented_total"}, doc_text=doc
        )
        blob = "\n".join(findings)
        assert "emitted but not documented: undocumented_total" in blob
        assert "documented but not emitted: phantom_total" in blob
        # DYNAMIC_FAMILIES doc-mention guard is live too.
        assert any("dynamic family" in x for x in findings)


# ---------------------------------------------------------------------------
# Waiver machinery: validated as used-and-reasoned.
# ---------------------------------------------------------------------------


class TestWaivers:
    def test_waiver_missing_reason_and_unknown_rule(self):
        f = load_fixture("waiver_bad.py")
        msgs = " | ".join(v.message for v in f.waiver_errors)
        assert "has no reason" in msgs
        assert "unknown rule 'made-up-rule'" in msgs

    def test_unused_waiver_reported(self):
        got = core.run_lint(
            make_checkers(), paths=[str(FIXTURES / "waiver_bad.py")]
        )
        unused = [v for v in got if v.rule == "unused-waiver"]
        assert len(unused) == 1
        assert "except-exception" in unused[0].message
        # ...and the consumed monotonic waiver is NOT flagged unused.
        assert not any(
            v.rule == "unused-waiver" and "monotonic" in v.message
            for v in got
        )

    def test_waiver_on_own_line_covers_next_statement(self):
        src = (
            "import time\n"
            "def f():\n"
            "    # lint: allow-monotonic-time(own-line waiver)\n"
            "    return time.time()\n"
        )
        f = core.SourceFile(
            path=pathlib.Path("x.py"), rel="pilosa_tpu/x.py",
            text=src, tree=ast.parse(src),
        )
        f._parse_waivers(ALL_RULES)
        assert not list(MonotonicTimeChecker().check_file(f))
        assert f.waivers[0].used


# ---------------------------------------------------------------------------
# Waiver ratchet: the committed per-rule census (ISSUE r13 satellite).
# ---------------------------------------------------------------------------


class TestWaiverRatchet:
    def test_committed_ledger_matches_live_census(self):
        """The real gate: tools/lint/waivers.lock equals the tree's
        waiver counts exactly (also covered by the repo-clean test,
        but this pins WHICH property failed when it does)."""
        files = [
            core.SourceFile.load(p, ALL_RULES)
            for p in core.collect_files()
            if "__pycache__" not in p.parts
        ]
        census = core.waiver_census(f for f in files if f.tree is not None)
        assert census == core.read_waiver_ledger()

    def _tree_with_one_waiver(self, tmp_path):
        tree = tmp_path / "tree"
        tree.mkdir()
        (tree / "m.py").write_text(
            "import time\n"
            "def f():\n"
            "    return time.time()  # lint: allow-monotonic-time(test)\n"
        )
        return tree

    def test_new_waiver_without_ledger_bump_fails(self, tmp_path, monkeypatch):
        ledger = tmp_path / "waivers.lock"
        ledger.write_text("monotonic-time 0\n")
        monkeypatch.setattr(core, "WAIVER_LEDGER", ledger)
        monkeypatch.setattr(core, "DEFAULT_TREE",
                            str(self._tree_with_one_waiver(tmp_path)))
        got = [v for v in core.run_lint(make_checkers())
               if v.rule == "waiver-ratchet"]
        assert len(got) == 1
        assert "1 waiver(s) for 'monotonic-time'" in got[0].message
        assert "bump" in got[0].hint

    def test_stale_ledger_must_ratchet_down(self, tmp_path, monkeypatch):
        ledger = tmp_path / "waivers.lock"
        ledger.write_text("monotonic-time 5\nexcept-exception 2\n")
        monkeypatch.setattr(core, "WAIVER_LEDGER", ledger)
        monkeypatch.setattr(core, "DEFAULT_TREE",
                            str(self._tree_with_one_waiver(tmp_path)))
        got = [v for v in core.run_lint(make_checkers())
               if v.rule == "waiver-ratchet"]
        msgs = " | ".join(v.message for v in got)
        assert "ledger records 5" in msgs       # monotonic: 5 vs 1
        assert "ledger records 2" in msgs       # except: 2 vs 0
        assert all("ratchet down" in v.hint for v in got)

    def test_missing_ledger_is_a_violation(self, tmp_path, monkeypatch):
        monkeypatch.setattr(core, "WAIVER_LEDGER",
                            tmp_path / "does_not_exist.lock")
        monkeypatch.setattr(core, "DEFAULT_TREE",
                            str(self._tree_with_one_waiver(tmp_path)))
        got = [v for v in core.run_lint(make_checkers())
               if v.rule == "waiver-ratchet"]
        assert len(got) == 1 and "missing" in got[0].message

    def test_subset_and_rule_filtered_runs_skip_the_ratchet(
        self, tmp_path, monkeypatch
    ):
        """--changed / explicit paths / --rule see a partial census by
        construction: the ratchet must not judge them."""
        monkeypatch.setattr(core, "WAIVER_LEDGER",
                            tmp_path / "does_not_exist.lock")
        got = core.run_lint(
            make_checkers(), paths=["pilosa_tpu/utils/tracing.py"]
        )
        assert not [v for v in got if v.rule == "waiver-ratchet"]
        got = core.run_lint(make_checkers(), rules={"monotonic-time"})
        assert not [v for v in got if v.rule == "waiver-ratchet"]

    def test_list_waivers_cli(self, capsys):
        from tools.lint.__main__ import main

        assert main(["--list-waivers"]) == 0
        out = capsys.readouterr().out
        assert "shared-state 22" in out
        # Per-site lines carry file:line, rule and the reason text.
        assert "pilosa_tpu/utils/tracing.py" in out
        assert "[monotonic-time]" in out


# ---------------------------------------------------------------------------
# Framework: registry, CLI, --changed fast mode.
# ---------------------------------------------------------------------------


class TestFramework:
    def test_registry_rules_unique_and_documented(self):
        checkers = make_checkers()
        rules = [c.rule for c in checkers]
        assert len(rules) == len(set(rules)) == 12
        for c in checkers:
            assert c.rule and c.doc, f"{type(c).__name__} lacks rule/doc"

    def test_cli_exit_codes(self, capsys):
        from tools.lint.__main__ import main

        assert main([]) == 0  # clean tree
        assert "lint clean" in capsys.readouterr().out
        assert main([str(FIXTURES / "except_bad.py"),
                     "--rule", "except-exception"]) == 1
        out = capsys.readouterr().out
        assert "[except-exception]" in out
        assert "violation(s)" in out
        assert main(["--list-rules"]) == 0
        assert "lock-discipline" in capsys.readouterr().out

    def test_changed_mode_lints_only_changed_files(self, monkeypatch):
        monkeypatch.setattr(
            core, "_git_changed_files",
            lambda: [FIXTURES / "except_bad.py"],
        )
        got = core.run_lint(make_checkers(), changed=True,
                            rules={"except-exception"})
        assert {v.path for v in got if v.rule == "except-exception"} == {
            "tests/lint_fixtures/except_bad.py"
        }
        # And an empty change set is a clean no-op, not an error.
        monkeypatch.setattr(core, "_git_changed_files", lambda: [])
        assert core.run_lint(make_checkers(), changed=True,
                             rules={"except-exception"}) == []

    def test_parse_error_reported_not_raised(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text("def broken(:\n")
        got = core.run_lint(make_checkers(), paths=[str(bad)])
        assert any(v.rule == "parse" for v in got)

    def test_missing_path_reported_not_raised(self, tmp_path):
        got = core.run_lint(
            make_checkers(), paths=[str(tmp_path / "does_not_exist.py")]
        )
        assert len(got) == 1 and got[0].rule == "parse"
        assert "cannot read" in got[0].message


class TestReviewRegressions:
    """Fixes from the r12 review pass, pinned."""

    def test_version_gate_compat_def_not_import_time(self):
        """A def nested under a module-level try:/except ImportError:
        only runs when called — the natural spelling of a jax
        version gate must not trip import-jnp."""
        src = (
            "import jax\n"
            "try:\n"
            "    from jax import shard_map\n"
            "except ImportError:\n"
            "    def shard_map(f, **kw):\n"
            "        return jax.experimental.shard_map.shard_map(f, **kw)\n"
        )
        f = core.SourceFile(
            path=pathlib.Path("x.py"), rel="pilosa_tpu/exec/x.py",
            text=src, tree=ast.parse(src),
        )
        assert not [
            v for v in JaxDispatchChecker().check_file(f)
            if "import time" in v.message
        ]
        # ...while a call under the SAME try: block still fires.
        src2 = "import jax.numpy as jnp\ntry:\n    T = jnp.arange(4)\nexcept Exception:\n    T = None\n"
        f2 = core.SourceFile(
            path=pathlib.Path("y.py"), rel="pilosa_tpu/exec/y.py",
            text=src2, tree=ast.parse(src2),
        )
        assert any(
            "import time" in v.message
            for v in JaxDispatchChecker().check_file(f2)
        )

    def test_stale_lock_waiver_on_unheld_blocking_site_is_unused(
        self, tmp_path, monkeypatch
    ):
        """A lock-discipline waiver on a blocking call that holds no
        lock was never needed: on a FULL-tree run it must surface as
        unused-waiver, not be silently consumed by the propagation
        filter. (Judged only on full runs — the tmp dir stands in as
        the default tree.)"""
        p = tmp_path / "stale.py"
        p.write_text(
            "import subprocess\n"
            "def build():\n"
            "    subprocess.run(['true'])  "
            "# lint: allow-lock-discipline(stale permission)\n"
        )
        monkeypatch.setattr(core, "DEFAULT_TREE", str(tmp_path))
        got = core.run_lint(make_checkers(),
                            rules={"lock-discipline"})
        assert [v.rule for v in got] == ["unused-waiver"]

    def test_subset_run_does_not_misjudge_cross_file_lock_waivers(self):
        """Linting one file (--changed shape) must not flag resize.py's
        lock-discipline waivers as unused just because the consuming
        edge runs through the unlinted cluster/client.py."""
        got = core.run_lint(
            make_checkers(), paths=["pilosa_tpu/cluster/resize.py"]
        )
        assert not [v for v in got if v.rule == "unused-waiver"], [
            v.render() for v in got
        ]


# ---------------------------------------------------------------------------
# The shim: existing check_metrics_docs invocations keep working.
# ---------------------------------------------------------------------------


class TestShim:
    def test_shim_delegates(self, capsys):
        import importlib.util

        path = core.REPO_ROOT / "tools" / "check_metrics_docs.py"
        spec = importlib.util.spec_from_file_location("cmd_shim", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        assert mod.main() == 0
        assert "clean" in capsys.readouterr().out
        # The legacy API surface the old tests rely on is still there.
        assert "peer_rpc_seconds" in mod.source_metrics()
        exact, wild = mod.doc_tokens()
        assert exact and wild
