"""HTTP server + API tests: drive the real socket surface with urllib,
mirroring the reference's http/handler_test.go + api_test.go coverage."""

import json
import socket
import urllib.error
import urllib.request

import numpy as np
import pytest

from pilosa_tpu.core import Holder
from pilosa_tpu.exec import Executor
from pilosa_tpu.server.api import API
from pilosa_tpu.server.http import Server
from pilosa_tpu.server.wire import (
    ImportRequest,
    ImportRoaringRequest,
    ImportRoaringRequestView,
    ImportValueRequest,
    QueryRequest,
)


@pytest.fixture
def server(tmp_path):
    holder = Holder(str(tmp_path / "data")).open()
    srv = Server(API(holder, Executor(holder)), host="localhost", port=0).open()
    yield srv
    srv.close()
    holder.close()


def req(srv, method, path, body=None, ctype="application/json", raw=False):
    data = None
    if body is not None:
        data = body if isinstance(body, bytes) else json.dumps(body).encode()
    r = urllib.request.Request(
        srv.uri + path, data=data, method=method, headers={"Content-Type": ctype}
    )
    resp = urllib.request.urlopen(r)
    payload = resp.read()
    return payload if raw else json.loads(payload)


class TestSchemaRoutes:
    def test_crud(self, server):
        out = req(server, "POST", "/index/myidx", {"options": {"trackExistence": True}})
        assert out["name"] == "myidx"
        out = req(server, "POST", "/index/myidx/field/f", {})
        assert out["name"] == "f"
        schema = req(server, "GET", "/schema")
        assert schema["indexes"][0]["name"] == "myidx"
        assert schema["indexes"][0]["fields"][0]["name"] == "f"
        out = req(server, "GET", "/index/myidx")
        assert out["name"] == "myidx"
        req(server, "DELETE", "/index/myidx/field/f")
        req(server, "DELETE", "/index/myidx")
        assert req(server, "GET", "/schema") == {"indexes": []}

    def test_conflict_and_missing(self, server):
        req(server, "POST", "/index/i", {})
        with pytest.raises(urllib.error.HTTPError) as e:
            req(server, "POST", "/index/i", {})
        assert e.value.code == 409
        with pytest.raises(urllib.error.HTTPError) as e:
            req(server, "DELETE", "/index/nope")
        assert e.value.code == 404

    def test_int_field_options(self, server):
        req(server, "POST", "/index/i", {})
        out = req(
            server, "POST", "/index/i/field/v",
            {"options": {"type": "int", "min": -10, "max": 100}},
        )
        assert out["options"]["type"] == "int"
        assert out["options"]["min"] == -10

    def test_post_schema_idempotent(self, server):
        schema = {
            "indexes": [
                {"name": "i", "options": {}, "fields": [{"name": "f", "options": {}}]}
            ]
        }
        req(server, "POST", "/schema", schema)
        req(server, "POST", "/schema", schema)  # idempotent
        got = req(server, "GET", "/schema")
        assert got["indexes"][0]["fields"][0]["name"] == "f"


class TestQueryRoutes:
    def test_query_flow(self, server):
        req(server, "POST", "/index/i", {})
        req(server, "POST", "/index/i/field/f", {})
        out = req(server, "POST", "/index/i/query", b"Set(10, f=1)", ctype="text/plain")
        assert out == {"results": [True]}
        out = req(server, "POST", "/index/i/query", b"Row(f=1)", ctype="text/plain")
        assert out == {"results": [{"attrs": {}, "columns": [10]}]}
        out = req(server, "POST", "/index/i/query", b"Count(Row(f=1))", ctype="text/plain")
        assert out == {"results": [1]}

    def test_query_error(self, server):
        req(server, "POST", "/index/i", {})
        with pytest.raises(urllib.error.HTTPError) as e:
            req(server, "POST", "/index/i/query", b"Row(", ctype="text/plain")
        assert e.value.code == 400
        body = json.loads(e.value.read())
        assert "error" in body

    def test_query_protobuf(self, server):
        req(server, "POST", "/index/i", {})
        req(server, "POST", "/index/i/field/f", {})
        req(server, "POST", "/index/i/query", b"Set(3, f=9)", ctype="text/plain")
        qr = QueryRequest(query="Count(Row(f=9))")
        out = req(
            server, "POST", "/index/i/query", qr.to_bytes(),
            ctype="application/x-protobuf",
        )
        assert out == {"results": [1]}

    def test_shards_param(self, server):
        from pilosa_tpu.shardwidth import SHARD_WIDTH

        req(server, "POST", "/index/i", {})
        req(server, "POST", "/index/i/field/f", {})
        req(server, "POST", "/index/i/query", f"Set({SHARD_WIDTH+1}, f=1)".encode(), ctype="text/plain")
        req(server, "POST", "/index/i/query", b"Set(1, f=1)", ctype="text/plain")
        out = req(server, "POST", "/index/i/query?shards=1", b"Count(Row(f=1))", ctype="text/plain")
        assert out == {"results": [1]}


class TestImportRoutes:
    def test_json_import(self, server):
        req(server, "POST", "/index/i", {})
        req(server, "POST", "/index/i/field/f", {})
        req(
            server, "POST", "/index/i/field/f/import",
            {"rowIDs": [1, 1, 2], "columnIDs": [10, 20, 10]},
        )
        out = req(server, "POST", "/index/i/query", b"Row(f=1)", ctype="text/plain")
        assert out["results"][0]["columns"] == [10, 20]
        # existence tracked
        out = req(server, "POST", "/index/i/query", b"All()", ctype="text/plain")
        assert out["results"][0]["columns"] == [10, 20]

    def test_protobuf_import(self, server):
        req(server, "POST", "/index/i", {})
        req(server, "POST", "/index/i/field/f", {})
        msg = ImportRequest(index="i", field="f", row_ids=[5, 5], column_ids=[1, 2])
        req(
            server, "POST", "/index/i/field/f/import", msg.to_bytes(),
            ctype="application/x-protobuf",
        )
        out = req(server, "POST", "/index/i/query", b"Row(f=5)", ctype="text/plain")
        assert out["results"][0]["columns"] == [1, 2]

    def test_protobuf_value_import(self, server):
        req(server, "POST", "/index/i", {})
        req(
            server, "POST", "/index/i/field/v",
            {"options": {"type": "int", "min": -100, "max": 100}},
        )
        msg = ImportValueRequest(index="i", field="v", column_ids=[1, 2], values=[42, -7])
        req(
            server, "POST", "/index/i/field/v/import", msg.to_bytes(),
            ctype="application/x-protobuf",
        )
        out = req(server, "POST", "/index/i/query", b"Sum(field=v)", ctype="text/plain")
        assert out["results"][0] == {"value": 35, "count": 2}

    def test_import_roaring(self, server):
        from pilosa_tpu.roaring import Bitmap, serialize

        req(server, "POST", "/index/i", {})
        req(server, "POST", "/index/i/field/f", {})
        bm = Bitmap(np.array([1, 2, 3], dtype=np.uint64))
        msg = ImportRoaringRequest(
            views=[ImportRoaringRequestView(name="", data=serialize(bm))]
        )
        req(
            server, "POST", "/index/i/field/f/import-roaring/0", msg.to_bytes(),
            ctype="application/x-protobuf",
        )
        out = req(server, "POST", "/index/i/query", b"Row(f=0)", ctype="text/plain")
        assert out["results"][0]["columns"] == [1, 2, 3]

    def test_keyed_import(self, server):
        req(server, "POST", "/index/k", {"options": {"keys": True}})
        req(server, "POST", "/index/k/field/f", {"options": {"keys": True}})
        req(
            server, "POST", "/index/k/field/f/import",
            {"rowKeys": ["red", "red"], "columnKeys": ["a", "b"]},
        )
        out = req(server, "POST", "/index/k/query", b'Row(f="red")', ctype="text/plain")
        assert sorted(out["results"][0]["keys"]) == ["a", "b"]


class TestInfoRoutes:
    def test_status_info_version(self, server):
        out = req(server, "GET", "/status")
        assert out["state"] == "NORMAL"
        assert out["nodes"][0]["isCoordinator"] is True
        out = req(server, "GET", "/info")
        assert "shardWidth" in out
        out = req(server, "GET", "/version")
        assert "version" in out

    def test_shards_max(self, server):
        req(server, "POST", "/index/i", {})
        req(server, "POST", "/index/i/field/f", {})
        req(server, "POST", "/index/i/query", b"Set(1, f=1)", ctype="text/plain")
        out = req(server, "GET", "/internal/shards/max")
        assert out == {"standard": {"i": 0}}

    def test_metrics(self, server):
        raw = req(server, "GET", "/metrics", raw=True)
        assert isinstance(raw, bytes)

    def test_export(self, server):
        req(server, "POST", "/index/i", {})
        req(server, "POST", "/index/i/field/f", {})
        req(server, "POST", "/index/i/query", b"Set(7, f=3)", ctype="text/plain")
        raw = req(server, "GET", "/export?index=i&field=f&shard=0", raw=True)
        assert raw.decode().strip() == "3,7"

    def test_fragment_internal_routes(self, server):
        req(server, "POST", "/index/i", {})
        req(server, "POST", "/index/i/field/f", {})
        req(server, "POST", "/index/i/query", b"Set(7, f=3)", ctype="text/plain")
        out = req(server, "GET", "/internal/fragment/blocks?index=i&field=f&view=standard&shard=0")
        assert len(out["blocks"]) == 1
        raw = req(server, "GET", "/internal/fragment/data?index=i&field=f&view=standard&shard=0", raw=True)
        from pilosa_tpu.roaring.codec import deserialize

        bm = deserialize(raw)
        assert bm.count() == 1


class TestWireCodec:
    def test_roundtrips(self):
        m = ImportRequest(index="i", field="f", shard=3, row_ids=[1, 2], column_ids=[9],
                          row_keys=["a"], column_keys=["b"], timestamps=[0, -5])
        m2 = ImportRequest.from_bytes(m.to_bytes())
        assert m2 == m
        v = ImportValueRequest(index="i", field="v", column_ids=[1], values=[-42])
        assert ImportValueRequest.from_bytes(v.to_bytes()) == v
        q = QueryRequest(query="Row(f=1)", shards=[0, 5], remote=True)
        assert QueryRequest.from_bytes(q.to_bytes()) == q
        r = ImportRoaringRequest(clear=True, views=[ImportRoaringRequestView("x", b"\x01\x02")])
        r2 = ImportRoaringRequest.from_bytes(r.to_bytes())
        assert r2.clear and r2.views[0].name == "x" and r2.views[0].data == b"\x01\x02"


class TestProtobufResponses:
    """QueryResponse protobuf encoding (reference public.proto:66 +
    encoding/proto/proto.go:416): content-negotiated via Accept."""

    def _pb_query(self, srv, index, pql):
        from pilosa_tpu.server.wire import decode_query_response

        r = urllib.request.Request(
            srv.uri + f"/index/{index}/query",
            data=pql.encode(),
            method="POST",
            headers={"Content-Type": "text/plain", "Accept": "application/x-protobuf"},
        )
        resp = urllib.request.urlopen(r)
        assert resp.headers.get("Content-Type") == "application/x-protobuf"
        return decode_query_response(resp.read())

    def _setup(self, srv):
        req(srv, "POST", "/index/i", {})
        req(srv, "POST", "/index/i/field/f", {})
        req(srv, "POST", "/index/i/field/v",
            {"options": {"type": "int", "min": -100, "max": 100}})
        req(srv, "POST", "/index/i/query", b"Set(1, f=3) Set(2, f=3) Set(9, f=5)",
            ctype="text/plain")
        req(srv, "POST", "/index/i/query", b"Set(1, v=42) Set(2, v=-7)",
            ctype="text/plain")

    def test_row_count_pairs_valcount(self, server):
        self._setup(server)
        out = self._pb_query(server, "i", "Row(f=3)")
        assert out["results"][0]["columns"] == [1, 2]
        out = self._pb_query(server, "i", "Count(Row(f=3))")
        assert out["results"][0] == 2
        out = self._pb_query(server, "i", "TopN(f, n=2)")
        assert out["results"][0] == [
            {"id": 3, "count": 2},
            {"id": 5, "count": 1},
        ]
        out = self._pb_query(server, "i", "Sum(field=v)")
        assert out["results"][0] == {"value": 35, "count": 2}
        out = self._pb_query(server, "i", "Min(field=v)")
        assert out["results"][0] == {"value": -7, "count": 1}

    def test_bool_rows_groupby_pairfield(self, server):
        self._setup(server)
        out = self._pb_query(server, "i", "Set(77, f=3)")
        assert out["results"][0] is True
        out = self._pb_query(server, "i", "Rows(f)")
        assert out["results"][0]["rows"] == [3, 5]
        out = self._pb_query(server, "i", "GroupBy(Rows(f))")
        gcs = out["results"][0]
        assert {g["group"][0]["rowID"]: g["count"] for g in gcs} == {3: 3, 5: 1}
        out = self._pb_query(server, "i", "MaxRow(field=f)")
        assert out["results"][0]["id"] == 5
        out = self._pb_query(server, "i", "SetRowAttrs(f, 3, note=\"hi\")")
        assert out["results"][0] is None

    def test_error_encoded(self, server):
        self._setup(server)
        import urllib.error

        r = urllib.request.Request(
            server.uri + "/index/i/query",
            data=b"Bogus(f=1)",
            method="POST",
            headers={"Content-Type": "text/plain", "Accept": "application/x-protobuf"},
        )
        try:
            urllib.request.urlopen(r)
            raise AssertionError("expected HTTPError")
        except urllib.error.HTTPError as e:
            from pilosa_tpu.server.wire import decode_query_response

            out = decode_query_response(e.read())
            assert "error" in out


class TestConfigWiredKnobs:
    """Knobs the config-drift rule caught parsed-but-dead, now wired
    (ISSUE r13 tentpole 3)."""

    def test_max_writes_per_request_enforced(self, server):
        req(server, "POST", "/index/i", {})
        req(server, "POST", "/index/i/field/f", {})
        server.api.max_writes_per_request = 2
        try:
            with pytest.raises(urllib.error.HTTPError) as e:
                req(server, "POST", "/index/i/query",
                    b"Set(1, f=1) Set(2, f=1) Set(3, f=1)", raw=True)
            assert e.value.code == 400
            body = json.loads(e.value.read())
            assert body["code"] == "too-many-writes"
            assert "3 write calls" in body["error"]
            # Exactly at the cap: admitted.
            out = req(server, "POST", "/index/i/query",
                      b"Set(4, f=1) Set(5, f=1)")
            assert "results" in out
        finally:
            server.api.max_writes_per_request = 0

    def test_metric_service_none_disables_exposition(self, server):
        server.api.metric_service = "none"
        try:
            with pytest.raises(urllib.error.HTTPError) as e:
                req(server, "GET", "/metrics", raw=True)
            assert e.value.code == 404
            assert json.loads(e.value.read())["code"] == "metrics-disabled"
        finally:
            server.api.metric_service = "memory"
        # Back to memory: the exposition serves again.
        text = req(server, "GET", "/metrics", raw=True)
        assert b"http_requests_total" in text


class TestFinalizationBarrier:
    """Server.quiesce (ISSUE r13 satellite): the deterministic barrier
    for the 'handler finalizes one GIL slice after the client has the
    reply bytes' race class that PR 10 papered over with per-test poll
    loops."""

    def test_idle_server_quiesces_immediately(self, server):
        assert server.quiesce(timeout=0.5)

    def test_quiesce_blocks_until_inflight_request_finalizes(self, server):
        """A request still executing holds quiesce open; it returns
        only once the handler (reply AND post-reply bookkeeping) is
        done — asserted via the in-flight query gauge being zero with
        NO polling."""
        import queue
        import threading

        req(server, "POST", "/index/i", {})
        req(server, "POST", "/index/i/field/f", {})
        results: queue.Queue = queue.Queue()

        def one_query():
            results.put(
                req(server, "POST", "/index/i/query", b"Count(Row(f=1))",
                    raw=True)
            )

        threads = [
            threading.Thread(target=one_query, daemon=True)
            for _ in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=10)
        # The clients all HAVE their bytes; the handlers may still be
        # in their finally blocks. After quiesce, the gauge must read
        # zero immediately — this is the exact assertion that flaked
        # as a poll loop before.
        assert server.quiesce(timeout=5.0)
        assert server.api._inflight_queries == 0
        assert results.qsize() == 4

    def test_quiesce_times_out_while_request_held_open(self, server):
        """quiesce reports False (not a hang) when a request genuinely
        outlives the timeout."""
        srv = server._httpd
        srv._request_begin()  # simulate a stuck in-flight request
        try:
            assert not server.quiesce(timeout=0.1)
        finally:
            srv._request_end()
        assert server.quiesce(timeout=1.0)


class TestAdmissionControl:
    """In-flight /query cap (ISSUE r11 satellite): past the cap the
    server sheds deliberately — 429 + Retry-After + code=overloaded,
    counted — instead of queueing toward an accept-path reset."""

    def _fill(self, server, n):
        self._drain(server)
        for _ in range(n):
            assert server.api.begin_query()

    @staticmethod
    def _drain(server) -> None:
        """The handler's `finally: end_query()` runs ~1 ms AFTER the
        client has read the response body; quiesce() is the server's
        finalization barrier for exactly this race (ISSUE r13 — this
        used to be an ad-hoc poll loop on the gauge)."""
        assert server.quiesce(timeout=5.0)
        assert server.api._inflight_queries == 0

    def test_shed_past_cap_then_recover(self, server):
        from pilosa_tpu.utils.stats import global_stats

        req(server, "POST", "/index/i", {})
        req(server, "POST", "/index/i/field/f", {})
        req(server, "POST", "/index/i/query", b"Set(1, f=1)", raw=True)
        api = server.api
        api.max_inflight_queries = 2
        before = global_stats.snapshot()["counters"].get(
            "http_requests_shed_total", 0.0
        )
        self._fill(server, 2)  # saturate the cap deterministically
        try:
            with pytest.raises(urllib.error.HTTPError) as e:
                req(server, "POST", "/index/i/query", b"Count(Row(f=1))", raw=True)
            assert e.value.code == 429
            assert e.value.headers.get("Retry-After") == "1"
            body = json.loads(e.value.read())
            assert body["code"] == "overloaded"
            after = global_stats.snapshot()["counters"].get(
                "http_requests_shed_total", 0.0
            )
            assert after - before == 1
        finally:
            api.end_query()
            api.end_query()
        # Slots freed: the same query is admitted and answers normally.
        out = req(server, "POST", "/index/i/query", b"Count(Row(f=1))")
        assert out["results"] == [1]

    def test_unbounded_by_default(self, server):
        assert server.api.max_inflight_queries == 0
        assert server.api.begin_query()
        server.api.end_query()

    def test_shed_keeps_keepalive_connection_usable(self, server):
        """The shed 429 must drain the unread body: a keep-alive client's
        NEXT request on the same socket must parse cleanly, not desync
        into the shed request's body."""
        import http.client

        req(server, "POST", "/index/i", {})
        req(server, "POST", "/index/i/field/f", {})
        req(server, "POST", "/index/i/query", b"Set(1, f=1)", raw=True)
        api = server.api
        api.max_inflight_queries = 1
        self._drain(server)
        assert api.begin_query()
        try:
            conn = http.client.HTTPConnection(server.host, server.port)
            conn.request(
                "POST", "/index/i/query", b"Count(Row(f=1))",
                {"Content-Type": "application/json"},
            )
            resp = conn.getresponse()
            assert resp.status == 429
            resp.read()
        finally:
            api.end_query()
        # Same connection, next request: admitted and correct.
        conn.request(
            "POST", "/index/i/query", b"Count(Row(f=1))",
            {"Content-Type": "application/json"},
        )
        resp = conn.getresponse()
        assert resp.status == 200
        assert json.loads(resp.read())["results"] == [1]
        conn.close()


class TestRuntimeMonitor:
    def test_gauges_populate(self, server):
        from pilosa_tpu.utils.monitor import RuntimeMonitor
        from pilosa_tpu.utils.stats import global_stats

        mon = RuntimeMonitor(server.api.holder)
        mon.poll_once()
        text = global_stats.prometheus_text()
        lines = {
            l.split()[0]: float(l.split()[1])
            for l in text.splitlines()
            if l and not l.startswith("#") and len(l.split()) == 2
        }
        assert lines.get("pilosa_runtime_rss_bytes", 0) > 0
        assert lines.get("pilosa_runtime_threads", 0) >= 1
        assert lines.get("pilosa_runtime_open_fds", 0) > 0

    def test_diagnostics_endpoint(self, server):
        out = req(server, "GET", "/debug/diagnostics")
        assert out["version"]
        assert out["platform"]["python"]
        assert out["rss_bytes"] > 0
        assert "uptime_seconds" in out


class TestRequestParsing:
    """The hand-rolled HTTP/1.x request parser (server/http.py
    parse_request replaced the stdlib's email.feedparser path) must
    mirror stdlib semantics on the adversarial edges."""

    def _raw(self, server, payload: bytes) -> bytes:
        s = socket.create_connection(("localhost", server.port), timeout=10)
        try:
            s.sendall(payload)
            s.shutdown(socket.SHUT_WR)
            out = b""
            while True:
                chunk = s.recv(65536)
                if not chunk:
                    break
                out += chunk
            return out
        finally:
            s.close()

    def test_status_ok(self, server):
        out = self._raw(server, b"GET /status HTTP/1.1\r\nHost: x\r\n\r\n")
        assert out.startswith(b"HTTP/1.1 200")

    def test_bad_request_line(self, server):
        out = self._raw(server, b"GARBAGE\r\n\r\n")
        assert b" 400 " in out.split(b"\r\n", 1)[0]

    def test_bad_version(self, server):
        out = self._raw(server, b"GET /status HTTQ/1.1\r\n\r\n")
        assert b" 400 " in out.split(b"\r\n", 1)[0]

    def test_http2_rejected_505(self, server):
        out = self._raw(server, b"GET /status HTTP/2.0\r\n\r\n")
        assert b" 505 " in out.split(b"\r\n", 1)[0]

    def test_oversized_header_line_431(self, server):
        big = b"X-Big: " + b"a" * 70000
        out = self._raw(server, b"GET /status HTTP/1.1\r\n" + big + b"\r\n\r\n")
        assert b" 431 " in out.split(b"\r\n", 1)[0]

    def test_too_many_headers_431(self, server):
        headers = b"".join(b"X-H%d: v\r\n" % i for i in range(150))
        out = self._raw(server, b"GET /status HTTP/1.1\r\n" + headers + b"\r\n")
        assert b" 431 " in out.split(b"\r\n", 1)[0]

    def test_conflicting_content_length_rejected(self, server):
        # RFC 7230 §3.3.2: differing repeated Content-Length must be
        # rejected — accepting either value desyncs front proxies that
        # pick the other one (CL.CL request smuggling).
        payload = (
            b"POST /index/dup HTTP/1.1\r\nHost: x\r\n"
            b"Content-Length: 2\r\nContent-Length: 4\r\n\r\n" + b"{}xx"
        )
        out = self._raw(server, payload)
        assert b" 400 " in out.split(b"\r\n", 1)[0], out[:200]

    def test_identical_duplicate_content_length_ok(self, server):
        payload = (
            b"POST /index/dup2 HTTP/1.1\r\nHost: x\r\n"
            b"Content-Length: 2\r\nContent-Length: 2\r\n\r\n" + b"{}"
        )
        out = self._raw(server, payload)
        assert out.startswith(b"HTTP/1.1 200"), out[:200]

    def test_header_case_insensitive(self, server):
        payload = (
            b"POST /index/ci HTTP/1.1\r\nHost: x\r\n"
            b"cOnTeNt-LeNgTh: 2\r\n\r\n{}"
        )
        out = self._raw(server, payload)
        assert out.startswith(b"HTTP/1.1 200"), out[:200]

    def test_http10_keepalive_honored(self, server):
        s = socket.create_connection(("localhost", server.port), timeout=10)
        try:
            s.sendall(b"GET /status HTTP/1.0\r\nConnection: keep-alive\r\n\r\n")
            first = b""
            while b"}\n" not in first:
                chunk = s.recv(65536)
                if not chunk:
                    break
                first += chunk
            assert first.startswith(b"HTTP/1.1 200")
            # The connection must still be open for a second request.
            s.sendall(b"GET /status HTTP/1.0\r\nConnection: keep-alive\r\n\r\n")
            second = s.recv(65536)
            assert second.startswith(b"HTTP/1.1 200")
        finally:
            s.close()

    def test_chunked_body_decoded(self, server):
        # ISSUE r7 (VERDICT r5 missing #1): chunked bodies decode like
        # the reference's stdlib instead of the old blanket 501. The
        # split JSON body must reassemble before the route parses it.
        payload = (
            b"POST /index/chk HTTP/1.1\r\nHost: x\r\n"
            b"Content-Type: application/json\r\n"
            b"Transfer-Encoding: chunked\r\n\r\n"
            b"1\r\n{\r\n1\r\n}\r\n0\r\n\r\n"
        )
        out = self._raw(server, payload)
        assert out.startswith(b"HTTP/1.1 200"), out[:200]

    def test_chunked_with_extensions_and_keepalive(self, server):
        # Chunk extensions are ignored (RFC 7230 §4.1.1) and the decoder
        # consumes the full frame, so the SECOND pipelined request is
        # served off the same connection — no TE desync.
        payload = (
            b"POST /index/chk2 HTTP/1.1\r\nHost: x\r\n"
            b"Content-Type: application/json\r\n"
            b"Transfer-Encoding: chunked\r\n\r\n"
            b"2 ;ext=1\r\n{}\r\n0\r\n\r\n"  # BWS before ';' is grammar-legal
            b"GET /status HTTP/1.1\r\nHost: x\r\n\r\n"
        )
        out = self._raw(server, payload)
        assert out.startswith(b"HTTP/1.1 200"), out[:200]
        assert out.count(b"HTTP/1.1 200") == 2, out[:400]

    def test_chunked_trailers_rejected(self, server):
        payload = (
            b"POST /index/chk3 HTTP/1.1\r\nHost: x\r\n"
            b"Transfer-Encoding: chunked\r\n\r\n"
            b"2\r\n{}\r\n0\r\nX-Trailer: v\r\n\r\n"
        )
        out = self._raw(server, payload)
        assert b" 400 " in out.split(b"\r\n", 1)[0], out[:200]
        assert out.count(b"HTTP/1.1 ") == 1  # connection closed

    def test_chunked_with_content_length_rejected(self, server):
        # TE + CL is the classic TE.CL smuggling shape (RFC 7230
        # §3.3.3): reject outright, never pick a winner.
        payload = (
            b"POST /index/chk4 HTTP/1.1\r\nHost: x\r\n"
            b"Content-Length: 2\r\nTransfer-Encoding: chunked\r\n\r\n"
            b"2\r\n{}\r\n0\r\n\r\n"
        )
        out = self._raw(server, payload)
        assert b" 400 " in out.split(b"\r\n", 1)[0], out[:200]

    def test_repeated_transfer_encoding_rejected(self, server):
        # TE.TE: RFC 7230 joins repeated TE headers into a coding list
        # ("chunked, gzip" — malformed, chunked not final); first-wins
        # would decode framing a joining proxy sees differently.
        payload = (
            b"POST /index/chk8 HTTP/1.1\r\nHost: x\r\n"
            b"Transfer-Encoding: chunked\r\n"
            b"Transfer-Encoding: gzip\r\n\r\n"
            b"2\r\n{}\r\n0\r\n\r\n"
        )
        out = self._raw(server, payload)
        assert b" 400 " in out.split(b"\r\n", 1)[0], out[:200]

    def test_non_chunked_coding_still_501(self, server):
        payload = (
            b"POST /index/chk5 HTTP/1.1\r\nHost: x\r\n"
            b"Transfer-Encoding: gzip\r\n\r\n"
        )
        out = self._raw(server, payload)
        assert b" 501 " in out.split(b"\r\n", 1)[0], out[:200]

    def test_chunked_size_cap_413(self, server):
        # A declared chunk past the cap dies at the size line — the
        # decoder never buffers unbounded frames.
        payload = (
            b"POST /index/chk6 HTTP/1.1\r\nHost: x\r\n"
            b"Transfer-Encoding: chunked\r\n\r\n"
            b"fffffff0\r\n"
        )
        out = self._raw(server, payload)
        assert b" 413 " in out.split(b"\r\n", 1)[0], out[:200]

    def test_chunked_malformed_size_rejected(self, server):
        payload = (
            b"POST /index/chk7 HTTP/1.1\r\nHost: x\r\n"
            b"Transfer-Encoding: chunked\r\n\r\n"
            b"zz\r\n{}\r\n0\r\n\r\n"
        )
        out = self._raw(server, payload)
        assert b" 400 " in out.split(b"\r\n", 1)[0], out[:200]

    def test_obs_fold_continuation_rejected_400(self, server):
        # RFC 7230 §3.2.4: a server must reject or normalize obs-fold;
        # silently dropping "  continued" diverges from folding proxies.
        payload = (
            b"GET /status HTTP/1.1\r\nHost: x\r\n"
            b"X-Folded: part1\r\n  part2\r\n\r\n"
        )
        out = self._raw(server, payload)
        assert b" 400 " in out.split(b"\r\n", 1)[0], out[:200]

    def test_header_without_colon_rejected_400(self, server):
        payload = b"GET /status HTTP/1.1\r\nHost: x\r\nnocolonhere\r\n\r\n"
        out = self._raw(server, payload)
        assert b" 400 " in out.split(b"\r\n", 1)[0], out[:200]

    def test_malformed_content_length_rejected_400(self, server):
        # "abc" (or unicode digits, or "-5") must die at parse time: a
        # later 500 would not close the connection and the unread body
        # would desync the keep-alive stream (code review r5 finding).
        for bad in (b"abc", b"-5", b"\xb2", b"1.5"):
            payload = (
                b"POST /index/cl HTTP/1.1\r\nHost: x\r\n"
                b"Content-Length: " + bad + b"\r\n\r\nxx"
            )
            out = self._raw(server, payload)
            assert b" 400 " in out.split(b"\r\n", 1)[0], (bad, out[:200])

    def test_embedded_bare_cr_in_header_rejected_400(self, server):
        # readline splits on \n only; "X-Bad\r: v" would otherwise be
        # silently normalized to "X-Bad" while a CR-terminating proxy
        # sees a different header set (code review r5 finding).
        payload = b"GET /status HTTP/1.1\r\nHost: x\r\nX-Bad\r: v\r\n\r\n"
        out = self._raw(server, payload)
        assert b" 400 " in out.split(b"\r\n", 1)[0], out[:200]

    def test_whitespace_inside_header_name_rejected_400(self, server):
        payload = b"GET /status HTTP/1.1\r\nX Y: v\r\n\r\n"
        out = self._raw(server, payload)
        assert b" 400 " in out.split(b"\r\n", 1)[0], out[:200]

    def test_ctl_in_header_value_rejected_400(self, server):
        for bad in (b"a\x00b", b"a\x0bb", b"a\x7fb"):
            payload = (
                b"GET /status HTTP/1.1\r\nHost: x\r\nX-Meta: " + bad
                + b"\r\n\r\n"
            )
            out = self._raw(server, payload)
            assert b" 400 " in out.split(b"\r\n", 1)[0], (bad, out[:200])
        # HTAB in a value is legal field-content
        out = self._raw(
            server, b"GET /status HTTP/1.1\r\nHost: x\r\nX-Meta: a\tb\r\n\r\n"
        )
        assert out.startswith(b"HTTP/1.1 200"), out[:200]

    def test_space_before_colon_rejected_400(self, server):
        # "Host : x" — RFC 7230 §3.2.4 explicitly requires 400 for
        # whitespace between field-name and colon (proxies disagree on
        # whether the name is "Host" or "Host ").
        payload = b"GET /status HTTP/1.1\r\nHost : x\r\n\r\n"
        out = self._raw(server, payload)
        assert b" 400 " in out.split(b"\r\n", 1)[0], out[:200]

    def test_connection_close_honored(self, server):
        s = socket.create_connection(("localhost", server.port), timeout=10)
        try:
            s.sendall(b"GET /status HTTP/1.1\r\nConnection: close\r\n\r\n")
            out = b""
            while True:  # server must close after the response
                chunk = s.recv(65536)
                if not chunk:
                    break
                out += chunk
            assert out.startswith(b"HTTP/1.1 200")
        finally:
            s.close()


class TestPprof:
    """/debug/pprof/* — the live CPU-profile analog (VERDICT r3 #3)."""

    def test_start_stop_and_profile(self):
        import threading
        import time

        from pilosa_tpu.utils.profiler import SamplingProfiler

        p = SamplingProfiler(interval=0.002)
        stop = threading.Event()

        def burn():
            while not stop.is_set():
                sum(i * i for i in range(500))

        t = threading.Thread(target=burn, daemon=True)
        t.start()
        assert p.start()
        assert not p.start()  # second session refused
        # Deadline-based wait: a fixed 0.1 s sleep flaked on this 1-core
        # host when the whole suite starved the sampler thread below 10
        # samples; wait for the samples themselves instead.
        deadline = time.time() + 10
        while p._samples < 10 and time.time() < deadline:
            time.sleep(0.02)
        # top=50, not 10: the sampler records EVERY thread each tick, and
        # blocked daemon threads accumulated across the suite all sample
        # at one stable frame apiece — enough of them crowd a hot but
        # frame-alternating burn loop out of a top-10 (full-suite flake).
        rep = p.stop(top=50)
        stop.set()
        t.join()
        assert rep["samples"] >= 10
        assert rep["frames"]
        funcs = {f["function"] for f in rep["frames"]}
        assert "burn" in funcs or "<genexpr>" in funcs
        # restartable
        assert p.start()
        p.stop()

    def test_http_endpoints(self, server):
        import json as _json
        import urllib.request

        base = f"http://localhost:{server.port}"
        req = urllib.request.Request(f"{base}/debug/pprof/start", b"", method="POST")
        assert _json.loads(urllib.request.urlopen(req).read())["profiling"]
        req = urllib.request.Request(
            f"{base}/debug/pprof/stop?top=5", b"", method="POST"
        )
        rep = _json.loads(urllib.request.urlopen(req).read())
        assert "samples" in rep and "frames" in rep
