"""Tier-1 wiring for tools/check_metrics_docs.py (ISSUE r8 satellite):
the metric catalogue in docs/observability.md can never rot — every
emitted name must be documented and every documented name must exist."""

import importlib.util
import pathlib


def _load_checker():
    path = (
        pathlib.Path(__file__).resolve().parent.parent
        / "tools"
        / "check_metrics_docs.py"
    )
    spec = importlib.util.spec_from_file_location("check_metrics_docs", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_metrics_docs_in_sync(capsys):
    checker = _load_checker()
    rc = checker.main()
    out = capsys.readouterr().out
    assert rc == 0, f"metric catalogue drift:\n{out}"


def test_checker_catches_drift():
    """The guard itself must be live: an emitted-but-undocumented name
    and a documented-but-phantom name are both reported."""
    checker = _load_checker()
    src = checker.source_metrics()
    doc_exact, doc_wild = checker.doc_tokens()
    # Direction 1: a name only the source knows would be flagged.
    fake = "definitely_not_documented_total"
    assert fake not in doc_exact
    assert not any(fake.startswith(w) for w in doc_wild)
    # Direction 2: a name only the docs know would be flagged.
    assert "peer_rpc_seconds" in src  # sanity: scan sees real emitters
    assert "made_up_metric_total" not in src
