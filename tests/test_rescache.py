"""Epoch-tagged result cache tests (ISSUE r12): canonicalization
equivalence pins, epoch/journal addressability semantics, the bounded-
staleness contract, strict LRU size accounting, the differential
cached-vs-uncached contract under import churn (including the TopN
rank-cache interaction), and the HTTP bypass/marker surface."""

import http.client
import json

import numpy as np
import pytest

from pilosa_tpu.core import Holder
from pilosa_tpu.core.field import options_for_int
from pilosa_tpu.exec import ExecOptions, Executor
from pilosa_tpu.exec.rescache import ResultCache, result_nbytes
from pilosa_tpu.exec.result import result_to_json
from pilosa_tpu.pql import canonical_key, canonicalize, parse_string
from pilosa_tpu.shardwidth import SHARD_WIDTH
from pilosa_tpu.utils.stats import global_stats


def one(q):
    return parse_string(q).calls[0]


def encode(results):
    return json.dumps([result_to_json(r) for r in results], sort_keys=True)


@pytest.fixture
def holder():
    h = Holder(None).open()
    idx = h.create_index("i")
    f = idx.create_field("f")
    g = idx.create_field("g")
    rng = np.random.default_rng(5)
    for shard in range(3):
        base = shard * SHARD_WIDTH
        for field in (f, g):
            rows = np.repeat(np.arange(4, dtype=np.uint64), 200)
            cols = rng.integers(0, SHARD_WIDTH, rows.size).astype(
                np.uint64
            ) + base
            field.import_bits(rows, cols)
    v = idx.create_field("v", options_for_int(-10000, 10000))
    cols = np.arange(300, dtype=np.uint64) * 17 % (3 * SHARD_WIDTH)
    v.import_value(
        np.unique(cols), (np.unique(cols).astype(np.int64) % 400) - 200
    )
    yield h
    h.close()


def cached_executor(h, max_bytes=1 << 20, max_staleness=0):
    ex = Executor(h)
    ex.rescache = ResultCache(
        h, max_bytes=max_bytes, max_staleness=max_staleness
    )
    return ex


class TestCanonicalization:
    def test_intersect_order_shares_key(self):
        a = one("Count(Intersect(Row(f=1), Row(g=2)))")
        b = one("Count(Intersect(Row(g=2), Row(f=1)))")
        assert canonical_key(a) == canonical_key(b)

    def test_union_xor_share_keys(self):
        for op in ("Union", "Xor"):
            a = one(f"{op}(Row(f=1), Row(g=2), Row(f=3))")
            b = one(f"{op}(Row(f=3), Row(f=1), Row(g=2))")
            assert canonical_key(a) == canonical_key(b), op

    def test_difference_order_does_not_share(self):
        a = one("Count(Difference(Row(f=1), Row(g=2)))")
        b = one("Count(Difference(Row(g=2), Row(f=1)))")
        assert canonical_key(a) != canonical_key(b)

    def test_nested_commutative_sorts(self):
        a = one("Count(Intersect(Union(Row(g=2), Row(f=1)), Row(f=3)))")
        b = one("Count(Intersect(Row(f=3), Union(Row(f=1), Row(g=2))))")
        assert canonical_key(a) == canonical_key(b)

    def test_distinct_literals_distinct_keys(self):
        assert canonical_key(one("Row(f=1)")) != canonical_key(one("Row(f=2)"))
        assert canonical_key(one('Row(f="a")')) != canonical_key(
            one('Row(f="b")')
        )

    def test_copy_on_write_identity(self):
        # Already-canonical trees come back unchanged — no allocation on
        # the hot path (the _translate_call discipline).
        c = one("Count(Row(f=1))")
        assert canonicalize(c) is c
        swapped = one("Intersect(Row(g=1), Row(f=1))")
        out = canonicalize(swapped)
        assert out is not swapped
        assert [k.to_string() for k in out.children] == sorted(
            k.to_string() for k in swapped.children
        )

    def test_groupby_filter_arg_canonicalizes(self):
        a = one("GroupBy(Rows(f), filter=Intersect(Row(g=2), Row(f=1)))")
        b = one("GroupBy(Rows(f), filter=Intersect(Row(f=1), Row(g=2)))")
        assert canonical_key(a) == canonical_key(b)


class TestAddressability:
    def test_hit_miss_and_negative_result(self, holder):
        ex = cached_executor(holder)
        # f=9 has no bits: the empty/zero answer caches like any other.
        for q in ("Count(Row(f=1))", "Count(Row(f=9))"):
            first = ex.execute("i", q)
            second = ex.execute("i", q)
            assert first == second
        d = ex.rescache.debug_dump()
        assert d["hits"] == 2 and d["misses"] == 2 and d["inserts"] == 2

    def test_covered_write_stops_addressing(self, holder):
        ex = cached_executor(holder)
        q = "Count(Row(f=1))"
        before = ex.execute("i", q)[0]
        holder.index("i").field("f").set_bit(1, 5)
        after = ex.execute("i", q)
        d = ex.rescache.debug_dump()
        assert d["misses"] == 2 and d["hits"] == 0
        assert after[0] in (before, before + 1)  # col 5 may already be set

    def test_disjoint_shard_write_keeps_entry(self, holder):
        # The journal-refined epoch trick: a write to a shard OUTSIDE
        # the query's pinned shard set keeps the entry addressable.
        ex = cached_executor(holder)
        q = "Count(Row(f=1))"
        ex.execute("i", q, shards=[0])
        holder.index("i").field("f").set_bit(1, 2 * SHARD_WIDTH + 9)
        ex.execute("i", q, shards=[0])
        d = ex.rescache.debug_dump()
        assert d["hits"] == 1 and d["misses"] == 1

    def test_unrelated_field_write_keeps_entry(self, holder):
        ex = cached_executor(holder)
        q = "Count(Row(f=1))"
        ex.execute("i", q)
        holder.index("i").field("g").set_bit(0, 3)
        ex.execute("i", q)
        d = ex.rescache.debug_dump()
        assert d["hits"] == 1 and d["misses"] == 1

    def test_empty_field_first_write_not_stale(self, holder):
        # Structural axis: an entry computed over an EMPTY field (no
        # views at all) must stop being addressable when the first
        # write creates the view — no data generation exists to
        # witness it, the field structure_version does.
        idx = holder.index("i")
        idx.create_field("fresh")
        ex = cached_executor(holder)
        q = "Count(Row(fresh=1))"
        assert ex.execute("i", q) == [0]
        idx.field("fresh").set_bit(1, 0)
        assert ex.execute("i", q) == [1]

    def test_recreated_field_not_stale(self, holder):
        idx = holder.index("i")
        ex = cached_executor(holder)
        q = "Count(Row(g=1))"
        before = ex.execute("i", q)[0]
        assert before > 0
        idx.delete_field("g")
        idx.create_field("g")
        assert ex.execute("i", q) == [0]

    def test_max_staleness_contract(self, holder):
        # Exact-epoch (0): any covered write is a miss. Bounded (large
        # N): the same write is served stale, counted as a stale hit.
        exact = cached_executor(holder, max_staleness=0)
        q = "Count(Row(f=2))"
        exact.execute("i", q)
        holder.index("i").field("f").set_bit(2, 11)
        exact.execute("i", q)
        assert exact.rescache.debug_dump()["hits"] == 0

        loose = cached_executor(holder, max_staleness=10_000)
        stale_val = loose.execute("i", q)[0]
        holder.index("i").field("f").set_bit(2, 12)
        served = loose.execute("i", q)
        d = loose.rescache.debug_dump()
        assert d["hits"] == 1 and d["staleHits"] == 1
        assert served[0] == stale_val  # the stale answer, by contract

    def test_attr_write_invalidates_index(self, holder):
        ex = cached_executor(holder)
        q = "Row(f=1)"
        ex.execute("i", q)
        ex.execute("i", "SetRowAttrs(f, 1, color=\"blue\")")
        row = ex.execute("i", q)[0]
        assert row.attrs == {"color": "blue"}

    def test_clustered_coordinator_without_provider_never_consults(self, holder):
        # A wired mapper means answers depend on peer-held shards. With
        # no peer-epoch provider installed (ISSUE r15 tentpole 3) those
        # writes are unwitnessable and the cache must stay out — the
        # pre-r15 contract, still the safety rail for direct Executor
        # wiring that bypasses Cluster.attach.
        ex = cached_executor(holder)
        ex.mapper = lambda index, shards, c, map_fn, reduce_fn, opt: (
            sum(map_fn(s) for s in shards)
        )
        ex.execute("i", "Count(Row(f=1))")
        ex.execute("i", "Count(Row(f=1))")
        d = ex.rescache.debug_dump()
        assert d["hits"] == 0 and d["misses"] == 0

    def test_remote_leg_key_never_collides(self, holder):
        # Remote per-node partials (untrimmed TopN) cache under a
        # remote-flagged key: a coordinator answer for the same PQL
        # must never be served a partial, nor vice versa.
        ex = cached_executor(holder)
        q = "TopN(f, n=2)"
        local = ex.execute("i", q)[0]
        remote = ex.execute("i", q, opt=ExecOptions(remote=True))[0]
        d = ex.rescache.debug_dump()
        assert d["misses"] == 2 and d["entryCount"] == 2
        assert ex.execute("i", q)[0] is local
        assert (
            ex.execute("i", q, opt=ExecOptions(remote=True))[0] is remote
        )

    def test_uncacheable_calls_pass_through(self, holder):
        ex = cached_executor(holder)
        # Writes and unknown-coverage calls never enter the cache.
        ex.execute("i", "Set(3, f=1)")
        ex.execute("i", "Rows(f)")
        d = ex.rescache.debug_dump()
        assert d["inserts"] == 0 and d["misses"] == 0

    def test_bypass_skips_lookup_and_population(self, holder):
        ex = cached_executor(holder)
        opt = ExecOptions(cache_bypass=True)
        ex.execute("i", "Count(Row(f=1))", opt=opt)
        ex.execute("i", "Count(Row(f=1))", opt=opt)
        d = ex.rescache.debug_dump()
        assert d["bypass"] == 2 and d["inserts"] == 0 and d["hits"] == 0


class TestGroupByCaching:
    """Terminal GroupBy rides the result cache end to end (ISSUE 17):
    epoch-addressed hits, wire-bytes reuse across requests, and write
    invalidation on every grouped field."""

    Q = "GroupBy(Rows(f), Rows(g), Rows(h))"

    def _add_h(self, holder):
        # The fixture's f/g bits are sparse-random (empty triple
        # intersections); plant overlapping columns across all three
        # fields so the GroupBy answer is nonempty.
        idx = holder.index("i")
        hf = idx.create_field("h")
        for shard in range(3):
            cols = np.arange(120, dtype=np.uint64) + shard * SHARD_WIDTH
            for fld, nrows in ((idx.field("f"), 4), (idx.field("g"), 4),
                               (hf, 3)):
                rows = (np.arange(120) % nrows).astype(np.uint64)
                fld.import_bits(rows, cols)

    def test_hit_wire_bytes_and_invalidation(self, holder):
        self._add_h(holder)
        ex = cached_executor(holder)
        first = ex.execute("i", self.Q)
        assert len(first[0]) > 0
        assert ex.execute("i", self.Q) == first
        d = ex.rescache.debug_dump()
        assert d["hits"] == 1 and d["misses"] == 1 and d["inserts"] == 1
        # Wire plane: the encoded fragment memoizes on the entry and
        # replays on the next hit (the server splice path's contract).
        flags = ("json", False)
        tok = ex.rescache.begin("i", one(self.Q), [0, 1, 2])
        assert tok is not None and tok.hit
        assert ex.rescache.wire_for(tok, flags) is None
        ex.rescache.attach_wire(tok, flags, b'{"x":1}')
        tok2 = ex.rescache.begin("i", one(self.Q), [0, 1, 2])
        assert tok2.hit and ex.rescache.wire_for(tok2, flags) == b'{"x":1}'
        # A write to ANY grouped field stops addressing the entry.
        holder.index("i").field("h").set_bit(1, 2 * SHARD_WIDTH + 3)
        misses0 = d["misses"]
        after = ex.execute("i", self.Q)
        assert ex.rescache.debug_dump()["misses"] == misses0 + 1
        assert after == Executor(holder).execute("i", self.Q)

    def test_filtered_groupby_caches(self, holder):
        self._add_h(holder)
        ex = cached_executor(holder)
        q = "GroupBy(Rows(f), Rows(g), Rows(h), filter=Row(f=1))"
        first = ex.execute("i", q)
        assert ex.execute("i", q) == first
        d = ex.rescache.debug_dump()
        assert d["hits"] == 1 and d["misses"] == 1


class TestClusterPropagation:
    def test_bypass_rides_remote_legs(self):
        """X-Pilosa-Cache: bypass must cross the node boundary: peers
        consult their LOCAL result caches on remote legs, so a bypassed
        fan-out that didn't propagate would still be served from peer
        caches — the always-fresh contract silently inert exactly where
        staleness is possible."""
        import urllib.request

        from cluster_harness import TestCluster
        from pilosa_tpu.shardwidth import SHARD_WIDTH as SW

        with TestCluster(2) as c:
            c.create_index("i")
            c.create_field("i", "f")
            for shard in range(6):
                c.query(0, "i", f"Set({shard * SW + 1}, f=0)")
            c.await_shard_convergence("i")
            caches = []
            for cn in c.nodes:
                cn.executor.rescache = ResultCache(
                    cn.holder, max_bytes=1 << 20
                )
                caches.append(cn.executor.rescache)
            uri = str(c[0].node.uri)

            def post(headers):
                req = urllib.request.Request(
                    uri + "/index/i/query", data=b"Count(Row(f=0))",
                    method="POST", headers=headers,
                )
                with urllib.request.urlopen(req, timeout=10) as resp:
                    return json.loads(resp.read())

            assert post({})["results"] == [6]
            # The remote node's cache served/populated its local leg...
            assert any(cache.debug_dump()["inserts"] > 0
                       for cache in caches)
            base = [cache.debug_dump() for cache in caches]
            # ...and a bypassed fan-out touches NO cache on any node.
            assert post({"X-Pilosa-Cache": "bypass"})["results"] == [6]
            for cache, b in zip(caches, base):
                d = cache.debug_dump()
                assert d["hits"] == b["hits"], "bypass leg hit a cache"
                assert d["inserts"] == b["inserts"]
                assert d["bypass"] >= b["bypass"]


class TestClusteredCoordinatorCache:
    """ISSUE r15 tentpole 3: with the peer-epoch provider wired
    (Cluster.attach), a CLUSTERED coordinator serves fan-out answers
    from the result cache — keyed on the merged (local + peer) epoch
    vector — and a peer write inside the covered shard set makes the
    entry unservable on the next fan-out."""

    @staticmethod
    def _wire(c):
        for cn in c.nodes:
            cn.executor.rescache = ResultCache(cn.holder, max_bytes=1 << 20)
            # Re-attach: installs the peer-epoch provider on the cache
            # (the CLI wiring order does this in one pass).
            cn.cluster.attach(cn.executor, cn.api)
        return c[0].executor.rescache

    @staticmethod
    def _peer_shard(c, index):
        """A shard owned by node1 only: writes to it never touch
        node0's local views, so ONLY the peer epoch vector witnesses
        them."""
        topo = c[0].cluster.topology
        for s in range(6):
            if topo.shard_nodes(index, s)[0].id == "node1":
                return s
        raise AssertionError("no node1-owned shard in range")

    def test_fanout_hit_marker_and_peer_write_invalidation(self):
        import urllib.request

        from cluster_harness import TestCluster
        from pilosa_tpu.shardwidth import SHARD_WIDTH as SW

        with TestCluster(2) as c:
            c.create_index("i")
            c.create_field("i", "f")
            for shard in range(6):
                c.query(0, "i", f"Set({shard * SW + 1}, f=0)")
            c.await_shard_convergence("i")
            rc = self._wire(c)
            uri = str(c[0].node.uri)

            def post(headers=None):
                req = urllib.request.Request(
                    uri + "/index/i/query", data=b"Count(Row(f=0))",
                    method="POST", headers=headers or {},
                )
                with urllib.request.urlopen(req, timeout=10) as resp:
                    return (
                        resp.read(),
                        resp.headers.get("X-Pilosa-Cache"),
                    )

            # The seeding replica writes already piggybacked node1's
            # epochs onto node0's map, so the first fan-out is a real
            # MISS (commit), the second a HIT — served without fanning
            # out, marker on the response (ISSUE r15 acceptance).
            body1, marker1 = post()
            body2, marker2 = post()
            assert marker2 == "hit", (marker1, marker2)
            assert rc.debug_dump()["hits"] >= 1
            # Byte-identity differential: the cached body must equal a
            # cache-less (bypassed end-to-end) recompute of the same
            # state, byte for byte.
            body_fresh, marker_b = post({"X-Pilosa-Cache": "bypass"})
            assert marker_b == "bypass"
            assert body2 == body_fresh == body1

            # A peer write INSIDE the covered shard set: routed through
            # the coordinator, the peer's response piggybacks its new
            # epochs, and the entry becomes unservable on the next
            # fan-out — which recomputes the fresh answer.
            s = self._peer_shard(c, "i")
            c.query(0, "i", f"Set({s * SW + 2}, f=0)")
            body3, marker3 = post()
            assert marker3 == "miss", marker3
            assert json.loads(body3)["results"] == [7]
            # ...and the repopulated entry serves again, still
            # byte-identical to a fresh recompute across the churn.
            body4, marker4 = post()
            assert marker4 == "hit"
            body_fresh2, _ = post({"X-Pilosa-Cache": "bypass"})
            assert body4 == body_fresh2 == body3

    def test_unknown_peer_state_is_uncacheable(self):
        from cluster_harness import TestCluster
        from pilosa_tpu.shardwidth import SHARD_WIDTH as SW

        with TestCluster(2) as c:
            c.create_index("i")
            c.create_field("i", "f")
            for shard in range(6):
                c.query(0, "i", f"Set({shard * SW + 1}, f=0)")
            c.await_shard_convergence("i")
            rc = self._wire(c)
            # Drop everything the coordinator has heard: with a covering
            # peer's epochs unknown, fan-out answers must not cache (the
            # fan-out's own piggyback repopulates the map, so the NEXT
            # answer becomes cacheable — never a wrong serve meanwhile).
            with c[0].cluster._peer_epochs_lock:
                c[0].cluster._peer_epochs.clear()
            assert c[0].api.query("i", "Count(Row(f=0))")["results"] == [6]
            assert rc.debug_dump()["misses"] == 0  # uncacheable, not a miss
            assert c[0].api.query("i", "Count(Row(f=0))")["results"] == [6]
            assert rc.debug_dump()["misses"] == 1  # map repopulated: miss
            assert c[0].api.query("i", "Count(Row(f=0))")["results"] == [6]
            assert rc.debug_dump()["hits"] == 1

    def test_out_of_order_fold_never_regresses(self):
        """A slow leg's response (carrying an OLD epoch report) must not
        fold back over a newer one already recorded — that would
        re-validate a cache entry a synchronous write invalidation had
        already killed (review finding). Reports order by their newest
        generation, all minted from one per-process counter."""
        from cluster_harness import TestCluster

        old = {"f": {"structure": 1, "views": {"standard": 100}}}
        new = {"f": {"structure": 1, "views": {"standard": 200}}}
        with TestCluster(1) as c:
            cl = c[0].cluster
            cl.fold_peer_epochs(
                {"node": "peerX", "boot": 7, "indexes": {"i": new}}
            )
            cl.fold_peer_epochs(
                {"node": "peerX", "boot": 7, "indexes": {"i": old}}
            )
            with cl._peer_epochs_lock:
                assert cl._peer_epochs["peerX"]["i"] == (7, 200, new)
            # Equal-max (no intervening mint) and newer reports fold.
            newer = {"f": {"structure": 1, "views": {"standard": 300}}}
            cl.fold_peer_epochs(
                {"node": "peerX", "boot": 7, "indexes": {"i": newer}}
            )
            with cl._peer_epochs_lock:
                assert cl._peer_epochs["peerX"]["i"] == (7, 300, newer)
            # A TORN report (lock-free walk on the peer: view b read
            # pre-mint while view a read post-mint, max still high)
            # must not regress an individual stored generation — the
            # merge is per-view monotone, not per-report.
            full = {"f": {"structure": 1,
                          "views": {"a": 400, "b": 350}}}
            torn = {"f": {"structure": 1,
                          "views": {"a": 500, "b": 340}}}
            cl.fold_peer_epochs(
                {"node": "peerY", "boot": 7, "indexes": {"i": full}}
            )
            cl.fold_peer_epochs(
                {"node": "peerY", "boot": 7, "indexes": {"i": torn}}
            )
            with cl._peer_epochs_lock:
                got = cl._peer_epochs["peerY"]["i"]
            assert got[2]["f"]["views"] == {"a": 500, "b": 350}
            # A RESTARTED peer (new boot token) folds wholesale even
            # when its post-clock-step counter mints below the previous
            # life — the merge guard is per-incarnation, never across
            # reboots (and a reboot's fresh truth drops ghost entries).
            reborn = {"f": {"structure": 1, "views": {"standard": 50}}}
            cl.fold_peer_epochs(
                {"node": "peerX", "boot": 8, "indexes": {"i": reborn}}
            )
            with cl._peer_epochs_lock:
                assert cl._peer_epochs["peerX"]["i"] == (8, 50, reborn)


class TestSizeAccounting:
    def test_resident_bytes_sums_exactly(self, holder):
        # The ledger discipline: the gauge and the dump total are the
        # exact sum of per-entry accounted bytes (like /debug/hbm's
        # tier sums).
        ex = cached_executor(holder)
        for rid in range(4):
            ex.execute("i", f"Count(Row(f={rid}))")
            ex.execute("i", f"Row(g={rid})")
        d = ex.rescache.debug_dump()
        assert d["entryCount"] == 8
        assert d["residentBytes"] == sum(e["bytes"] for e in d["entries"])
        gauges = global_stats.snapshot()["gauges"]
        assert gauges["rescache_resident_bytes"] == d["residentBytes"]
        assert gauges["rescache_entries"] == d["entryCount"]

    def test_lru_eviction_under_budget(self, holder):
        ex = cached_executor(holder)
        # Measure one entry's cost, then budget for ~3 of them.
        ex.execute("i", "Count(Row(f=0))")
        per = ex.rescache.resident_bytes()
        ex = cached_executor(holder, max_bytes=3 * per + per // 2)
        for rid in range(6):
            ex.execute("i", f"Count(Row(f={rid}))")
        d = ex.rescache.debug_dump()
        assert d["evictions"] >= 2
        assert d["residentBytes"] <= ex.rescache.max_bytes
        assert d["residentBytes"] == sum(e["bytes"] for e in d["entries"])
        # Coldest evicted first: the surviving entries are the newest.
        queries = [e["query"] for e in d["entries"]]
        assert "Count(Row(f=0))" not in queries
        assert "Count(Row(f=5))" in queries

    def test_oversized_answer_not_retained(self, holder):
        # Budget sized so a Count entry fits but a Row's column array
        # does not: the oversized answer must be dropped WITHOUT
        # flushing the live entries on its way through.
        ex = cached_executor(holder)
        ex.execute("i", "Count(Row(f=0))")
        per = ex.rescache.resident_bytes()
        ex = cached_executor(holder, max_bytes=2 * per)
        ex.execute("i", "Count(Row(f=0))")
        before = ex.rescache.debug_dump()
        assert before["entryCount"] == 1
        ex.execute("i", "Row(f=1)")  # column array alone exceeds budget
        d = ex.rescache.debug_dump()
        assert d["entryCount"] == 1  # survivor intact, not flushed
        assert d["evictions"] == 1  # the churn stays visible
        assert d["residentBytes"] == before["residentBytes"]
        ex.execute("i", "Count(Row(f=0))")
        assert ex.rescache.debug_dump()["hits"] == 1  # still served

    def test_result_nbytes_strictness(self):
        # Estimator sanity: monotone in payload size, never zero.
        from pilosa_tpu.core.row import Row

        small = Row([1, 2, 3])
        big = Row(list(range(1000)))
        assert 0 < result_nbytes(small) < result_nbytes(big)
        assert result_nbytes(None) > 0
        assert result_nbytes([1, "x", None]) > 0


class TestDifferentialUnderChurn:
    QUERIES = (
        "Count(Row(f=1))",
        "Count(Intersect(Row(f=1), Row(g=2)))",
        "Row(f=2)",
        "Union(Row(f=0), Row(g=3))",
        "TopN(f, n=3)",
        "Sum(field=v)",
        "Min(field=v)",
        "Max(field=v)",
        "GroupBy(Rows(f))",
        "Count(Not(Row(f=1)))",
    )

    def test_cached_equals_uncached_across_churn(self, holder):
        """The acceptance contract: at every churn epoch, answers served
        through the cache are byte-identical to a cache-less executor's
        — including TopN, whose per-fragment rank cache invalidates on
        mutation and must never leak a pre-churn ranking through the
        result cache."""
        cached = cached_executor(holder, max_bytes=4 << 20)
        plain = Executor(holder)
        idx = holder.index("i")
        rng = np.random.default_rng(77)
        for epoch in range(5):
            # Serve everything twice: the second pass is the hot path
            # (hits at this epoch), both must equal the uncached oracle.
            for _ in range(2):
                got = [cached.execute("i", q)[0] for q in self.QUERIES]
                want = [plain.execute("i", q)[0] for q in self.QUERIES]
                assert encode(got) == encode(want), f"epoch {epoch}"
            assert cached.rescache.debug_dump()["hits"] > 0
            # Churn window: set-bit imports AND BSI import_value, the
            # two write planes with distinct freshness paths.
            shard = int(rng.integers(0, 3))
            rows = rng.integers(0, 4, 40).astype(np.uint64)
            cols = rng.integers(0, SHARD_WIDTH, 40).astype(
                np.uint64
            ) + shard * SHARD_WIDTH
            idx.field("f").import_bits(rows, cols)
            vcols = np.unique(
                rng.integers(0, 3 * SHARD_WIDTH, 20).astype(np.uint64)
            )
            idx.field("v").import_value(
                vcols, rng.integers(-200, 200, vcols.size)
            )

    def test_hit_rate_recovers_after_churn(self, holder):
        cached = cached_executor(holder)
        for q in self.QUERIES[:4]:
            cached.execute("i", q)
        h0 = cached.rescache.debug_dump()["hits"]
        for q in self.QUERIES[:4]:
            cached.execute("i", q)
        assert cached.rescache.debug_dump()["hits"] == h0 + 4
        # Burst: everything covered goes unaddressable...
        holder.index("i").field("f").set_bit(0, 1)
        holder.index("i").field("g").set_bit(0, 1)
        for q in self.QUERIES[:4]:
            cached.execute("i", q)
        assert cached.rescache.debug_dump()["hits"] == h0 + 4
        # ...and one repopulating pass restores hits.
        for q in self.QUERIES[:4]:
            cached.execute("i", q)
        assert cached.rescache.debug_dump()["hits"] == h0 + 8


class TestHTTPSurface:
    @pytest.fixture
    def server(self, holder):
        from pilosa_tpu.server.api import API
        from pilosa_tpu.server.http import Server

        ex = cached_executor(holder)
        srv = Server(API(holder, ex), host="localhost", port=0).open()
        yield srv, ex
        srv.close()

    def _post(self, srv, body, headers=None):
        conn = http.client.HTTPConnection("localhost", srv.port)
        h = {"Content-Type": "application/json"}
        h.update(headers or {})
        conn.request("POST", "/index/i/query", body, h)
        resp = conn.getresponse()
        out = (resp.getheader("X-Pilosa-Cache"), json.loads(resp.read()))
        conn.close()
        return out

    def test_import_response_carries_epoch_piggyback(self, server):
        """Imports are writes: a peer-issued import's response must
        carry the post-write epochs, or a coordinator-routed import
        would leave the coordinator serving cached pre-import fan-outs
        until the next ~1 s probe fold (review finding; the documented
        contract says writes invalidate synchronously with their own
        response)."""
        srv, _ = server
        body = json.dumps({"rowIDs": [1], "columnIDs": [2]})
        h = {"Content-Type": "application/json"}
        conn = http.client.HTTPConnection("localhost", srv.port)
        conn.request("POST", "/index/i/field/f/import?remote=true", body, h)
        resp = conn.getresponse()
        resp.read()
        hdr = resp.getheader("X-Pilosa-View-Epochs")
        conn.close()
        assert hdr and json.loads(hdr)["indexes"]["i"]["f"]["views"]
        # External imports never pay the report bytes.
        conn = http.client.HTTPConnection("localhost", srv.port)
        conn.request("POST", "/index/i/field/f/import", body, h)
        resp = conn.getresponse()
        resp.read()
        assert resp.getheader("X-Pilosa-View-Epochs") is None
        conn.close()

    def test_marker_and_bypass_header(self, server):
        srv, _ = server
        q = "Count(Row(f=1))"
        assert self._post(srv, q)[0] == "miss"
        marker, body = self._post(srv, q)
        assert marker == "hit"
        # Bypass: always-fresh, never populates, marked as such.
        marker, bypass_body = self._post(
            srv, q, {"X-Pilosa-Cache": "bypass"}
        )
        assert marker == "bypass"
        assert bypass_body == body  # byte-identical answers
        assert self._post(srv, q + q)[0] in ("hit", "partial")

    def test_marker_mixed_uncacheable_is_partial(self, server):
        # A request mixing a cached Count with an uncacheable Rows must
        # NOT claim `hit`: part of the response was computed fresh.
        srv, _ = server
        q = "Count(Row(f=1))"
        self._post(srv, q)  # populate
        marker, _ = self._post(srv, q + "Rows(f)")
        assert marker == "partial"

    def test_debug_rescache_endpoint(self, server):
        srv, ex = server
        self._post(srv, "Count(Row(f=1))")
        self._post(srv, "Count(Row(f=1))")
        conn = http.client.HTTPConnection("localhost", srv.port)
        conn.request("GET", "/debug/rescache")
        d = json.loads(conn.getresponse().read())
        conn.close()
        assert d["enabled"] is True
        assert d["hits"] >= 1 and d["entryCount"] >= 1
        assert d["residentBytes"] == sum(e["bytes"] for e in d["entries"])
        assert all(
            set(e) >= {"index", "query", "bytes", "hits", "ageSeconds"}
            for e in d["entries"]
        )

    def test_debug_rescache_disabled(self, holder):
        from pilosa_tpu.server.api import API
        from pilosa_tpu.server.http import Server

        srv = Server(API(holder, Executor(holder)), host="localhost",
                     port=0).open()
        try:
            conn = http.client.HTTPConnection("localhost", srv.port)
            conn.request("GET", "/debug/rescache")
            d = json.loads(conn.getresponse().read())
            conn.close()
            assert d["enabled"] is False
        finally:
            srv.close()

    def test_shed_request_never_caches(self, holder):
        # Admission gating composes: a 429-shed query must neither hit
        # nor populate (it never reaches the executor).
        from pilosa_tpu.server.api import API
        from pilosa_tpu.server.http import Server

        ex = cached_executor(holder)
        api = API(holder, ex)
        api.max_inflight_queries = 1
        # Saturate the gate directly, then post: the request sheds.
        assert api.begin_query()
        srv = Server(api, host="localhost", port=0).open()
        try:
            conn = http.client.HTTPConnection("localhost", srv.port)
            conn.request(
                "POST", "/index/i/query", "Count(Row(f=1))",
                {"Content-Type": "application/json"},
            )
            resp = conn.getresponse()
            assert resp.status == 429
            resp.read()
            conn.close()
        finally:
            srv.close()
            api.end_query()
        d = ex.rescache.debug_dump()
        assert d["inserts"] == 0 and d["misses"] == 0


class TestConfigWiring:
    def test_knobs_parse(self):
        from pilosa_tpu.server.config import Config

        cfg = Config.from_sources(env={
            "PILOSA_TPU_MAX_RESULT_CACHE_BYTES": "1048576",
            "PILOSA_TPU_MAX_STALENESS": "3",
            "PILOSA_TPU_CACHE_ENABLED": "false",
        })
        assert cfg.max_result_cache_bytes == 1 << 20
        assert cfg.max_staleness == 3
        assert cfg.cache_enabled is False
        d = cfg.to_dict()
        assert d["max-result-cache-bytes"] == 1 << 20
        assert d["max-staleness"] == 3
        assert d["cache-enabled"] is False
        assert "max-result-cache-bytes = 1048576" in cfg.toml_text()

    def test_zero_bytes_means_disabled(self):
        with pytest.raises(ValueError):
            ResultCache(None, max_bytes=0)
