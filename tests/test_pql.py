"""PQL parser tests — cases mirror the reference grammar (pql/pql.peg) and
the query shapes exercised throughout the reference's executor_test.go."""

import pytest

from pilosa_tpu.pql import (
    BETWEEN,
    EQ,
    GT,
    LT,
    NEQ,
    Call,
    Condition,
    ParseError,
    parse_string,
)


def one(q):
    query = parse_string(q)
    assert len(query.calls) == 1
    return query.calls[0]


class TestBasicCalls:
    def test_row(self):
        c = one("Row(f=10)")
        assert c.name == "Row"
        assert c.args == {"f": 10}

    def test_row_keyed(self):
        c = one('Row(f="ten")')
        assert c.args == {"f": "ten"}

    def test_set(self):
        c = one("Set(3, f=10)")
        assert c.name == "Set"
        assert c.args == {"_col": 3, "f": 10}

    def test_set_with_timestamp(self):
        c = one("Set(3, f=10, 2010-01-02T03:04)")
        assert c.args == {"_col": 3, "f": 10, "_timestamp": "2010-01-02T03:04"}

    def test_set_keyed(self):
        c = one("Set('col-key', f='row-key')")
        assert c.args == {"_col": "col-key", "f": "row-key"}

    def test_clear(self):
        c = one("Clear(3, f=10)")
        assert c.args == {"_col": 3, "f": 10}

    def test_clear_row(self):
        c = one("ClearRow(f=5)")
        assert c.name == "ClearRow"
        assert c.args == {"f": 5}

    def test_nested(self):
        c = one("Count(Intersect(Row(a=1), Row(b=2)))")
        assert c.name == "Count"
        inter = c.children[0]
        assert inter.name == "Intersect"
        assert [ch.name for ch in inter.children] == ["Row", "Row"]
        assert inter.children[0].args == {"a": 1}
        assert inter.children[1].args == {"b": 2}

    def test_multiple_calls(self):
        q = parse_string("Set(1, f=2) Set(3, f=4)\nCount(Row(f=2))")
        assert [c.name for c in q.calls] == ["Set", "Set", "Count"]
        assert q.write_call_n() == 2

    def test_union_empty_and_many(self):
        assert one("Union()").children == []
        c = one("Union(Row(f=1), Row(f=2), Row(f=3))")
        assert len(c.children) == 3

    def test_not(self):
        c = one("Not(Row(f=1))")
        assert c.name == "Not" and c.children[0].args == {"f": 1}

    def test_store(self):
        c = one("Store(Row(f=1), dest=2)")
        assert c.name == "Store"
        assert c.children[0].name == "Row"
        assert c.args == {"dest": 2}


class TestArgs:
    def test_topn(self):
        c = one("TopN(f, n=25)")
        assert c.args == {"_field": "f", "n": 25}

    def test_topn_no_args(self):
        c = one("TopN(f)")
        assert c.args == {"_field": "f"}

    def test_topn_with_child_and_args(self):
        c = one("TopN(f, Row(other=7), n=12)")
        assert c.args == {"_field": "f", "n": 12}
        assert c.children[0].name == "Row"

    def test_rows(self):
        c = one("Rows(f, limit=10, previous=3)")
        assert c.args == {"_field": "f", "limit": 10, "previous": 3}

    def test_list_arg(self):
        c = one("TopN(f, ids=[1,2,3])")
        assert c.args["ids"] == [1, 2, 3]

    def test_null_in_list_is_bare_string(self):
        # The grammar's null/true/false lookahead is &(comma / sp ')'), so a
        # terminal "null]" falls through to the bare-string rule.
        c = one("TopN(f, ids=[1,null])")
        assert c.args["ids"] == [1, "null"]

    def test_empty_list_rejected(self):
        with pytest.raises(ParseError):
            parse_string("TopN(f, ids=[])")

    def test_timestamp_with_trailing_garbage_rejected(self):
        # Strict-PEG commit semantics: the timestamp alternative consumes
        # '2020-01-01T00:00', the leftover ':00Z' breaks the enclosing rule,
        # and like the reference's packrat parser this is a parse error.
        with pytest.raises(ParseError):
            parse_string("Row(f=2020-01-01T00:00:00Z)")

    def test_string_and_bool_and_null(self):
        c = one('GroupBy(Rows(a), limit=7, filter=null, x=true, y=false, s="hi")')
        assert c.args["filter"] is None
        assert c.args["x"] is True
        assert c.args["y"] is False
        assert c.args["s"] == "hi"

    def test_floats_and_negatives(self):
        c = one("Foo(a=1.5, b=-2, c=-0.25, d=.5)")
        assert c.args == {"a": 1.5, "b": -2, "c": -0.25, "d": 0.5}

    def test_bare_string(self):
        c = one("Options(Row(f=1), shards=[0,2])")
        assert c.args["shards"] == [0, 2]

    def test_call_as_value(self):
        c = one("GroupBy(Rows(a), filter=Row(b=1))")
        assert isinstance(c.args["filter"], Call)
        assert c.args["filter"].name == "Row"

    def test_duplicate_arg_rejected(self):
        with pytest.raises(ParseError, match="duplicate"):
            parse_string("Row(f=1, f=2)")


class TestConditions:
    def test_comparison_ops(self):
        for text, op in [
            ("Row(f > 5)", GT),
            ("Row(f < 5)", LT),
            ("Row(f == 5)", EQ),
            ("Row(f != 5)", NEQ),
        ]:
            c = one(text)
            cond = c.args["f"]
            assert isinstance(cond, Condition)
            assert cond.op == op and cond.value == 5

    def test_between_op(self):
        c = one("Row(f >< [4, 8])")
        cond = c.args["f"]
        assert cond.op == BETWEEN and cond.value == [4, 8]

    def test_conditional_form(self):
        c = one("Row(4 < f <= 9)")
        cond = c.args["f"]
        assert cond.op == BETWEEN
        assert cond.value == [5, 9]  # strict < bumps the low bound

    def test_conditional_inclusive(self):
        c = one("Row(-5 <= f <= 5)")
        assert c.args["f"].value == [-5, 5]

    def test_range_generic_fallback(self):
        # Range(f > 5) must fall through the special Range form to the
        # generic-call rule, like the PEG's ordered choice.
        c = one("Range(f > 5)")
        assert c.name == "Range"
        assert c.args["f"] == Condition(GT, 5)

    def test_range_timestamp_form(self):
        c = one("Range(f=1, 2010-01-01T00:00, 2011-01-01T00:00)")
        assert c.args == {
            "f": 1,
            "from": "2010-01-01T00:00",
            "to": "2011-01-01T00:00",
        }

    def test_range_from_to_labels(self):
        c = one("Range(f=1, from=2010-01-01T00:00, to=2011-01-01T00:00)")
        assert c.args["from"] == "2010-01-01T00:00"
        assert c.args["to"] == "2011-01-01T00:00"


class TestAttrs:
    def test_set_row_attrs(self):
        c = one('SetRowAttrs(f, 10, color="blue", rank=5)')
        assert c.args == {"_field": "f", "_row": 10, "color": "blue", "rank": 5}

    def test_set_column_attrs(self):
        c = one('SetColumnAttrs(7, happy=true)')
        assert c.args == {"_col": 7, "happy": True}


class TestErrors:
    def test_garbage(self):
        with pytest.raises(ParseError):
            parse_string("Row(f=1")
        with pytest.raises(ParseError):
            parse_string(")}{")
        with pytest.raises(ParseError):
            parse_string("Row(=1)")

    def test_empty_is_ok(self):
        assert parse_string("").calls == []
        assert parse_string("   \n ").calls == []


class TestStringify:
    def test_roundtrip(self):
        c = one("Count(Intersect(Row(a=1), Row(b=2)))")
        assert one(c.to_string()) == c

    def test_condition_string(self):
        c = one("Row(4 < f <= 9)")
        assert "5 <= f <= 9" in c.to_string()
