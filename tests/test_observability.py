"""Round-8 cluster observability plane: distributed trace assembly,
span-stack hygiene, peer-RPC metrics, metrics federation, the HBM
ledger, and the diagnostics device inventory (ISSUE r8)."""

import json
import urllib.request

import numpy as np
import pytest

from pilosa_tpu.shardwidth import SHARD_WIDTH
from pilosa_tpu.utils.stats import global_stats
from pilosa_tpu.utils.tracing import Tracer, global_tracer
from tests.cluster_harness import TestCluster


def _counter(name_prefix: str) -> float:
    snap = global_stats.snapshot()["counters"]
    return sum(v for k, v in snap.items() if k.startswith(name_prefix))


def _get_json(uri: str, path: str) -> dict:
    with urllib.request.urlopen(uri + path, timeout=10) as resp:
        return json.loads(resp.read())


def _get_text(uri: str, path: str) -> str:
    with urllib.request.urlopen(uri + path, timeout=10) as resp:
        return resp.read().decode()


class TestTracerHygiene:
    def test_finish_pops_abandoned_children(self):
        t = Tracer()
        before = _counter("trace_spans_dropped_total")
        outer = t.start_span("outer")
        t.start_span("abandoned-child")  # exception path: never finished
        outer.finish()
        # The abandoned child must NOT keep re-parenting later spans.
        assert t.active_span() is None
        fresh = t.start_span("fresh")
        assert fresh.trace_id != outer.trace_id
        fresh.finish()
        assert _counter("trace_spans_dropped_total") == before + 1

    def test_depth_cap_forced_pop(self):
        t = Tracer()
        before = _counter("trace_spans_dropped_total")
        root = t.start_span("root")
        for i in range(1, t.MAX_STACK_DEPTH + 5):
            t.start_span(f"s{i}")
        stack = t._stack()
        assert len(stack) <= t.MAX_STACK_DEPTH
        # The live ROOT survives the cap; the oldest abandoned entries
        # ABOVE it were the forced-pop victims.
        assert stack[0] is root
        assert _counter("trace_spans_dropped_total") == before + 5
        # When the root finally finishes, its whole abandoned subtree
        # is truncated and counted.
        root.finish()
        assert t.active_span() is None
        # 5 force-pops + the 63 abandoned children truncated at finish:
        # every span but the root was dropped exactly once.
        assert (
            _counter("trace_spans_dropped_total")
            == before + t.MAX_STACK_DEPTH + 4
        )

    def test_spans_for_indexes_by_trace(self):
        t = Tracer()
        with t.start_span("a") as a:
            with t.start_span("a-child"):
                pass
        with t.start_span("b") as b:
            pass
        assert a.trace_id != b.trace_id
        got = t.spans_for(a.trace_id)
        assert {s["name"] for s in got} == {"a", "a-child"}
        assert all(s["traceID"] == a.trace_id for s in got)
        # Wall-clock start is recorded for cross-node ordering.
        assert all(s["start"] > 0 for s in got)
        assert t.spans_for("nonexistent") == []

    def test_ring_trim_prunes_trace_index(self):
        t = Tracer(capacity=8)
        for i in range(40):
            t.start_span(f"s{i}").finish()
        live = {s.trace_id for s in t._spans}
        assert set(t._by_trace) == live


class TestClusterTraces:
    def _seed(self, c, n_shards=6):
        c.create_index("i")
        c.create_field("i", "f")
        for shard in range(n_shards):
            c.query(0, "i", f"Set({shard * SHARD_WIDTH + 1}, f=0)")
        c.await_shard_convergence("i")

    def test_trace_propagates_across_nodes(self):
        """A fanned-out query leaves spans carrying ONE trace id on both
        the coordinator and the remote node (ISSUE r8 satellite)."""
        with TestCluster(2) as c:
            self._seed(c)
            uri = str(c[0].node.uri)
            req = urllib.request.Request(
                uri + "/index/i/query", data=b"Count(Row(f=0))", method="POST"
            )
            with urllib.request.urlopen(req, timeout=10) as resp:
                out = json.loads(resp.read())
            assert out["results"][0] == 6
            # Spans enter the ring at finish(), and the remote node's
            # http span finishes AFTER its reply bytes reached the
            # coordinator — an in-process client can read the ring a
            # GIL slice before that finalization lands. quiesce() on
            # BOTH nodes is the deterministic barrier (ISSUE r13; this
            # used to be an ad-hoc poll loop on the span ring). It must
            # come BEFORE picking "the newest query span": until the
            # coordinator's handler finalizes, the newest
            # http.handle_post_query in the ring is a _seed write's —
            # whose trace never fanned out.
            assert c[1].server.quiesce(timeout=5.0)
            assert c[0].server.quiesce(timeout=5.0)
            # The serving span of THIS query: newest http query span.
            qspans = [
                s
                for s in global_tracer.recent(400)
                if s["name"] == "http.handle_post_query"
            ]
            assert qspans
            trace_id = qspans[-1]["traceID"]
            spans = global_tracer.spans_for(trace_id)
            nodes = {
                s["tags"].get("node") for s in spans
                if "node" in s["tags"]
            }
            assert {"node0", "node1"} <= nodes, spans
            # The remote leg is linked, not a parallel orphan: node1's
            # http span chains to a coordinator-side mapper span.
            by_id = {s["spanID"]: s for s in spans}
            remote = next(
                s for s in spans if s["tags"].get("node") == "node1"
            )
            parent = by_id.get(remote["parentID"])
            assert parent is not None and parent["name"] == "cluster.mapShards"

    def test_debug_traces_assembles_one_tree(self):
        """/debug/traces/<id> returns one parent-linked tree containing
        spans attributed to >= 2 distinct nodes (acceptance)."""
        with TestCluster(2) as c:
            self._seed(c)
            uri = str(c[0].node.uri)
            req = urllib.request.Request(
                uri + "/index/i/query", data=b"Count(Row(f=0))", method="POST"
            )
            with urllib.request.urlopen(req, timeout=10) as resp:
                json.loads(resp.read())
            qspans = [
                s
                for s in global_tracer.recent(400)
                if s["name"] == "http.handle_post_query"
            ]
            trace_id = qspans[-1]["traceID"]
            tree = _get_json(uri, f"/debug/traces/{trace_id}")
            assert tree["traceID"] == trace_id
            assert tree["spanCount"] >= 3
            assert len(tree["nodes"]) >= 2
            assert tree["scrapeFailures"] == []

            # Every span appears exactly once (the in-process harness
            # shares rings; assembly must dedup by span id).
            seen = []

            def walk(node):
                seen.append(node["spanID"])
                for ch in node["children"]:
                    assert ch["parentID"] == node["spanID"]
                    walk(ch)

            for root in tree["tree"]:
                walk(root)
            assert len(seen) == len(set(seen)) == tree["spanCount"]
            # The remote node's serving span is a DESCENDANT in the tree.
            flat_nodes = set()

            def collect(node):
                flat_nodes.add(node.get("node"))
                for ch in node["children"]:
                    collect(ch)

            for root in tree["tree"]:
                collect(root)
            assert {"node0", "node1"} <= flat_nodes

    def test_internal_traces_serves_local_ring(self):
        with TestCluster(1) as c:
            with global_tracer.start_span("local-op") as sp:
                pass
            out = _get_json(
                str(c[0].node.uri), f"/internal/traces/{sp.trace_id}"
            )
            assert out["node"] == "node0"
            assert any(s["name"] == "local-op" for s in out["spans"])


class TestPeerRpcMetrics:
    def test_latency_series_tagged_per_peer_and_method(self):
        with TestCluster(2) as c:
            c.create_index("i")
            c.create_field("i", "f")
            for shard in range(4):
                c.query(0, "i", f"Set({shard * SHARD_WIDTH + 1}, f=0)")
            c.query(0, "i", "Count(Row(f=0))")
            timings = global_stats.snapshot()["timings"]
            series = [
                k
                for k in timings
                if k.startswith("peer_rpc_seconds")
                and 'method="query_node"' in k
            ]
            assert series, sorted(timings)[:20]
            assert all('peer="' in k for k in series)

    def test_error_classes_counted(self):
        from pilosa_tpu.cluster.client import ClientError, InternalClient

        # retries=0: this asserts PER-ATTEMPT error counting; the
        # idempotent-GET retry (ISSUE r9) would legitimately dial — and
        # count — a second transport error.
        client = InternalClient(timeout=0.5, retries=0)
        before = _counter("peer_rpc_errors_total")
        with pytest.raises(ClientError):
            client.status("http://127.0.0.1:1")  # nothing listens on :1
        snap = global_stats.snapshot()["counters"]
        transport = [
            k
            for k, v in snap.items()
            if k.startswith("peer_rpc_errors_total")
            and 'class="transport"' in k
            and 'peer="127.0.0.1:1"' in k
            and 'method="status"' in k
        ]
        assert transport
        assert _counter("peer_rpc_errors_total") == before + 1

    def test_failed_node_counts_a_retry(self):
        """Scatter-gather re-split onto a replica increments
        peer_rpc_retries_total for the failed peer."""
        with TestCluster(2, replica_n=2) as c:
            c.create_index("i")
            c.create_field("i", "f")
            for shard in range(4):
                c.query(0, "i", f"Set({shard * SHARD_WIDTH + 1}, f=0)")
            c.sync_all()
            before = _counter("peer_rpc_retries_total")
            # Kill node1's listener: remote legs fail, shards re-split
            # onto node0's replicas.
            c[1].server.close()
            # Fresh client: keep-alive state would mask the refusal.
            out = c.query(0, "i", "Count(Row(f=0))")
            assert out["results"][0] == 4
            assert _counter("peer_rpc_retries_total") >= before + 1


class TestFederation:
    def test_metrics_cluster_tags_every_node(self):
        with TestCluster(2) as c:
            text = _get_text(str(c[0].node.uri), "/metrics/cluster")
            assert 'node="node0"' in text
            assert 'node="node1"' in text
            assert 'pilosa_cluster_scrape_up{node="node0"} 1' in text
            assert 'pilosa_cluster_scrape_up{node="node1"} 1' in text
            assert "pilosa_cluster_scrape_seconds" in text
            # Pre-existing labels survive the retag (node label FIRST).
            assert 'pilosa_http_requests_total{node="node0",' in text

    def test_downed_node_is_scrape_failure_not_hang(self):
        with TestCluster(2) as c:
            before = _counter("cluster_scrape_failures_total")
            c[1].server.close()
            text = _get_text(
                str(c[0].node.uri), "/metrics/cluster?timeout=2"
            )
            assert 'pilosa_cluster_scrape_up{node="node1"} 0' in text
            assert 'pilosa_cluster_scrape_up{node="node0"} 1' in text
            assert _counter("cluster_scrape_failures_total") >= before + 1

    def test_debug_cluster_federates_vars(self):
        with TestCluster(2) as c:
            out = _get_json(str(c[0].node.uri), "/debug/cluster")
            assert set(out["nodes"]) == {"node0", "node1"}
            for ent in out["nodes"].values():
                assert ent["up"] is True
                assert "counters" in ent["vars"]
                # The LOCAL leg serves the same shape as remote
                # /debug/vars — version/uptime must not be missing for
                # exactly one node.
                assert "version" in ent["vars"]
                assert "uptimeSeconds" in ent["vars"]
                assert ent["scrapeMs"] >= 0

    def test_retag_renames_preexisting_node_label(self):
        """A member's own node=-tagged series (scrape-failure counters)
        must federate as exported_node=, never as an illegal duplicate
        node label."""
        with TestCluster(2) as c:
            # Seed a node=-tagged series on node0's registry.
            global_stats.with_tags("node:deadbeef").count(
                "cluster_scrape_failures_total"
            )
            text = _get_text(str(c[0].node.uri), "/metrics/cluster")
            assert 'exported_node="deadbeef"' in text
            for line in text.splitlines():
                assert line.count('node="') - line.count(
                    'exported_node="'
                ) <= 1, line

    def test_single_node_is_one_member_cluster(self):
        with TestCluster(1) as c:
            text = _get_text(str(c[0].node.uri), "/metrics/cluster")
            assert 'node="node0"' in text


class TestHbmLedger:
    @staticmethod
    def _blocks_cls():
        tpu = pytest.importorskip(
            "pilosa_tpu.exec.tpu",
            reason="device backend needs jax.shard_map",
            exc_type=ImportError,
        )
        return tpu._StackedBlocks

    def _field(self, h, name, cols):
        idx = h.index("b") or h.create_index("b")
        f = idx.create_field(name)
        f.import_bits(np.zeros(len(cols), dtype=np.uint64),
                      np.asarray(cols, dtype=np.uint64))
        return f

    def test_tier_bytes_sum_to_resident(self):
        from pilosa_tpu.core.holder import Holder

        _StackedBlocks = self._blocks_cls()
        h = Holder(None).open()
        rng = np.random.default_rng(5)
        # Sparse bits -> array containers.
        f = self._field(h, "f", rng.integers(0, SHARD_WIDTH, 500))
        # Contiguous range, optimized -> run container(s).
        g = self._field(h, "g", np.arange(7000))
        for frag_field in (g,):
            frag = frag_field.view("standard").fragment(0)
            frag.storage.optimize()
        blocks = _StackedBlocks()
        blocks.get("b", f, (0,))
        blocks.get("b", g, (0,))
        tiers = blocks.tier_bytes()
        assert sum(tiers.values()) == blocks.resident_bytes() > 0
        assert tiers["array"] > 0
        assert tiers["run"] > 0
        h.close()

    def test_ledger_coldness_order_and_access_churn(self):
        from pilosa_tpu.core.holder import Holder

        _StackedBlocks = self._blocks_cls()
        h = Holder(None).open()
        rng = np.random.default_rng(6)
        f = self._field(h, "f", rng.integers(0, SHARD_WIDTH, 300))
        g = self._field(h, "g", rng.integers(0, SHARD_WIDTH, 300))
        blocks = _StackedBlocks()
        blocks.get("b", f, (0,))
        blocks.get("b", g, (0,))
        led = blocks.ledger()
        assert [e["field"] for e in led] == ["f", "g"]  # f is coldest
        # Access churn: touching f reorders the eviction-candidate list.
        blocks.get("b", f, (0,))
        led = blocks.ledger()
        assert [e["field"] for e in led] == ["g", "f"]
        ent = next(e for e in led if e["field"] == "f")
        assert ent["accessCount"] == 2
        assert ent["uploads"] == 1
        assert ent["uploadEpoch"] >= 1
        h.close()

    def test_rebuild_bumps_epoch_eviction_drops_entry(self):
        from pilosa_tpu.core.holder import Holder

        _StackedBlocks = self._blocks_cls()
        h = Holder(None).open()
        rng = np.random.default_rng(7)
        f = self._field(h, "f", rng.integers(0, SHARD_WIDTH, 300))
        blocks = _StackedBlocks()
        blocks.get("b", f, (0,))
        epoch0 = blocks.ledger()[0]["uploadEpoch"]
        # A write starts a new epoch; the refreshed entry keeps its
        # access history but records the new upload.
        f.import_bits(np.array([1], dtype=np.uint64),
                      np.array([99], dtype=np.uint64))
        blocks.get("b", f, (0,))
        ent = blocks.ledger()[0]
        assert ent["uploadEpoch"] > epoch0
        assert ent["uploads"] == 2
        blocks.clear()
        assert blocks.ledger() == []
        assert blocks.tier_bytes() == {"dense": 0, "array": 0, "run": 0}
        h.close()

    def test_debug_hbm_endpoint(self):
        """/debug/hbm serves the ledger; tier totals sum to the resident
        gauge (acceptance). Stub block store: the HTTP wiring under test
        is backend-agnostic."""
        from types import SimpleNamespace

        with TestCluster(1) as c:

            class FakeBlocks:
                evictions = 2

                def resident_bytes(self):
                    return 96

                def tier_bytes(self):
                    return {"dense": 32, "array": 48, "run": 16}

                def ledger(self):
                    return [
                        {"index": "b", "field": "f", "view": "standard",
                         "bytes": 96,
                         "tierBytes": {"dense": 32, "array": 48, "run": 16},
                         "rows": 8, "uploadEpoch": 1, "uploads": 1,
                         "accessCount": 3, "lastAccess": 0.0,
                         "idleSeconds": 1.0}
                    ]

            c[0].executor.backend = SimpleNamespace(blocks=FakeBlocks())
            out = _get_json(str(c[0].node.uri), "/debug/hbm")
            assert out["residentBytes"] == 96
            assert sum(out["tierBytes"].values()) == out["residentBytes"]
            assert out["entries"][0]["field"] == "f"
            # And the tier gauges land on /metrics at scrape time.
            text = _get_text(str(c[0].node.uri), "/metrics")
            assert 'pilosa_hbm_resident_bytes{tier="array"} 48' in text
            assert "pilosa_hbm_resident_bytes 96" in text

    def test_debug_hbm_without_backend(self):
        with TestCluster(1) as c:
            out = _get_json(str(c[0].node.uri), "/debug/hbm")
            assert out == {"residentBytes": 0, "tierBytes": {},
                           "evictions": 0, "entries": [],
                           "totalEntries": 0}


class TestDiagnosticsDevices:
    def test_snapshot_includes_jax_inventory(self):
        from pilosa_tpu.utils.monitor import diagnostics_snapshot

        snap = diagnostics_snapshot()
        jx = snap["jax"]
        assert "error" not in jx, jx
        assert jx["device_count"] >= 1
        assert jx["platform"]
        d0 = jx["devices"][0]
        assert {"id", "platform", "kind"} <= set(d0)

    def test_served_over_http(self):
        with TestCluster(1) as c:
            out = _get_json(str(c[0].node.uri), "/debug/diagnostics")
            assert out["jax"]["device_count"] >= 1


class TestResizeGossipCounters:
    def test_resize_job_counters_and_progress(self):
        before_started = _counter("resize_jobs_started_total")
        before_done = _counter("resize_jobs_completed_total")
        with TestCluster(2) as c:
            c.create_index("i")
            c.create_field("i", "f")
            for shard in range(4):
                c.query(0, "i", f"Set({shard * SHARD_WIDTH + 1}, f=0)")
            c.add_node_via_resize()
            assert _counter("resize_jobs_started_total") == before_started + 1
            assert _counter("resize_jobs_completed_total") == before_done + 1
            gauges = global_stats.snapshot()["gauges"]
            assert gauges.get("resize_pending_nodes") == 0
            assert "resize_migration_sources_total" in gauges

    def test_state_transition_counter(self):
        with TestCluster(1) as c:
            before = _counter("cluster_state_transitions_total")
            c[0].cluster.set_state("RESIZING")
            c[0].cluster.set_state("NORMAL")
            c[0].cluster.set_state("NORMAL")  # no-op: not a transition
            assert _counter("cluster_state_transitions_total") == before + 2
