"""Cluster lifecycle robustness (ISSUE r9): failover-safe resize
(follower leases, coordinator heartbeats, completion-report retry,
promoted-coordinator adoption + job epochs), verified & throttled shard
migration, persisted topology, anti-entropy observability, and the
union-repair limitation pin.

Chaos coverage: an in-process coordinator-death-mid-resize simulation
(tier-1-safe) plus a real-subprocess SIGKILL-the-coordinator-mid-resize
drill (skips cleanly where subprocess networking is restricted).
"""

from __future__ import annotations

import json
import os
import pathlib
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
import zlib

import pytest

from pilosa_tpu.cluster import broadcast as bc
from pilosa_tpu.cluster.broadcast import Message
from pilosa_tpu.cluster.client import ClientError
from pilosa_tpu.cluster.resize import ResizeError
from pilosa_tpu.cluster.topology import (
    Node,
    STATE_NORMAL,
    STATE_RESIZING,
    Topology,
    URI,
    load_topology,
    save_topology,
)
from pilosa_tpu.shardwidth import SHARD_WIDTH
from pilosa_tpu.utils.stats import global_stats
from tests.cluster_harness import TestCluster

REPO = str(pathlib.Path(__file__).resolve().parent.parent)
VIEW_STANDARD = "standard"


def _counter(name: str) -> float:
    snap = global_stats.snapshot()["counters"]
    return sum(v for k, v in snap.items() if k.startswith(name))


def _frag(cn, index, field, shard):
    idx = cn.holder.index(index)
    f = idx.field(field) if idx else None
    v = f.view(VIEW_STANDARD) if f else None
    return v.fragment(shard) if v else None


def _await(cond, timeout=10.0, every=0.02, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(every)
    raise TimeoutError(f"{what} never held within {timeout}s")


# ---------------------------------------------------------------------------
# Follower lease / coordinator heartbeats
# ---------------------------------------------------------------------------


class TestFollowerLease:
    def test_lease_expiry_rolls_back_to_normal(self):
        """A follower frozen in RESIZING with no coordinator heartbeat
        rolls itself back to NORMAL on the old topology within the lease
        window — the coordinator-crash escape hatch."""
        with TestCluster(2) as c:
            rz = c[1].cluster.resizer
            rz.lease_timeout = 0.3
            exp0 = _counter("resize_lease_expirations_total")
            old_nodes = list(c[1].cluster.topology.nodes)
            c[1].cluster.apply_message(
                Message.make(bc.MSG_CLUSTER_STATUS, state=STATE_RESIZING)
            )
            assert c[1].cluster.state() == STATE_RESIZING
            _await(
                lambda: c[1].cluster.state() == STATE_NORMAL,
                timeout=3, what="lease rollback",
            )
            # Old topology intact: the lease reverts STATE only.
            assert c[1].cluster.topology.nodes == old_nodes
            assert _counter("resize_lease_expirations_total") - exp0 == 1

    def test_heartbeats_keep_the_lease_alive(self):
        with TestCluster(2) as c:
            rz = c[1].cluster.resizer
            rz.lease_timeout = 0.3
            c[1].cluster.apply_message(
                Message.make(bc.MSG_CLUSTER_STATUS, state=STATE_RESIZING)
            )
            for _ in range(4):
                time.sleep(0.15)
                c[1].cluster.apply_message(
                    Message.make(bc.MSG_RESIZE_HEARTBEAT, job=1, epoch=0)
                )
                assert c[1].cluster.state() == STATE_RESIZING
            # Heartbeats stop: the lease fires.
            _await(
                lambda: c[1].cluster.state() == STATE_NORMAL,
                timeout=3, what="lease rollback after heartbeats stopped",
            )

    def test_terminal_status_cancels_lease(self):
        with TestCluster(2) as c:
            rz = c[1].cluster.resizer
            rz.lease_timeout = 0.3
            exp0 = _counter("resize_lease_expirations_total")
            c[1].cluster.apply_message(
                Message.make(bc.MSG_CLUSTER_STATUS, state=STATE_RESIZING)
            )
            c[1].cluster.apply_message(
                Message.make(bc.MSG_CLUSTER_STATUS, state=STATE_NORMAL)
            )
            time.sleep(0.6)
            assert c[1].cluster.state() == STATE_NORMAL
            assert _counter("resize_lease_expirations_total") == exp0

    def test_coordinator_own_job_arms_no_lease(self):
        """The coordinator's job is terminated by its job_timeout, never
        by a self-lease racing its own heartbeats."""
        with TestCluster(2) as c:
            rz = c[0].cluster.resizer
            rz.lease_timeout = 0.2
            with rz._lock:
                rz._new_nodes = list(c[0].cluster.topology.nodes)
            rz.renew_lease()
            assert rz._lease is None
            with rz._lock:
                rz._new_nodes = None

    def test_coordinator_heartbeats_reach_followers(self):
        """A live job's heartbeat loop actually renews follower leases
        over the real broadcast surface."""
        with TestCluster(2) as c:
            for cn in c.nodes:
                cn.cluster.resizer.lease_timeout = 0.6
            rz0 = c[0].cluster.resizer
            # Arm an artificial live job on the coordinator and freeze
            # the follower; the heartbeat loop must keep node1 frozen
            # well past its lease window.
            with rz0._lock:
                rz0._active_job = 99
                rz0._new_nodes = list(c[0].cluster.topology.nodes)
                rz0._notify_nodes = list(c[0].cluster.topology.nodes)
            c[1].cluster.apply_message(
                Message.make(bc.MSG_CLUSTER_STATUS, state=STATE_RESIZING)
            )
            rz0._start_heartbeats(99)
            try:
                time.sleep(1.5)  # > 2 lease windows
                assert c[1].cluster.state() == STATE_RESIZING
            finally:
                with rz0._lock:
                    rz0._active_job = None
                    rz0._new_nodes = None
                    rz0._notify_nodes = []
                rz0._stop_heartbeats()
                c[1].cluster.resizer.cancel_lease()
                c[1].cluster.set_state(STATE_NORMAL)


# ---------------------------------------------------------------------------
# Completion-report retry + coordinator re-resolution
# ---------------------------------------------------------------------------


class TestCompletionRetry:
    def test_report_rides_out_coordinator_failover(self):
        """The completion report retries against the CURRENTLY resolved
        coordinator: a report addressed to a dead coordinator lands on
        the promoted successor once the coordinator flag moves."""
        with TestCluster(3) as c:
            rz2 = c[2].cluster.resizer
            rz2.lease_timeout = 10.0
            c[2].cluster.set_state(STATE_RESIZING)
            # Ghost coordinator: instruction came from a node that died.
            ghost = Node("ghost", URI(host="127.0.0.1", port=1), True)
            instruction = Message.make(
                bc.MSG_RESIZE_INSTRUCTION, job=3, epoch=0,
                coordinator=ghost.to_json(), sources=[],
            )
            # node2's local view still flags the ghost as coordinator.
            for n in c[2].cluster.topology.nodes:
                n.is_coordinator = False
            got: list = []
            orig = c[1].cluster.resizer.mark_complete
            c[1].cluster.resizer.mark_complete = lambda m: got.append(m)
            retries0 = _counter("resize_complete_retries_total")
            done = Message.make(
                bc.MSG_RESIZE_COMPLETE, job=3, epoch=0, node="node2"
            )
            t = threading.Thread(
                target=rz2._report_complete, args=(done, instruction),
                daemon=True,
            )
            t.start()
            time.sleep(0.4)  # a few failed attempts against the ghost
            # Failover: node1 becomes the flagged coordinator.
            for n in c[2].cluster.topology.nodes:
                n.is_coordinator = n.id == "node1"
            t.join(timeout=10)
            assert not t.is_alive()
            c[1].cluster.resizer.mark_complete = orig
            c[2].cluster.set_state(STATE_NORMAL)
            assert [m.get("node") for m in got] == ["node2"]
            assert _counter("resize_complete_retries_total") > retries0

    def test_report_gives_up_when_cluster_left_resizing(self):
        """An abort (or lease rollback) mid-retry ends the loop: recovery
        belongs to the rollback, not to a report nobody is waiting on."""
        with TestCluster(2) as c:
            rz1 = c[1].cluster.resizer
            rz1.lease_timeout = 30.0
            ghost = Node("ghost", URI(host="127.0.0.1", port=1), True)
            instruction = Message.make(
                bc.MSG_RESIZE_INSTRUCTION, job=4, epoch=0,
                coordinator=ghost.to_json(), sources=[],
            )
            for n in c[1].cluster.topology.nodes:
                n.is_coordinator = False
            c[1].cluster.set_state(STATE_RESIZING)
            done = Message.make(
                bc.MSG_RESIZE_COMPLETE, job=4, epoch=0, node="node1"
            )
            t = threading.Thread(
                target=rz1._report_complete, args=(done, instruction),
                daemon=True,
            )
            t.start()
            time.sleep(0.3)
            c[1].cluster.set_state(STATE_NORMAL)  # the rollback
            t.join(timeout=10)
            assert not t.is_alive()
            for n in c[1].cluster.topology.nodes:
                n.is_coordinator = n.id == "node0"


# ---------------------------------------------------------------------------
# Promotion adopts (and aborts) the orphaned job; epochs reject staleness
# ---------------------------------------------------------------------------


class TestPromotionAdoption:
    def test_promoted_coordinator_aborts_orphaned_job(self):
        with TestCluster(2) as c:
            for cn in c.nodes:
                cn.cluster.set_state(STATE_RESIZING)
            rz1 = c[1].cluster.resizer
            with rz1._lock:
                rz1._observed_epoch = 5
                rz1._observed_job = 7
            adopted0 = _counter("resize_jobs_adopted_total")
            # The failover: node1 learns it is now the coordinator.
            c[1].cluster.apply_message(
                Message.make(bc.MSG_SET_COORDINATOR, id="node1")
            )
            assert c[1].cluster.state() == STATE_NORMAL
            _await(
                lambda: c[0].cluster.state() == STATE_NORMAL,
                timeout=5, what="abort broadcast unfreezing node0",
            )
            assert _counter("resize_jobs_adopted_total") - adopted0 == 1
            # Epoch bumped past the dead job's: its COMPLETEs are stale.
            assert rz1._epoch == 6

    def test_stale_epoch_complete_rejected(self):
        """A COMPLETE carrying the dead coordinator's epoch must not
        satisfy the promoted coordinator's same-numbered job."""
        with TestCluster(2) as c:
            rz = c[0].cluster.resizer
            with rz._lock:
                rz._epoch = 2
                rz._active_job = 1
                rz._pending_nodes = {"node0", "node1"}
                rz._new_nodes = list(c[0].cluster.topology.nodes)
                rz._notify_nodes = []
            rz.mark_complete(
                Message.make(bc.MSG_RESIZE_COMPLETE, job=1, epoch=1, node="node0")
            )
            assert rz._pending_nodes == {"node0", "node1"}  # rejected
            rz.mark_complete(
                Message.make(bc.MSG_RESIZE_COMPLETE, job=1, epoch=2, node="node0")
            )
            assert rz._pending_nodes == {"node1"}  # matching epoch lands
            with rz._lock:
                rz._pending_nodes = set()
                rz._new_nodes = None
                rz._active_job = None
                rz._epoch = 0

    def test_observe_follower_aborts_from_probe_status(self):
        """A coordinator that never saw the job (promoted after the
        freeze reached the followers) adopts it from a follower's
        /status and aborts it."""
        with TestCluster(2) as c:
            c[1].cluster.set_state(STATE_RESIZING)
            with c[1].cluster.resizer._lock:
                c[1].cluster.resizer._observed_epoch = 3
                c[1].cluster.resizer._observed_job = 9
            # node1's /status carries the orphan report...
            st = c[1].api.status()
            assert st["resize"] == {"job": 9, "epoch": 3}
            # ...and the coordinator's probe merge adopts + aborts it.
            adopted0 = _counter("resize_jobs_adopted_total")
            c[0].cluster.resizer.observe_follower(st["resize"])
            _await(
                lambda: c[1].cluster.state() == STATE_NORMAL,
                timeout=5, what="observe_follower abort",
            )
            assert _counter("resize_jobs_adopted_total") - adopted0 == 1
            assert c[0].cluster.resizer._epoch == 4

    def test_follower_status_absent_when_normal(self):
        with TestCluster(2) as c:
            assert "resize" not in c[0].api.status()


# ---------------------------------------------------------------------------
# In-process chaos: coordinator dies mid-resize
# ---------------------------------------------------------------------------


@pytest.mark.chaos
class TestCoordinatorDeathMidResize:
    def test_survivors_exit_resizing_with_no_lost_writes(self):
        """Tier-1-safe coordinator-death simulation: the coordinator
        freezes the cluster and delivers instructions, then dies (timer
        and heartbeats die with it, its server stops answering). Every
        survivor must exit RESIZING within the lease window via its own
        rollback, writes must stop answering 503, and every acknowledged
        pre-resize write must survive."""
        with TestCluster(3, replica_n=2) as c:
            c.create_index("i")
            c.create_field("i", "f")
            cols = list(range(0, 6 * SHARD_WIDTH, SHARD_WIDTH // 2))
            c[0].api.import_bits("i", "f", [1] * len(cols), cols)
            want = c.query(1, "i", "Count(Row(f=1))")["results"][0]
            assert want == len(cols)
            for cn in c.nodes:
                cn.cluster.resizer.lease_timeout = 0.6
            rz0 = c[0].cluster.resizer
            # The coordinator freezes everyone, then dies before any
            # instruction goes out: stop its announce mid-job by making
            # instruction delivery hang forever is overkill — drop the
            # instructions, then kill the coordinator's control plane.
            orig_send = c[0].cluster.broadcaster.send_to

            def drop_instructions(node, msg):
                if msg.get("type") == bc.MSG_RESIZE_INSTRUCTION:
                    return  # "delivered", never followed
                return orig_send(node, msg)

            c[0].cluster.broadcaster.send_to = drop_instructions
            cn_new = c.spawn_node()
            rz0.job_timeout = 600  # its timer "dies" with it anyway
            rz0.add_node(Node(cn_new.node.id, cn_new.node.uri, False))
            assert c[0].cluster.state() == STATE_RESIZING
            assert c[1].cluster.state() == STATE_RESIZING
            # -- the coordinator dies -------------------------------------
            rz0._stop_heartbeats()
            if rz0._timer is not None:
                rz0._timer.cancel()
            c[0].server.close()
            # -- survivors roll back on their leases ----------------------
            _await(
                lambda: c[1].cluster.state() == STATE_NORMAL
                and c[2].cluster.state() == STATE_NORMAL,
                timeout=5, what="survivor lease rollback",
            )
            # What the survivors' failure detectors would do next (no
            # detector runs in the harness): confirm the dead
            # coordinator DOWN so routing skips it.
            for cn in (c[1], c[2]):
                dead = cn.cluster.topology.node_by_id("node0")
                dead.state = "DOWN"
            # Writes are accepted again (no 503) on a survivor whose
            # replicas are alive, and no acknowledged write was lost.
            c[1].api.import_bits("i", "f", [2], [3])
            assert c.query(1, "i", "Count(Row(f=1))")["results"][0] == want


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _http(port, method, path, body=None, timeout=5):
    data = body if isinstance(body, (bytes, type(None))) else json.dumps(body).encode()
    r = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=data, method=method
    )
    with urllib.request.urlopen(r, timeout=timeout) as resp:
        raw = resp.read()
    return json.loads(raw) if raw else {}


@pytest.mark.chaos
class TestCoordinatorSigkillSubprocess:
    """The real thing: SIGKILL the coordinator PROCESS mid-resize and
    assert the surviving nodes exit RESIZING within the lease window
    with no lost acknowledged writes (ISSUE r9 chaos acceptance). Skips
    cleanly where subprocess networking is restricted."""

    def _spawn(self, port, data_dir, hosts=None, join=None, extra=None):
        env = dict(
            os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu",
            PILOSA_TPU_RESIZE_LEASE="4",
        )
        env.pop("PILOSA_TPU_CLUSTER_HOSTS", None)
        env.pop("PILOSA_TPU_CLUSTER_REPLICAS", None)
        if hosts:
            env["PILOSA_TPU_CLUSTER_HOSTS"] = hosts
            env["PILOSA_TPU_CLUSTER_REPLICAS"] = "2"
        env.update(extra or {})
        cmd = [sys.executable, "-m", "pilosa_tpu.cli", "server",
               "-d", data_dir, "-b", f"127.0.0.1:{port}",
               "--executor", "cpu"]
        if join:
            cmd += ["--join", join]
        return subprocess.Popen(
            cmd, env=env, cwd=REPO,
            stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT,
        )

    def _ready(self, proc, port, timeout=25) -> bool:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if proc.poll() is not None:
                return False
            try:
                _http(port, "GET", "/status", timeout=2)
                return True
            except (urllib.error.URLError, OSError):
                time.sleep(0.2)
        return False

    def test_sigkill_coordinator_mid_resize(self, tmp_path):
        pa, pb, pc = _free_port(), _free_port(), _free_port()
        hosts = f"127.0.0.1:{pa},127.0.0.1:{pb}"
        procs = []
        try:
            a = self._spawn(pa, str(tmp_path / "a"), hosts=hosts)
            b = self._spawn(pb, str(tmp_path / "b"), hosts=hosts)
            procs += [a, b]
            if not (self._ready(a, pa) and self._ready(b, pb)):
                pytest.skip("subprocess servers unavailable in this environment")
            # Acknowledged pre-resize writes on the 2-node cluster.
            _http(pa, "POST", "/index/i", {})
            _http(pa, "POST", "/index/i/field/f", {})
            cols = list(range(0, 4 * SHARD_WIDTH, SHARD_WIDTH // 4))
            _http(pa, "POST", "/index/i/field/f/import",
                  {"rowIDs": [1] * len(cols), "columnIDs": cols}, timeout=15)
            want = _http(pa, "POST", "/index/i/query",
                         b"Count(Row(f=1))")["results"][0]
            assert want == len(cols)
            # A joiner with a migration bandwidth crawl: the resize job
            # stays in flight long enough to kill the coordinator inside
            # it deterministically.
            coord_port = min((pa, pb))  # lowest node id coordinates
            other = pb if coord_port == pa else pa
            coord = a if coord_port == pa else b
            surv = b if coord_port == pa else a
            c = self._spawn(
                pc, str(tmp_path / "c"),
                join=f"http://127.0.0.1:{coord_port}",
                extra={"PILOSA_TPU_MIGRATION_BANDWIDTH": "500"},
            )
            procs.append(c)
            if not self._ready(c, pc):
                pytest.skip("joiner subprocess unavailable")
            # Wait for the join-triggered resize to freeze the cluster...
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                try:
                    if _http(other, "GET", "/status")["state"] == "RESIZING":
                        break
                except (urllib.error.URLError, OSError):
                    pass
                time.sleep(0.05)
            else:
                pytest.skip("resize never started (join lost?)")
            # ...and SIGKILL the coordinator mid-job.
            coord.send_signal(signal.SIGKILL)
            coord.wait(timeout=10)
            # The survivor exits RESIZING within the lease window
            # (rollback or adopted abort), well under the old forever.
            deadline = time.monotonic() + 20
            while time.monotonic() < deadline:
                try:
                    if _http(other, "GET", "/status")["state"] != "RESIZING":
                        break
                except (urllib.error.URLError, OSError):
                    pass
                time.sleep(0.2)
            st = _http(other, "GET", "/status")
            assert st["state"] != "RESIZING", st
            # Nothing acknowledged is lost (reads re-split off the dead
            # replica immediately)...
            got = _http(other, "POST", "/index/i/query",
                        b"Count(Row(f=1))")["results"][0]
            assert got == want
            # ...and writes stop answering 503. The survivor's failure
            # detector needs a few probe rounds to confirm the killed
            # peer DOWN before write routing skips it, so poll for
            # eventual acceptance instead of asserting the first try.
            deadline = time.monotonic() + 20
            last = None
            while time.monotonic() < deadline:
                try:
                    _http(other, "POST", "/index/i/field/f/import",
                          {"rowIDs": [2], "columnIDs": [5]}, timeout=15)
                    last = None
                    break
                except urllib.error.HTTPError as e:
                    last = e
                    time.sleep(0.5)
            assert last is None, f"writes never accepted again: {last}"
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
                    try:
                        p.wait(timeout=10)
                    except subprocess.TimeoutExpired:
                        pass


# ---------------------------------------------------------------------------
# Verified migration: checksums, 404-vs-transport, failover, throttle
# ---------------------------------------------------------------------------


class TestVerifiedMigration:
    def test_fragment_data_carries_checksum_header(self):
        with TestCluster(1) as c:
            c.create_index("i")
            c.create_field("i", "f")
            c[0].api.import_bits("i", "f", [1], [10])
            url = (
                f"{c[0].cluster.local_node.uri}"
                "/internal/fragment/data?index=i&field=f&view=standard&shard=0"
            )
            with urllib.request.urlopen(url, timeout=5) as resp:
                data = resp.read()
                hdr = resp.headers.get("X-Pilosa-Content-Checksum")
            assert hdr == f"crc32:{zlib.crc32(data) & 0xFFFFFFFF:08x}"

    def test_corrupt_transfer_detected_never_ingested(self):
        """A payload whose bytes were damaged in flight raises
        code=checksum-mismatch from retrieve_shard BEFORE any caller can
        import it."""
        with TestCluster(2) as c:
            c.create_index("i")
            c.create_field("i", "f")
            c[0].api.import_bits("i", "f", [1], [10])
            client = c[1].cluster.client
            orig = client.__class__._do_once

            def corrupting(self_, method, uri, path, **kw):
                out = orig(self_, method, uri, path, **kw)
                if kw.get("want_headers") and "/fragment/data" in path:
                    data, headers = out
                    return bytes([data[0] ^ 0x01]) + data[1:], headers
                return out

            client._do_once = corrupting.__get__(client)
            try:
                with pytest.raises(ClientError) as e:
                    client.retrieve_shard(
                        c[0].cluster.local_node.uri, "i", "f", "standard", 0
                    )
                assert e.value.code == "checksum-mismatch"
            finally:
                del client._do_once

    def test_fetch_404_is_absence_not_failure(self):
        """`except ClientError: continue` used to conflate 404 with
        transport failure; now 404 everywhere returns None with zero
        fetch-error counts."""
        with TestCluster(2) as c:
            rz = c[1].cluster.resizer

            class Stub:
                def retrieve_shard(self, uri, *a):
                    raise ClientError("nope", status=404, code="not-found")

            rz.cluster = type(rz.cluster)(
                c[1].cluster.local_node, c[1].cluster.topology,
                use_broadcast=False,
            )
            rz.cluster.client = Stub()
            errs0 = _counter("resize_fetch_errors_total")
            assert rz._fetch_fragment(["u1", "u2"], "i", "f", "standard", 0) is None
            assert _counter("resize_fetch_errors_total") == errs0
            rz.cluster = c[1].cluster

    def test_transport_failure_retries_then_fails_over(self):
        """Transient failures burn bounded per-source retries, then the
        fetch fails over to the next surviving old owner — counted,
        never silently skipped."""
        with TestCluster(2) as c:
            rz = c[1].cluster.resizer
            rz.fetch_retries = 1
            calls: list = []

            class Stub:
                def retrieve_shard(self, uri, *a):
                    calls.append(uri)
                    if uri == "u1":
                        raise ClientError("reset", transport=True)
                    return b"payload"

            real_cluster = rz.cluster
            rz.cluster = type(real_cluster)(
                c[1].cluster.local_node, c[1].cluster.topology,
                use_broadcast=False,
            )
            rz.cluster.client = Stub()
            errs0 = _counter('resize_fetch_errors_total{kind="transport"}')
            try:
                out = rz._fetch_fragment(["u1", "u2"], "i", "f", "standard", 0)
            finally:
                rz.cluster = real_cluster
            assert out == b"payload"
            assert calls == ["u1", "u1", "u2"]  # retry, then failover
            assert (
                _counter('resize_fetch_errors_total{kind="transport"}') - errs0
                == 2
            )

    def test_all_sources_dead_raises_counted(self):
        with TestCluster(2) as c:
            rz = c[1].cluster.resizer
            rz.fetch_retries = 0

            class Stub:
                def retrieve_shard(self, uri, *a):
                    raise ClientError("boom", status=500)

            real_cluster = rz.cluster
            rz.cluster = type(real_cluster)(
                c[1].cluster.local_node, c[1].cluster.topology,
                use_broadcast=False,
            )
            rz.cluster.client = Stub()
            errs0 = _counter('resize_fetch_errors_total{kind="http"}')
            try:
                with pytest.raises(ResizeError):
                    rz._fetch_fragment(["u1", "u2"], "i", "f", "standard", 0)
            finally:
                rz.cluster = real_cluster
            assert _counter('resize_fetch_errors_total{kind="http"}') - errs0 == 2

    def test_bandwidth_throttle_paces_transfers(self):
        with TestCluster(1) as c:
            rz = c[0].cluster.resizer
            rz.bandwidth_limit = 100_000  # bytes/s
            t0 = time.monotonic()
            rz._throttle(10_000)
            rz._throttle(10_000)
            # 20 KB at 100 KB/s: at least ~0.2 s of pacing.
            assert time.monotonic() - t0 >= 0.15

    def test_instructions_carry_alternate_sources(self):
        """With replica_n=2 every migrating fragment names a second
        surviving owner the fetcher can fail over to."""
        with TestCluster(3, replica_n=2) as c:
            c.create_index("i")
            c.create_field("i", "f")
            cols = list(range(0, 6 * SHARD_WIDTH, SHARD_WIDTH // 2))
            c[0].api.import_bits("i", "f", [1] * len(cols), cols)
            rz = c[0].cluster.resizer
            old = c[0].cluster.topology
            new = Topology(
                nodes=list(old.nodes)
                + [Node("node9", URI(host="127.0.0.1", port=9), False)],
                replica_n=2, partition_n=old.partition_n, hasher=old.hasher,
            )
            instr = rz._build_instructions(old, new, None)
            sources = [s for lst in instr.values() for s in lst]
            assert sources, "expected at least one migrating fragment"
            assert any(s["alts"] for s in sources)
            for s in sources:
                assert s["from"] not in s["alts"]

    def test_abort_cancels_inflight_migration_workers(self):
        """A lease expiry or abort stops in-flight fetch workers: they
        must not keep migrating (or re-arm the cleanup flag) for a job
        already declared dead."""
        with TestCluster(2) as c:
            c.create_index("i")
            c.create_field("i", "f")
            rz = c[1].cluster.resizer
            rz.fetch_concurrency = 1
            started = threading.Event()
            release = threading.Event()

            class SlowStub:
                def field_state(self, uri, index, field):
                    started.set()
                    release.wait(5)
                    return {"views": ["standard"]}

                def retrieve_shard(self, *a):
                    raise ClientError("absent", status=404)

            real_cluster = rz.cluster
            stub = type(real_cluster)(
                c[1].cluster.local_node, c[1].cluster.topology,
                holder=c[1].holder, use_broadcast=False,
            )
            stub.client = SlowStub()
            rz.cluster = stub
            stub.resizer = rz
            sources = [
                {"index": "i", "field": "f", "shard": s, "from": "u1"}
                for s in range(3)
            ]
            msg = Message.make(
                bc.MSG_RESIZE_INSTRUCTION, job=1, epoch=1, sources=sources,
            )
            result: list = []

            def run():
                try:
                    rz._follow_instruction_inner(msg)
                    result.append(None)
                except ResizeError as e:
                    result.append(e)

            t = threading.Thread(target=run, daemon=True)
            t.start()
            try:
                assert started.wait(5)  # first source mid-fetch
                rz.abort(local=True)  # the job dies under the workers
            finally:
                release.set()
                t.join(timeout=10)
                rz.cluster = real_cluster
            assert not t.is_alive()
            assert isinstance(result[0], ResizeError)  # reported, not silent
            assert rz._needs_clean is False  # never re-armed by workers

    def test_resize_still_converges_with_concurrency(self):
        """End-to-end: the concurrent, verified fetch plane moves a real
        resize exactly like the old sequential loop did."""
        with TestCluster(2) as c:
            c.create_index("i")
            c.create_field("i", "f")
            cols = list(range(0, 8 * SHARD_WIDTH, SHARD_WIDTH // 2))
            c[0].api.import_bits("i", "f", [1] * len(cols), cols)
            for cn in c.nodes:
                cn.cluster.resizer.fetch_concurrency = 4
            want = c.query(0, "i", "Count(Row(f=1))")["results"][0]
            cn = c.add_node_via_resize()
            assert (
                cn.api.query("i", "Count(Row(f=1))")["results"][0] == want
            )


# ---------------------------------------------------------------------------
# Resize edge cases that existed untested (ISSUE r9 satellite)
# ---------------------------------------------------------------------------


class TestResizeEdgeCases:
    def _arm_job(self, c, pending):
        rz = c[0].cluster.resizer
        with rz._lock:
            rz._active_job = 1
            rz._pending_nodes = set(pending)
            rz._new_nodes = list(c[0].cluster.topology.nodes)
            rz._notify_nodes = list(c[0].cluster.topology.nodes)
        c[0].cluster.set_state(STATE_RESIZING)
        return rz

    def test_complete_with_error_still_flips_topology(self):
        """The heal-via-anti-entropy contract: a follower that failed
        mid-fetch reports an error but the job still completes — a
        wedged RESIZING is worse than missing fragments anti-entropy
        will copy."""
        with TestCluster(2) as c:
            rz = self._arm_job(c, {"node1"})
            done0 = _counter("resize_jobs_completed_total")
            rz.mark_complete(
                Message.make(
                    bc.MSG_RESIZE_COMPLETE, job=1, epoch=0, node="node1",
                    error="injected fetch failure",
                )
            )
            assert rz._active_job is None
            assert c[0].cluster.state() == STATE_NORMAL
            assert _counter("resize_jobs_completed_total") - done0 == 1

    def test_abort_only_job_loses_race_to_completion(self):
        """abort(only_job=) arriving AFTER the final completion is a
        no-op: re-freezing the new topology would undo a finished job."""
        with TestCluster(2) as c:
            rz = self._arm_job(c, {"node1"})
            rz.mark_complete(
                Message.make(bc.MSG_RESIZE_COMPLETE, job=1, epoch=0, node="node1")
            )
            assert c[0].cluster.state() == STATE_NORMAL
            aborts0 = _counter("resize_jobs_aborted_total")
            rz.abort(only_job=1)  # the timeout thread losing the race
            assert c[0].cluster.state() == STATE_NORMAL
            assert _counter("resize_jobs_aborted_total") == aborts0

    def test_stale_job_complete_rejected_after_abort(self):
        with TestCluster(2) as c:
            rz = self._arm_job(c, {"node1"})
            rz.abort()
            done0 = _counter("resize_jobs_completed_total")
            rz.mark_complete(
                Message.make(bc.MSG_RESIZE_COMPLETE, job=1, epoch=0, node="node1")
            )
            assert _counter("resize_jobs_completed_total") == done0

    def test_every_job_gets_a_fresh_epoch(self):
        """Two sequential jobs never share an epoch, so a dead job's
        straggler COMPLETE (still in a reporter's retry backoff) cannot
        satisfy a successor whose job counter happens to collide."""
        with TestCluster(2) as c:
            rz = c[0].cluster.resizer
            n9 = Node("node9", URI(host="127.0.0.1", port=1), False)
            epochs = []
            orig_start = rz._start_job

            def spy(new_nodes, removed=None):
                try:
                    return orig_start(new_nodes, removed)
                finally:
                    epochs.append(rz._epoch)

            rz._start_job = spy
            with pytest.raises(ResizeError):
                rz.add_node(n9)  # dead URI: job arms, delivery aborts it
            with pytest.raises(ResizeError):
                rz.add_node(n9)
            assert len(set(epochs)) == 2  # distinct epochs per job


# ---------------------------------------------------------------------------
# Persisted topology
# ---------------------------------------------------------------------------


class TestPersistedTopology:
    def _topo(self):
        return Topology(
            nodes=[
                Node("a", URI(host="h1", port=1), True),
                Node("b", URI(host="h2", port=2), False),
            ],
            replica_n=2,
            partition_n=64,
        )

    def test_save_load_roundtrip(self, tmp_path):
        p = str(tmp_path / ".topology")
        save_topology(p, self._topo(), "b", resize_epoch=7)
        d = load_topology(p)
        assert d["localID"] == "b"
        assert d["replicaN"] == 2
        assert d["partitionN"] == 64
        assert d["resizeEpoch"] == 7
        assert [n["id"] for n in d["nodes"]] == ["a", "b"]
        assert d["nodes"][0]["isCoordinator"] is True

    def test_save_is_atomic_no_tmp_left(self, tmp_path):
        p = str(tmp_path / ".topology")
        save_topology(p, self._topo(), "a")
        save_topology(p, self._topo(), "a")
        assert not os.path.exists(p + ".tmp")

    def test_corrupt_file_loads_none(self, tmp_path):
        p = str(tmp_path / ".topology")
        with open(p, "w") as f:
            f.write('{"nodes": [truncated')
        assert load_topology(p) is None
        with open(p, "w") as f:
            f.write('{"no": "nodes"}')
        assert load_topology(p) is None
        assert load_topology(str(tmp_path / "absent")) is None

    def test_cluster_persists_on_membership_change(self, tmp_path):
        with TestCluster(2) as c:
            p = str(tmp_path / ".topology")
            c[0].cluster.topology_file = p
            new_nodes = [n.to_json() for n in c[0].cluster.topology.nodes] + [
                Node("node9", URI(host="127.0.0.1", port=9), False).to_json()
            ]
            c[0].cluster.apply_message(
                Message.make(
                    bc.MSG_CLUSTER_STATUS, state=STATE_NORMAL,
                    nodes=new_nodes, replicaN=2,
                )
            )
            d = load_topology(p)
            assert d is not None
            assert len(d["nodes"]) == 3
            assert d["replicaN"] == 2
            assert d["localID"] == "node0"

    def test_cluster_persists_on_coordinator_move(self, tmp_path):
        with TestCluster(2) as c:
            p = str(tmp_path / ".topology")
            c[1].cluster.topology_file = p
            c[1].cluster.apply_message(
                Message.make(bc.MSG_SET_COORDINATOR, id="node1")
            )
            d = load_topology(p)
            coords = [n["id"] for n in d["nodes"] if n["isCoordinator"]]
            assert coords == ["node1"]

    def test_persist_failure_is_nonfatal(self):
        with TestCluster(1) as c:
            c[0].cluster.topology_file = "/nonexistent-dir/zzz/.topology"
            c[0].cluster.persist_topology()  # logs, does not raise


# ---------------------------------------------------------------------------
# Anti-entropy observability + jitter (ISSUE r9 satellites)
# ---------------------------------------------------------------------------


class TestAntiEntropyObservability:
    def test_run_counters_histogram_and_gauge(self):
        with TestCluster(2) as c:
            c.create_index("i")
            c.create_field("i", "f")
            c[0].api.import_bits("i", "f", [1], [5])
            runs0 = _counter("anti_entropy_runs_total")
            c.sync_all()
            assert _counter("anti_entropy_runs_total") - runs0 == 2
            hist = global_stats.histogram_snapshot()
            assert any(
                k.startswith("anti_entropy_run_seconds") for k in hist
            )
            gauges = global_stats.snapshot()["gauges"]
            last = gauges.get("anti_entropy_last_run_seconds")
            assert last is not None and 0 < last <= time.monotonic()

    def test_repairs_counted_by_kind(self):
        with TestCluster(2, replica_n=2) as c:
            c.create_index("i")
            c.create_field("i", "f")
            # Diverge one replica directly (bypassing replication).
            f0 = c[0].holder.index("i").field("f")
            f0.create_view_if_not_exists(VIEW_STANDARD).create_fragment_if_not_exists(0)
            _frag(c[0], "i", "f", 0).set_bit(1, 5)
            f0.add_available_shard(0)
            frag0 = _counter('anti_entropy_blocks_repaired_total{kind="fragment"}')
            c.sync_all()
            assert (
                _counter('anti_entropy_blocks_repaired_total{kind="fragment"}')
                > frag0
            )

    def test_daemon_interval_jitters_25_pct(self):
        from pilosa_tpu.cluster.sync import SyncDaemon

        with TestCluster(1) as c:
            waits: list[float] = []

            class Recorder:
                def wait(self, t):
                    waits.append(t)
                    return True  # stop immediately

                def set(self):
                    pass

            for _ in range(32):
                d = SyncDaemon(c[0].cluster, interval=100.0)
                d._stop = Recorder()
                d._run()
            assert all(75.0 <= w <= 125.0 for w in waits)
            assert max(waits) - min(waits) > 1.0  # actually jittered


# ---------------------------------------------------------------------------
# Epoch-directed repair convergence contract (ISSUE r15 tentpole 1 —
# the flipped TestUnionRepairLimitation pin: resurrection is FIXED)
# ---------------------------------------------------------------------------


class TestEpochDirectedConvergence:
    def test_cleared_bit_stays_cleared_after_sync(self):
        """THE flipped r9 pin: anti-entropy used to union differing
        blocks, so a clear that reached only one replica was
        resurrected by the next pass. The sync wire now ships per-block
        (checksum, epoch) and the HIGHER epoch wins — the clear's fresh
        stamp beats the stale set, the tombstone propagates, and the
        cleared bit STAYS cleared on both replicas."""
        with TestCluster(2, replica_n=2) as c:
            c.create_index("i")
            c.create_field("i", "f")
            c.query(0, "i", "Set(5, f=1)")
            c.await_shard_convergence("i")
            assert _frag(c[0], "i", "f", 0).row_count(1) == 1
            assert _frag(c[1], "i", "f", 0).row_count(1) == 1
            # The divergence shape: a clear that reached only ONE
            # replica (as a partition would leave it).
            _frag(c[1], "i", "f", 0).clear_bit(1, 5)
            assert _frag(c[1], "i", "f", 0).row_count(1) == 0
            directed0 = _counter("anti_entropy_directed_repairs_total")
            c.sync_all()
            # No resurrection: the clear's higher epoch won everywhere.
            assert _frag(c[0], "i", "f", 0).row_count(1) == 0
            assert _frag(c[1], "i", "f", 0).row_count(1) == 0
            assert c.query(0, "i", "Count(Row(f=1))")["results"][0] == 0
            assert c.query(1, "i", "Count(Row(f=1))")["results"][0] == 0
            assert _counter("anti_entropy_directed_repairs_total") > directed0
            # Converged on the epoch axis too: both replicas report the
            # same (checksum, epoch) for the repaired block.
            assert (
                _frag(c[0], "i", "f", 0).block_sums_epochs()
                == _frag(c[1], "i", "f", 0).block_sums_epochs()
            )

    def test_symmetric_set_and_clear_converge_to_higher_epoch(self):
        """Set-on-one/clear-on-other for the SAME block: both replicas
        converge to whichever side wrote last (block-granular
        last-writer-wins — the documented trade in
        docs/administration.md), byte-identically."""
        with TestCluster(2, replica_n=2) as c:
            c.create_index("i")
            c.create_field("i", "f")
            c.query(0, "i", "Set(5, f=1)")
            c.await_shard_convergence("i")
            # Divergent writes to the same block, replica-local: node0
            # sets a second bit, then node1 clears the seeded one — the
            # clear is the LAST write, so its epoch is the highest.
            _frag(c[0], "i", "f", 0).set_bit(1, 9)
            _frag(c[1], "i", "f", 0).clear_bit(1, 5)
            c.sync_all()
            c.sync_all()  # second pass: the loser pulls the winner
            rows0 = _frag(c[0], "i", "f", 0).row(1).columns().tolist()
            rows1 = _frag(c[1], "i", "f", 0).row(1).columns().tolist()
            assert rows0 == rows1 == []  # the clear's block won wholesale
            assert (
                _frag(c[0], "i", "f", 0).block_sums_epochs()
                == _frag(c[1], "i", "f", 0).block_sums_epochs()
            )

    def test_epochless_peer_degrades_to_union_never_wipes(self):
        """Mixed-version safety pin (ISSUE r15 acceptance): a replica
        whose blocks carry no epochs (pre-upgrade data, crash-dropped
        sidecar) must be repaired by UNION — a directed wipe of data
        nobody can date would be silent loss."""
        with TestCluster(2, replica_n=2) as c:
            c.create_index("i")
            c.create_field("i", "f")
            c.query(0, "i", "Set(5, f=1)")
            c.await_shard_convergence("i")
            # node1 diverges (extra local bit), then loses its epoch
            # plane entirely — the pre-upgrade replica shape.
            _frag(c[1], "i", "f", 0).set_bit(1, 9)
            _frag(c[1], "i", "f", 0)._block_epochs.clear()
            # node0 writes LATER (higher epoch on its side): a directed
            # resolution would wipe node1's undated bit 9.
            _frag(c[0], "i", "f", 0).set_bit(1, 7)
            union0 = _counter("anti_entropy_blocks_repaired_total")
            c.sync_all()
            c.sync_all()
            # Union, not wipe: every bit from both sides survives.
            for cn in (c[0], c[1]):
                cols = _frag(cn, "i", "f", 0).row(1).columns().tolist()
                assert cols == [5, 7, 9], cols
            assert _counter("anti_entropy_blocks_repaired_total") > union0

    def test_tombstoned_block_propagates(self):
        """A block-wide clear (every bit gone) still ships on the sync
        wire as a (checksum 0, epoch) tombstone — the replica holding
        the old bits adopts the empty block instead of never hearing
        about it."""
        with TestCluster(2, replica_n=2) as c:
            c.create_index("i")
            c.create_field("i", "f")
            c.query(0, "i", "Set(5, f=1) Set(6, f=1)")
            c.await_shard_convergence("i")
            f1 = _frag(c[1], "i", "f", 0)
            f1.clear_bit(1, 5)
            f1.clear_bit(1, 6)
            assert f1.row_count(1) == 0
            # The tombstone is visible on the wire payload.
            assert any(
                s == 0 and e > 0 for _b, s, e in f1.block_sums_epochs()
            )
            c.sync_all()
            assert _frag(c[0], "i", "f", 0).row_count(1) == 0
            assert c.query(0, "i", "Count(Row(f=1))")["results"][0] == 0
