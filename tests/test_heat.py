"""Block heat + miss-ratio curve tests (ISSUE 18 tentpole 1): the
SHARDS reuse-distance estimator is pinned against an exact byte-weighted
Mattson LRU simulation (within 5 points on zipf and scan traces — the
acceptance bar), the lazy-EWMA heat math halves over exactly one
half-life, the rejection path stays allocation-free, and /debug/heat
serves the whole plane end to end."""

import json
import urllib.request

import numpy as np
import pytest

from pilosa_tpu.core import Holder
from pilosa_tpu.exec import Executor
from pilosa_tpu.exec.tpu import TPUBackend, _StackedBlocks
from pilosa_tpu.server.api import API
from pilosa_tpu.server.http import Server
from pilosa_tpu.utils.reuse import HASH_SPACE, ReuseDistanceEstimator


# -- exact Mattson oracle ---------------------------------------------------


def exact_lru_hit_rate(trace, budget_bytes):
    """Exact byte-weighted LRU stack simulation: a reference hits iff
    the bytes of more-recently-used entries plus its own fit the budget
    — the same distance definition the estimator uses, unbucketed and
    unsampled."""
    from collections import OrderedDict

    stack = OrderedDict()
    hits = 0
    for key, nb in trace:
        if key in stack:
            above = 0
            for k in reversed(stack):
                if k == key:
                    break
                above += stack[k]
            if above + nb <= budget_bytes:
                hits += 1
            del stack[key]
        stack[key] = nb
    return hits / len(trace)


def zipf_trace(n_keys=400, n_refs=20_000, a=1.2, nbytes=1000, seed=7):
    """Deterministic zipf-ish reference stream over integer keys (int
    keys hash deterministically, so SHARDS admission is seed-stable)."""
    rng = np.random.default_rng(seed)
    p = 1.0 / np.arange(1, n_keys + 1) ** a
    p /= p.sum()
    keys = rng.choice(n_keys, size=n_refs, p=p)
    return [((int(k),), nbytes) for k in keys]


class TestReuseEstimator:
    def test_zipf_within_5_points_of_exact(self):
        """Acceptance bar: predicted hit rate within 5 points of the
        exact LRU simulation across budgets spanning the working set
        (including the true-working-set knee), at sampling rate 1.0."""
        trace = zipf_trace()
        est = ReuseDistanceEstimator(max_samples=1 << 14)
        for key, nb in trace:
            est.record(key, nb)
        assert est.rate == 1.0
        for budget in (10_000, 25_000, 50_000, 100_000, 200_000, 400_000):
            exact = exact_lru_hit_rate(trace, budget)
            got = est.hit_rate(budget)
            assert abs(got - exact) <= 0.05, (budget, got, exact)

    def test_sampled_rate_still_within_5_points(self):
        """SHARDS-max pressure (max_samples far below the key
        population) drives the rate below 1.0; the 1/rate scaling keeps
        the curve within the same 5-point bar."""
        trace = zipf_trace(n_keys=800, n_refs=40_000, seed=11)
        est = ReuseDistanceEstimator(max_samples=512)
        for key, nb in trace:
            est.record(key, nb)
        assert est.rate < 1.0  # eviction actually lowered the threshold
        for budget in (50_000, 100_000, 200_000, 400_000):
            exact = exact_lru_hit_rate(trace, budget)
            got = est.hit_rate(budget)
            assert abs(got - exact) <= 0.05, (budget, got, exact)

    def test_scan_trace_is_all_misses_below_footprint(self):
        """Cyclic scan over N blocks: every reuse distance equals the
        full footprint, so any budget below it predicts ~0 hit rate
        (the anti-LRU workload the runbook warns about)."""
        n, nb = 100, 1000
        trace = [((i % n,), nb) for i in range(10 * n)]
        est = ReuseDistanceEstimator()
        for key, b in trace:
            est.record(key, b)
        assert est.hit_rate(n * nb // 2) == 0.0
        assert exact_lru_hit_rate(trace, n * nb // 2) == 0.0
        # At (footprint + one block) every warm reference fits. The
        # estimator's log-bucket rounding needs one bucket of headroom.
        gen = est.hit_rate(n * nb * 1.1)
        assert abs(gen - exact_lru_hit_rate(trace, n * nb)) <= 0.05

    def test_rejection_path_touches_nothing(self):
        """The admission gate is one hash compare: a rejected reference
        must not grow the stack, the histogram, or the sample count —
        the near-zero-idle-cost contract of the block-fetch path."""
        est = ReuseDistanceEstimator()
        est._threshold = 0  # reject everything
        for i in range(1000):
            assert est.record((i,), 1000) is False
        assert est.samples == 0
        assert len(est._stack) == 0
        assert est._hist == {}

    def test_curve_is_monotonic_and_bounded(self):
        est = ReuseDistanceEstimator()
        for key, nb in zipf_trace(n_refs=5000):
            est.record(key, nb)
        pts = est.curve(points=16)
        assert 0 < len(pts) <= 17  # log-thinned + kept endpoint
        rates = [p["hitRate"] for p in pts]
        assert rates == sorted(rates)
        assert all(0.0 <= r <= 1.0 for r in rates)
        budgets = [p["budgetBytes"] for p in pts]
        assert budgets == sorted(budgets)

    def test_shards_max_keeps_stack_bounded(self):
        est = ReuseDistanceEstimator(max_samples=32)
        for i in range(10_000):
            est.record((i,), 100)  # all-cold stream
        assert len(est._stack) <= 32
        assert est._threshold < HASH_SPACE  # rate self-tuned down


class TestHeatLedger:
    def test_heat_halves_over_one_half_life(self):
        """The lazy-EWMA pin: heat decays by exactly 2^(-idle/half_life)
        at the next touch — one half-life of idleness halves it."""
        blocks = _StackedBlocks(heat_half_life=10.0)
        led = {"access_count": 0}
        blocks._bump_heat(led)
        assert led["heat"] == 1.0
        # Rewind the stamp one full half-life: the next bump sees heat
        # 1.0 * 0.5 + 1.0.
        led["last_access"] -= 10.0
        blocks._bump_heat(led)
        assert led["heat"] == pytest.approx(1.5, abs=1e-3)
        assert led["access_count"] == 2

    def test_fresh_entry_skips_decay(self):
        """heat == 0.0 must not read last_access (a brand-new ledger
        entry has no stamp yet)."""
        blocks = _StackedBlocks(heat_half_life=10.0)
        led = {"access_count": 0}
        blocks._bump_heat(led)  # must not KeyError on last_access
        assert led["heat"] == 1.0

    def test_heat_snapshot_tiers_sum_to_entry_heat(self, tmp_path):
        holder = Holder(str(tmp_path / "d")).open()
        try:
            idx = holder.create_index("i")
            idx.create_field("f")
            ex = Executor(holder, backend=TPUBackend(holder,
                                                     heat_half_life=60.0))
            ex.execute("i", "Set(1, f=1) Set(100, f=2)")
            for _ in range(5):
                ex.execute("i", "Count(Row(f=1))")
            snap = ex.backend.blocks.heat_snapshot()
            assert snap["halfLifeSeconds"] == 60.0
            assert snap["entries"], snap
            ent = snap["entries"][0]
            assert ent["heat"] > 0
            assert ent["accessCount"] >= 5
            # The tier rollup splits entry heat by tier-byte fraction:
            # totals agree (no double counting).
            assert sum(snap["tierHeat"].values()) == pytest.approx(
                sum(e["heat"] for e in snap["entries"]), rel=1e-3
            )
            # entries=N truncation keeps the rollup intact (approx:
            # heat decays continuously between the two snapshots).
            top1 = ex.backend.blocks.heat_snapshot(entries=1)
            assert len(top1["entries"]) == 1
            for t in snap["tierHeat"]:
                assert top1["tierHeat"][t] == pytest.approx(
                    snap["tierHeat"][t], abs=0.01
                )
        finally:
            holder.close()

    def test_block_hits_feed_reuse_estimator(self, tmp_path):
        holder = Holder(str(tmp_path / "d")).open()
        try:
            idx = holder.create_index("i")
            idx.create_field("f")
            ex = Executor(holder, backend=TPUBackend(holder))
            ex.execute("i", "Set(1, f=1)")
            for _ in range(6):
                ex.execute("i", "Count(Row(f=1))")
            reuse = ex.backend.blocks.reuse.snapshot()
            assert reuse["samples"] >= 6
            # Warm re-references produced finite distances → a curve.
            assert reuse["finiteWeight"] > 0
            assert reuse["curve"], reuse
        finally:
            holder.close()


# -- end to end -------------------------------------------------------------


@pytest.fixture
def tpu_server(tmp_path):
    holder = Holder(str(tmp_path / "data")).open()
    ex = Executor(holder, backend=TPUBackend(holder))
    srv = Server(API(holder, ex), host="localhost", port=0).open()
    yield srv
    srv.close()
    holder.close()


def _post(srv, path, body=b"{}", ctype="application/json"):
    r = urllib.request.Request(
        srv.uri + path, data=body, method="POST",
        headers={"Content-Type": ctype},
    )
    return json.loads(urllib.request.urlopen(r).read())


def get_json(srv, path):
    return json.loads(urllib.request.urlopen(srv.uri + path).read())


class TestDebugHeatEndpoint:
    def test_serves_heat_and_curve(self, tpu_server):
        _post(tpu_server, "/index/i")
        _post(tpu_server, "/index/i/field/f")
        _post(tpu_server, "/index/i/query", b"Set(10, f=1)", "text/plain")
        for _ in range(4):
            _post(tpu_server, "/index/i/query", b"Count(Row(f=1))",
                  "text/plain")
        out = get_json(tpu_server, "/debug/heat")
        assert out["halfLifeSeconds"] > 0
        assert set(out["tierHeat"]) == {"dense", "array", "run"}
        assert out["entries"] and out["entries"][0]["heat"] > 0
        assert out["reuse"]["samples"] > 0
        assert isinstance(out["reuse"]["curve"], list)
        # ?top=N truncates the entry list, not the rollup (heat decays
        # continuously, so the two scrapes agree only approximately).
        top = get_json(tpu_server, "/debug/heat?top=1")
        assert len(top["entries"]) == 1
        for t in out["tierHeat"]:
            assert top["tierHeat"][t] == pytest.approx(
                out["tierHeat"][t], abs=0.01
            )

    def test_hbm_top_param(self, tpu_server):
        _post(tpu_server, "/index/i")
        _post(tpu_server, "/index/i/field/f")
        _post(tpu_server, "/index/i/field/g")
        _post(tpu_server, "/index/i/query", b"Set(10, f=1) Set(10, g=1)",
              "text/plain")
        _post(tpu_server, "/index/i/query",
              b"Count(Intersect(Row(f=1), Row(g=1)))", "text/plain")
        full = get_json(tpu_server, "/debug/hbm")
        assert full["totalEntries"] == len(full["entries"]) >= 2
        top = get_json(tpu_server, "/debug/hbm?top=1")
        assert len(top["entries"]) == 1
        assert top["totalEntries"] == full["totalEntries"]
        # Garbage in the param is a structured 400, not a 500.
        with pytest.raises(urllib.error.HTTPError) as ei:
            get_json(tpu_server, "/debug/hbm?top=zzz")
        assert ei.value.code == 400
