"""Connection-plane observability tests (ISSUE 20): the lifecycle
ledger's bounded tables, keep-alive reuse accounting, per-state time
conservation, queue-wait truth, the /proc kernel probes (fixture-parsed
+ non-Linux no-op), the thread-role registry, and the three debug
endpoints over the real socket surface."""

import http.client
import json
import threading
import time
import urllib.request

import pytest

from pilosa_tpu.core import Holder
from pilosa_tpu.exec import Executor
from pilosa_tpu.server.api import API
from pilosa_tpu.server.connplane import (
    ConnectionPlane,
    parse_listen_backlogs,
    parse_listen_drops,
)
from pilosa_tpu.server.http import Server
from pilosa_tpu.utils import threads
from pilosa_tpu.utils.locks import StallLedger
from pilosa_tpu.utils.stats import StatsClient, global_stats


@pytest.fixture
def server(tmp_path):
    holder = Holder(str(tmp_path / "data")).open()
    srv = Server(API(holder, Executor(holder)), host="localhost", port=0).open()
    yield srv
    srv.close()
    holder.close()


def get_json(srv, path):
    with urllib.request.urlopen(srv.uri + path) as resp:
        return json.loads(resp.read())


def hist_count(family):
    snap = global_stats.histogram_snapshot()
    return sum(
        sum(ent["buckets"]) for name, ent in snap.items()
        if name == family or name.startswith(family + "{")
    )


def counter_total(family):
    snap = global_stats.snapshot()["counters"]
    return sum(v for k, v in snap.items() if k.startswith(family))


class SmallPlane(ConnectionPlane):
    LIVE_CAP = 8
    RING_CAP = 4


class TestLedgerBounds:
    def test_ring_cap_under_churn(self):
        plane = SmallPlane()
        for i in range(20):
            e = plane.register(("10.0.0.1", 40000 + i))
            plane.close_entry(e)
        snap = plane.snapshot()
        assert snap["live"] == 0
        assert snap["opened"] == 20
        assert snap["tabled"] == 0
        # The closed ring kept only the newest RING_CAP entries.
        assert len(snap["recentClosed"]) == SmallPlane.RING_CAP
        ids = [e["id"] for e in snap["recentClosed"]]
        assert ids == sorted(ids, reverse=True)
        assert max(ids) == 20

    def test_live_cap_overflow_still_counts(self):
        plane = SmallPlane()
        entries = [
            plane.register(("10.0.0.2", 50000 + i)) for i in range(12)
        ]
        snap = plane.snapshot()
        # Past the cap: counted live, not tabled — bounded memory.
        assert snap["live"] == 12
        assert snap["tabled"] == SmallPlane.LIVE_CAP
        assert sum(1 for e in entries if not e.tracked) == 4
        for e in entries:
            plane.close_entry(e)
        snap = plane.snapshot()
        assert snap["live"] == 0
        assert snap["tabled"] == 0

    def test_queue_wait_observed_via_enter(self):
        plane = ConnectionPlane()
        e = plane.register(("10.0.0.3", 1234))
        time.sleep(0.03)
        plane.enter(e)
        assert e.queue_wait_s is not None and e.queue_wait_s >= 0.03
        # worstQueueWaits surfaces it, worst-first.
        plane2_snap = plane.snapshot()
        worst = plane2_snap["worstQueueWaits"]
        assert worst and worst[0]["queueWaitMs"] >= 30.0
        plane.close_entry(e)


class TestProcParsing:
    TCP = (
        "  sl  local_address rem_address   st tx_queue rx_queue tr "
        "tm->when retrnsmt   uid  timeout inode\n"
        # LISTEN (st=0A) on port 0x1F90=8080 with rx backlog 5.
        "   0: 00000000:1F90 00000000:0000 0A 00000000:00000005 "
        "00:00000000 00000000  1000 0 111 1 0 100 0 0 10 0\n"
        # ESTABLISHED (st=01) on the same port: must be ignored.
        "   1: 00000000:1F90 0100007F:D431 01 00000000:00000063 "
        "00:00000000 00000000  1000 0 112 1 0 100 0 0 10 0\n"
        # LISTEN on a port nobody asked about.
        "   2: 00000000:0016 00000000:0000 0A 00000000:00000002 "
        "00:00000000 00000000  0 0 113 1 0 100 0 0 10 0\n"
        "garbage line\n"
    )
    NETSTAT = (
        "TcpExt: SyncookiesSent ListenOverflows ListenDrops\n"
        "TcpExt: 0 7 9\n"
        "IpExt: InNoRoutes InTruncatedPkts\n"
        "IpExt: 0 0\n"
    )

    def test_parse_listen_backlogs(self):
        assert parse_listen_backlogs(self.TCP, {8080}) == {8080: 5}
        assert parse_listen_backlogs(self.TCP, {22}) == {22: 2}
        assert parse_listen_backlogs(self.TCP, {9999}) == {}
        assert parse_listen_backlogs("", {8080}) == {}

    def test_parse_listen_drops(self):
        assert parse_listen_drops(self.NETSTAT) == (7, 9)
        # Header without the fields, or no TcpExt pair at all: None.
        assert parse_listen_drops("TcpExt: Foo\nTcpExt: 1\n") is None
        assert parse_listen_drops("IpExt: A\nIpExt: 0\n") is None
        assert parse_listen_drops("") is None

    def test_poll_kernel_reads_fixture_proc(self, tmp_path):
        proc = tmp_path / "net"
        proc.mkdir()
        (proc / "tcp").write_text(self.TCP)
        (proc / "netstat").write_text(self.NETSTAT)
        plane = ConnectionPlane(proc_net=str(proc))
        plane.register_listener(8080)
        stats = StatsClient()
        out = plane.poll_kernel(stats)
        assert out == {
            "acceptQueueDepth": 5,
            "listenOverflows": 7,
            "listenDrops": 9,
        }
        # First poll establishes the baseline — no delta counted yet.
        counters = stats.snapshot()["counters"]
        assert "http_listen_overflows_total" not in counters
        # Kernel counters move; the second poll counts exactly the delta.
        (proc / "netstat").write_text(
            "TcpExt: SyncookiesSent ListenOverflows ListenDrops\n"
            "TcpExt: 0 10 9\n"
        )
        plane.poll_kernel(stats)
        counters = stats.snapshot()["counters"]
        assert counters["http_listen_overflows_total"] == 3
        assert "http_listen_drops_total" not in counters
        assert stats.snapshot()["gauges"]["http_accept_queue_depth"] == 5

    def test_non_linux_noop(self, tmp_path):
        plane = ConnectionPlane(proc_net=str(tmp_path / "nope"))
        plane.register_listener(8080)
        assert plane.accept_queue_depth() is None
        out = plane.poll_kernel(StatsClient())
        assert out == {
            "acceptQueueDepth": None,
            "listenOverflows": None,
            "listenDrops": None,
        }

    def test_listener_registry_refcounts(self):
        plane = ConnectionPlane(proc_net="/nonexistent")
        plane.register_listener(9000)
        plane.register_listener(9000)
        plane.unregister_listener(9000)
        assert plane._listeners == {9000: 1}
        plane.unregister_listener(9000)
        assert plane._listeners == {}


class TestServerIntegration:
    def test_keepalive_reuse_counting(self, server):
        reuse0 = counter_total("http_keepalive_reuse_total")
        conn = http.client.HTTPConnection(server.host, server.port)
        try:
            for _ in range(3):
                conn.request("GET", "/")
                conn.getresponse().read()
            snap = get_json(server, "/debug/connections")
            mine = [
                e for e in snap["connections"] if e["requests"] >= 3
            ]
            assert mine, snap["connections"]
            e = mine[0]
            assert e["reuses"] == e["requests"] - 1
            assert e["bytesIn"] > 0 and e["bytesOut"] > 0
            assert e["queueWaitMs"] is not None
            assert e["state"] == "idle"
        finally:
            conn.close()
        # The flush at each idle transition pushed the reuse deltas.
        assert counter_total("http_keepalive_reuse_total") >= reuse0 + 2

    def test_state_seconds_conserve_wall_time(self, server):
        conn = http.client.HTTPConnection(server.host, server.port)
        conn.request("GET", "/")
        conn.getresponse().read()
        time.sleep(0.05)  # measurable keep-alive idle dwell
        conn.request("GET", "/")
        conn.getresponse().read()
        conn.close()
        # The worker notices the FIN and retires the entry.
        deadline = time.time() + 5
        closed = []
        while time.time() < deadline:
            snap = get_json(server, "/debug/connections")
            closed = [
                e for e in snap["recentClosed"] if e["requests"] == 2
            ]
            if closed:
                break
            time.sleep(0.02)
        assert closed, "closed entry never reached the ring"
        e = closed[0]
        # Per-state dwell sums to the connection's whole life: the
        # clock is read only at transitions, and every transition
        # charges the outgoing state — nothing double-counted, nothing
        # dropped.
        total = sum(e["stateSeconds"].values())
        assert total == pytest.approx(e["ageSeconds"], abs=0.02)
        assert e["stateSeconds"].get("idle", 0.0) >= 0.05
        for st in e["stateSeconds"]:
            assert st in (
                "accepted", "queued", "reading", "parsing",
                "executing", "writing", "idle", "closed",
            )

    def test_queue_wait_histogram_observes(self, server):
        n0 = hist_count("http_queue_wait_seconds")
        get_json(server, "/status")
        assert hist_count("http_queue_wait_seconds") > n0

    def test_debug_connections_top_and_aggregates(self, server):
        conns = [
            http.client.HTTPConnection(server.host, server.port)
            for _ in range(3)
        ]
        try:
            for c in conns:
                c.request("GET", "/")
                c.getresponse().read()
            snap = get_json(server, "/debug/connections?top=1")
            assert snap["live"] >= 3
            assert snap["opened"] >= 4
            # Aggregates cover everything; the detail list honors top.
            assert sum(snap["stateOccupancy"].values()) == snap["tabled"]
            assert len(snap["connections"]) == 1
            assert set(snap["kernel"]) == {
                "acceptQueueDepth", "listenOverflows", "listenDrops",
            }
            assert snap["reuseDistribution"]
        finally:
            for c in conns:
                c.close()

    def test_debug_index_lists_routes(self, server):
        idx = get_json(server, "/debug")
        paths = {e["path"]: e for e in idx["endpoints"]}
        assert "/debug/connections" in paths
        assert "/debug/threads" in paths
        assert "/index/<index>/query" in paths
        for e in idx["endpoints"]:
            assert e["method"] in ("GET", "POST", "DELETE", "PATCH")
            assert isinstance(e["description"], str)
        assert "ledger" in paths["/debug/connections"]["description"].lower()

    def test_debug_threads_roles(self, server):
        # Drive one request so at least one worker thread is alive.
        get_json(server, "/status")
        out = get_json(server, "/debug/threads")
        assert out["count"] == len(out["threads"])
        assert out["roles"].get("http-listener", 0) >= 1
        assert out["roles"].get("http-worker", 0) >= 1
        for t in out["threads"]:
            assert set(t) == {
                "name", "ident", "role", "daemon", "ageSeconds",
            }
        named = [
            t for t in out["threads"] if t["role"] == "http-listener"
        ]
        assert all(t["name"].startswith("http-listener") for t in named)


class TestThreadRegistry:
    def test_spawn_registers_and_unregisters(self):
        seen = {}
        release = threading.Event()

        def work():
            seen["role"] = threads.role_of_current()
            seen["name"] = threading.current_thread().name
            release.wait(5)

        t = threads.spawn("monitor-poll", work)
        for _ in range(100):
            if "role" in seen:
                break
            time.sleep(0.01)
        assert seen["role"] == "monitor-poll"
        assert seen["name"].startswith("monitor-poll-")
        assert threads.roles_snapshot()[t.ident] == "monitor-poll"
        release.set()
        t.join(5)
        # Dead threads leave the registry — no accumulation.
        assert t.ident not in threads.roles_snapshot()
        assert threads.role_of(t.ident) == "unknown"

    def test_main_thread_role(self):
        assert threads.role_of_current() == "main"
        snap = threads.threads_snapshot()
        mains = [t for t in snap if t["role"] == "main"]
        assert len(mains) == 1

    def test_spawn_start_false(self):
        t = threads.spawn("preheat", lambda: None, start=False)
        assert not t.is_alive()
        t.start()
        t.join(5)

    def test_stall_exemplar_carries_role(self):
        ledger = StallLedger()
        ledger.record("test.site", 0.012, None)
        worst = ledger.worst()
        assert worst[0]["role"] == "main"
        assert worst[0]["site"] == "test.site"
