"""Mesh-vs-single-device equivalence suite (ISSUE r13).

Runs on the forced 8-virtual-device CPU platform (tests/conftest.py):
a sharded TPUBackend must answer every query family byte-identically
to the CPU oracle AND to a single-device TPUBackend, across write-
churn epochs — with the dirty-shard SPLICE (not a full rebuild)
absorbing each epoch on the resident sharded stacks, asserted via the
stack_incremental_updates_total / stack_full_rebuilds_total counters.

Also the ShardMesh unit contract (ISSUE r13 satellite): pad-to-multiple
zero-slab placement instead of the old divisibility assert, and a
structured MeshConfigError on an empty device list.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
tpu_mod = pytest.importorskip(
    "pilosa_tpu.exec.tpu", reason="device backend needs jax shard_map"
)

from pilosa_tpu.core import Holder  # noqa: E402
from pilosa_tpu.core.field import options_for_int  # noqa: E402
from pilosa_tpu.exec import Executor  # noqa: E402
from pilosa_tpu.exec.batcher import ShardLegBatcher  # noqa: E402
from pilosa_tpu.exec.result import result_to_json  # noqa: E402
from pilosa_tpu.exec.tpu import TPUBackend  # noqa: E402
from pilosa_tpu.parallel import (  # noqa: E402
    MeshConfigError,
    ShardMesh,
    pad_to_multiple,
)
from pilosa_tpu.shardwidth import SHARD_WIDTH  # noqa: E402
from pilosa_tpu.utils.stats import global_stats  # noqa: E402


@pytest.fixture
def holder(tmp_path):
    h = Holder(str(tmp_path / "data")).open()
    yield h
    h.close()


N_SHARDS = 11  # not a multiple of 8: exercises the zero-slab padding

#: Every device-lowered query family (the acceptance list: Count, Row,
#: Intersect, TopN, Sum, Min, Max, GroupBy — plus the verb/BSI variants
#: that ride the same programs).
FAMILIES = [
    "Count(Row(f=1))",
    "Count(Intersect(Row(f=1), Row(g=7)))",
    "Count(Union(Row(f=1), Row(f=2), Row(f=3)))",
    "Count(Difference(Row(f=1), Row(g=7)))",
    "Count(Xor(Row(f=1), Row(g=7)))",
    "Count(Not(Row(f=1)))",
    "Row(f=2)",
    "Intersect(Row(f=1), Row(g=7))",
    "TopN(f, n=3)",
    "TopN(f, Row(g=7), n=2)",
    "Sum(field=v)",
    "Min(field=v)",
    "Max(field=v)",
    "Count(Row(v > 100))",
    "Count(Row(v >< [-100, 100]))",
    "GroupBy(Rows(f))",
    "GroupBy(Rows(f), Rows(g))",
    "GroupBy(Rows(f), Rows(g), Rows(hh))",
]


def _setup(holder, rng):
    idx = holder.create_index("i")
    idx.create_field("f")
    idx.create_field("g")
    idx.create_field("hh")
    idx.create_field("v", options_for_int(-500, 500))
    span = N_SHARDS * SHARD_WIDTH
    for row in (1, 2, 3):
        cols = np.unique(rng.integers(0, span, 6000, dtype=np.uint64))
        idx.field("f").import_bits(
            np.full(cols.size, row, dtype=np.uint64), cols
        )
        idx.existence_field().import_bits(
            np.zeros(cols.size, dtype=np.uint64), cols
        )
    cols = np.unique(rng.integers(0, span, 4000, dtype=np.uint64))
    idx.field("g").import_bits(np.full(cols.size, 7, dtype=np.uint64), cols)
    cols = np.unique(rng.integers(0, span, 1500, dtype=np.uint64))
    idx.field("hh").import_bits(
        rng.integers(0, 2, cols.size, dtype=np.uint64), cols
    )
    cols = np.unique(rng.integers(0, span, 900, dtype=np.uint64))
    idx.field("v").import_value(cols, rng.integers(-500, 501, cols.size))
    return idx


def _answers(ex, queries=FAMILIES):
    return {
        q: [result_to_json(r) for r in ex.execute("i", q)] for q in queries
    }


def _stack_counters():
    c = global_stats.snapshot()["counters"]
    return {
        k: c.get(k, 0.0)
        for k in (
            "stack_incremental_updates_total",
            "stack_incremental_shards_total",
            "stack_full_rebuilds_total",
            "stack_update_bytes_total",
        )
    }


class TestMeshDifferential:
    """Forced 8-device mesh vs CPU oracle vs single-device backend,
    across churn epochs, splice-not-rebuild asserted."""

    def test_families_identical_across_churn_epochs(self, holder, rng):
        idx = _setup(holder, rng)
        ex_cpu = Executor(holder)
        be_one = TPUBackend(holder)
        ex_one = Executor(holder, backend=be_one)
        be_mesh = TPUBackend(holder, mesh=ShardMesh())
        ex_mesh = Executor(holder, backend=be_mesh)
        ex_mesh.batcher = ShardLegBatcher(be_mesh)

        # Epoch 0 (cold builds) …
        want = _answers(ex_cpu)
        assert _answers(ex_one) == want
        assert _answers(ex_mesh) == want

        # … then churn epochs: bit writes on existing rows (splice-able
        # on the resident stacks) + BSI value writes, each followed by
        # the full family sweep on all three engines.
        base = _stack_counters()
        for k in range(2):
            idx.field("f").set_bit(1, 5 + k * 131)
            idx.field("g").set_bit(7, 3 * SHARD_WIDTH + 17 + k)
            idx.field("v").set_value(29 + k * 97, (-1) ** k * (333 - k))
            want = _answers(ex_cpu)
            assert _answers(ex_mesh) == want, f"epoch {k}"
            assert _answers(ex_one) == want, f"epoch {k}"
        after = _stack_counters()
        # The epochs were absorbed by dirty-shard splices on the
        # already-resident stacks; the full-rebuild counter stays FLAT
        # (the fragment_rebuilds-style invariant the splice exists for).
        assert after["stack_incremental_updates_total"] > base[
            "stack_incremental_updates_total"
        ]
        assert after["stack_full_rebuilds_total"] == base[
            "stack_full_rebuilds_total"
        ]

    def test_mesh_splice_is_o_dirty(self, holder, rng):
        """One dirty shard ships O(slab) bytes into the sharded stack
        (n_devices slabs, one per device lane), never the whole stack."""
        _setup(holder, rng)
        mesh = ShardMesh()
        be = TPUBackend(holder, mesh=mesh)
        ex = Executor(holder, backend=be)
        ex.execute("i", "Row(f=1)")  # cold build
        f_obj = be._field("i", "f")
        block, rows_p = be.blocks.get("i", f_obj, tuple(range(N_SHARDS)))
        stack_bytes = int(np.prod(block.shape)) * 4
        base = _stack_counters()
        holder.index("i").field("f").set_bit(1, 5)
        got = [result_to_json(r) for r in ex.execute("i", "Row(f=1)")]
        want = [result_to_json(r) for r in Executor(holder).execute("i", "Row(f=1)")]
        assert got == want
        after = _stack_counters()
        assert after["stack_incremental_updates_total"] == base[
            "stack_incremental_updates_total"
        ] + 1
        assert after["stack_incremental_shards_total"] == base[
            "stack_incremental_shards_total"
        ] + 1
        assert after["stack_full_rebuilds_total"] == base[
            "stack_full_rebuilds_total"
        ]
        shipped = after["stack_update_bytes_total"] - base[
            "stack_update_bytes_total"
        ]
        # One splice round: one slab per device lane — strictly under
        # the 16-slab padded stack this shape produces.
        assert shipped == mesh.n * rows_p * (SHARD_WIDTH // 32) * 4
        assert shipped < stack_bytes

    def test_mesh_batched_paths_match_singles(self, holder, rng):
        """The batching plane's group launches (count/row/bsi/topn legs)
        through a meshed backend agree with per-query execution."""
        from pilosa_tpu.pql import parse_string

        _setup(holder, rng)
        be = TPUBackend(holder, mesh=ShardMesh())
        batcher = ShardLegBatcher(be)
        shards = list(range(N_SHARDS))
        calls = [
            parse_string(f"Intersect(Row(f={r}), Row(g=7))").calls[0]
            for r in (1, 2, 3)
        ]
        singles = [be.count_shards("i", c, shards) for c in calls]
        assert batcher.count("i", calls, shards) == singles
        row_call = parse_string("Intersect(Row(f=1), Row(g=7))").calls[0]
        assert (
            batcher.row("i", row_call, shards).columns().tolist()
            == be.bitmap_call("i", row_call, shards).columns().tolist()
        )
        assert batcher.topn("i", "f", shards, 3) == be.topn_field(
            "i", "f", shards, 3
        )
        assert batcher.bsi("bsi_sum", "i", "v", shards) == be.bsi_sum(
            "i", "v", shards
        )

    def test_mesh_groupn_tensor_serves_and_absorbs_churn(self, holder, rng):
        """The N>=3 group tensor (host-maintained per-shard table) is
        mesh-enabled: cold sweep under shard_map, then a write epoch
        resolves on the host with no re-dispatch."""
        idx = _setup(holder, rng)
        be = TPUBackend(holder, mesh=ShardMesh())
        ex = Executor(holder, backend=be)
        ex_cpu = Executor(holder)
        q = "GroupBy(Rows(f), Rows(g), Rows(hh))"
        want = [result_to_json(r) for r in ex_cpu.execute("i", q)]
        assert [result_to_json(r) for r in ex.execute("i", q)] == want
        assert be._groupn_cache, "mesh GroupN should populate the tensor cache"
        c0 = global_stats.snapshot()["counters"].get(
            "groupn_incremental_updates_total", 0.0
        )
        idx.field("f").set_bit(2, 7)
        want = [result_to_json(r) for r in ex_cpu.execute("i", q)]
        assert [result_to_json(r) for r in ex.execute("i", q)] == want
        assert global_stats.snapshot()["counters"].get(
            "groupn_incremental_updates_total", 0.0
        ) > c0


class TestShardMeshUnit:
    def test_put_pads_to_device_multiple(self):
        mesh = ShardMesh()
        arr = np.arange(
            N_SHARDS * 4, dtype=np.uint32
        ).reshape(N_SHARDS, 4)
        placed = mesh.put(arr)
        assert placed.shape[0] == pad_to_multiple(N_SHARDS, mesh.n)
        host = np.asarray(placed)
        np.testing.assert_array_equal(host[:N_SHARDS], arr)
        # Zero-slab padding: semantically inert for every reduction.
        assert not host[N_SHARDS:].any()

    def test_put_exact_multiple_unpadded(self):
        mesh = ShardMesh()
        arr = np.ones((mesh.n * 2, 3), dtype=np.uint32)
        assert mesh.put(arr).shape == arr.shape

    def test_empty_device_list_is_structured_error(self):
        with pytest.raises(MeshConfigError):
            ShardMesh(devices=[])
        assert issubclass(MeshConfigError, ValueError)

    def test_pad_to_multiple(self):
        assert pad_to_multiple(11, 8) == 16
        assert pad_to_multiple(16, 8) == 16
        assert pad_to_multiple(1, 8) == 8
        assert pad_to_multiple(5, 1) == 5
        assert pad_to_multiple(0, 8) == 0
