"""Randomized PQL generator (reference internal/test/querygenerator.go).

Generates random-but-valid query trees over a fixed schema: set fields
"f"/"g", a mutex "m", an int (BSI) field "v", and the existence field.
Used differentially — every generated query runs through both the CPU
oracle and the device backend, and the results must match exactly. This
is the cheapest way to shake out device-lowering edge cases the ~15
hand-picked query shapes in tests/test_tpu.py can't reach.
"""

from __future__ import annotations

import numpy as np

SET_FIELDS = ("f", "g")
MUTEX_FIELD = "m"
INT_FIELD = "v"
VERBS = ("Intersect", "Union", "Difference", "Xor")


class QueryGenerator:
    def __init__(self, seed: int, max_depth: int = 3, n_rows: int = 5,
                 int_lo: int = -50, int_hi: int = 50):
        self.rng = np.random.default_rng(seed)
        self.max_depth = max_depth
        self.n_rows = n_rows
        self.int_lo = int_lo
        self.int_hi = int_hi

    def _i(self, lo, hi) -> int:
        return int(self.rng.integers(lo, hi))

    def row_leaf(self) -> str:
        kind = self._i(0, 4)
        if kind == 0:  # plain set row (sometimes a missing row id)
            f = SET_FIELDS[self._i(0, len(SET_FIELDS))]
            return f"Row({f}={self._i(0, self.n_rows + 2)})"
        if kind == 1:  # mutex row
            return f"Row({MUTEX_FIELD}={self._i(0, 3)})"
        if kind == 2:  # BSI comparison
            op = ("<", ">", "<=", ">=", "==", "!=")[self._i(0, 6)]
            val = self._i(self.int_lo - 10, self.int_hi + 10)
            return f"Row({INT_FIELD} {op} {val})"
        # BSI between
        lo = self._i(self.int_lo - 5, self.int_hi)
        hi = self._i(lo, self.int_hi + 5)
        return f"Row({lo} <= {INT_FIELD} <= {hi})"

    def bitmap(self, depth: int = 0) -> str:
        if depth >= self.max_depth or self._i(0, 3) == 0:
            return self.row_leaf()
        kind = self._i(0, 6)
        if kind == 0:
            return f"Not({self.bitmap(depth + 1)})"
        verb = VERBS[self._i(0, len(VERBS))]
        n_children = self._i(2, 4)
        children = ", ".join(self.bitmap(depth + 1) for _ in range(n_children))
        return f"{verb}({children})"

    def group_by(self) -> str:
        fields = list(SET_FIELDS) + [MUTEX_FIELD]
        n = self._i(1, 4)
        self.rng.shuffle(fields)
        rows = ", ".join(f"Rows({f})" for f in fields[:n])
        extras = []
        if self._i(0, 2):
            extras.append(f"filter={self.row_leaf()}")
        if self._i(0, 2):
            extras.append(f"limit={self._i(1, 8)}")
            if self._i(0, 2):
                extras.append(f"offset={self._i(0, 4)}")
        tail = (", " + ", ".join(extras)) if extras else ""
        return f"GroupBy({rows}{tail})"

    def query(self) -> str:
        kind = self._i(0, 11)
        b = self.bitmap()
        if kind < 4:
            return f"Count({b})"
        if kind < 6:
            return b  # bare bitmap: compares columns
        if kind == 6:
            f = SET_FIELDS[self._i(0, len(SET_FIELDS))]
            return f"TopN({f}, {b}, n={self._i(1, 6)})"
        if kind == 7:
            return f"Sum({b}, field={INT_FIELD})"
        if kind == 8:
            return f"Min({b}, field={INT_FIELD})"
        if kind == 9:
            return f"Max({b}, field={INT_FIELD})"
        return self.group_by()


def build_schema(holder, rng, shards: int = 2, density: int = 1200):
    """Populate the generator's fixed schema with random data."""
    from pilosa_tpu.core.field import FieldOptions, options_for_int
    from pilosa_tpu.shardwidth import SHARD_WIDTH

    idx = holder.create_index("qg")
    for fname in SET_FIELDS:
        idx.create_field(fname)
    idx.create_field(MUTEX_FIELD, FieldOptions(type="mutex"))
    idx.create_field(INT_FIELD, options_for_int(-50, 50))
    span = shards * SHARD_WIDTH
    for fname in SET_FIELDS:
        for row in range(5):
            cols = np.unique(rng.integers(0, span, density, dtype=np.uint64))
            idx.field(fname).import_bits(
                np.full(cols.size, row, dtype=np.uint64), cols
            )
    for row in range(3):
        cols = np.unique(rng.integers(0, span, density // 2, dtype=np.uint64))
        idx.field(MUTEX_FIELD).import_bits(
            np.full(cols.size, row, dtype=np.uint64), cols
        )
    cols = np.unique(rng.integers(0, span, density, dtype=np.uint64))
    idx.field(INT_FIELD).import_value(
        cols, rng.integers(-50, 51, cols.size)
    )
    return idx
