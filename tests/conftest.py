"""Test configuration.

Forces JAX onto a virtual 8-device CPU platform so multi-chip sharding
(mesh/shard_map/psum paths) is exercised without TPU hardware, mirroring how
the reference tests multi-node with in-process clusters instead of real ones
(reference test/pilosa.go MustRunCluster). Must run before jax is imported.

Opt-in REAL-chip leg (VERDICT r4 #6): `PILOSA_TPU_TEST_TPU=1 pytest -m tpu`
keeps the ambient TPU platform and runs only the @pytest.mark.tpu tests
(tests/test_tpu_live.py) against the live chip. Run it SOLO — never
concurrently with bench.py or another chip user.
"""

import os

import numpy as np
import pytest

LIVE_TPU = os.environ.get("PILOSA_TPU_TEST_TPU", "") in ("1", "true")

if not LIVE_TPU:
    # Force, not setdefault: the ambient environment may preselect the real
    # TPU platform, but tests must run on the virtual 8-device CPU mesh.
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()

    # The image's sitecustomize imports jax at interpreter startup (TPU
    # plugin registration), which snapshots JAX_PLATFORMS before this file
    # runs — update the live config too.
    import jax

    jax.config.update("jax_platforms", "cpu")


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "tpu: opt-in tests that require the real TPU chip "
        "(PILOSA_TPU_TEST_TPU=1 pytest -m tpu; run solo)",
    )
    config.addinivalue_line(
        "markers",
        "chaos: fault-injection tests (FaultProxy blackhole/latency/drop "
        "in the in-process cluster harness); fast, bounded-timeout chaos "
        "stays in tier-1 — anything slow carries `slow` too",
    )
    config.addinivalue_line(
        "markers",
        "slow: excluded from the tier-1 gate (`-m 'not slow'`)",
    )


def pytest_collection_modifyitems(config, items):
    if LIVE_TPU:
        # Live-chip mode runs ONLY the tpu-marked leg: the rest of the
        # suite depends on the virtual 8-device CPU mesh (not forced
        # above) and must never hammer the shared chip.
        keep = [i for i in items if "tpu" in i.keywords]
        drop = [i for i in items if "tpu" not in i.keywords]
        if drop:
            config.hook.pytest_deselected(items=drop)
            items[:] = keep
        return
    skip_tpu = pytest.mark.skip(
        reason="real-chip leg: set PILOSA_TPU_TEST_TPU=1 and run -m tpu solo"
    )
    for item in items:
        if "tpu" in item.keywords:
            item.add_marker(skip_tpu)


@pytest.fixture
def rng():
    return np.random.default_rng(42)
