"""Test configuration.

Forces JAX onto a virtual 8-device CPU platform so multi-chip sharding
(mesh/shard_map/psum paths) is exercised without TPU hardware, mirroring how
the reference tests multi-node with in-process clusters instead of real ones
(reference test/pilosa.go MustRunCluster). Must run before jax is imported.
"""

import os

# Force, not setdefault: the ambient environment may preselect the real TPU
# platform, but tests must run on the virtual 8-device CPU mesh.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

# The image's sitecustomize imports jax at interpreter startup (TPU plugin
# registration), which snapshots JAX_PLATFORMS before this file runs —
# update the live config too.
import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.default_rng(42)
