"""Serving-path host-cost collapse tests (ISSUE r14): the byte-compat
differential suite for utils/fastjson vs json.dumps across every
response shape, the vectorized varint wire compat, the wire-bytes
result-cache hit path, and the vectorized-row-materialization vs
roaring-oracle differential under import/import_value churn."""

import json
import random

import numpy as np
import pytest

from pilosa_tpu.core import Holder
from pilosa_tpu.core.cache import Pair
from pilosa_tpu.core.field import options_for_int
from pilosa_tpu.core.row import Row
from pilosa_tpu.exec import Executor
from pilosa_tpu.exec.result import (
    FieldRow,
    GroupCount,
    PairField,
    PairsField,
    RowIDs,
    ValCount,
    result_to_json,
)
from pilosa_tpu.pql import parse_string
from pilosa_tpu.roaring import Bitmap
from pilosa_tpu.server.api import API
from pilosa_tpu.shardwidth import SHARD_WIDTH
from pilosa_tpu.utils import fastjson


def legacy_encode(r, exclude_columns=False):
    """The production dict encoder (server/api.py) as the oracle."""
    return API._encode_result(None, r, exclude_columns)


def assert_compat(r, exclude_columns=False):
    want = json.dumps(legacy_encode(r, exclude_columns)).encode()
    got = fastjson.encode_result(r, exclude_columns)
    assert got == want, (got[:120], want[:120])


class TestVectorEncoders:
    EDGES = [
        [], [0], [9], [10], [99], [100], [1], [2 ** 64 - 1],
        [10 ** 10 - 1], [10 ** 10], [10 ** 19], [10 ** 19 - 1],
        [10 ** k for k in range(20)],
        [10 ** k - 1 for k in range(1, 20)],
        [0] * 64,
    ]

    @pytest.mark.parametrize("vals", EDGES)
    def test_uints_edges(self, vals):
        got = fastjson.encode_uints(np.array(vals, dtype=np.uint64))
        assert got == ", ".join(str(v) for v in vals).encode()

    def test_uints_fuzz(self):
        rng = random.Random(14)
        for _ in range(20):
            mag = rng.choice([10, 2 ** 16, 2 ** 32, 2 ** 64])
            vals = [rng.randrange(mag) for _ in range(rng.randrange(1, 800))]
            got = fastjson.encode_uints(np.array(vals, dtype=np.uint64))
            assert got == ", ".join(str(v) for v in vals).encode()

    @pytest.mark.parametrize("vals", EDGES)
    def test_varints_edges(self, vals):
        from pilosa_tpu.server.wire import _encode_varint

        got = fastjson.encode_varints(np.array(vals, dtype=np.uint64))
        assert got == b"".join(_encode_varint(v) for v in vals)

    def test_varints_fuzz(self):
        from pilosa_tpu.server.wire import _encode_varint

        rng = random.Random(41)
        for _ in range(20):
            mag = rng.choice([128, 2 ** 14, 2 ** 35, 2 ** 64])
            vals = [rng.randrange(mag) for _ in range(rng.randrange(1, 500))]
            got = fastjson.encode_varints(np.array(vals, dtype=np.uint64))
            assert got == b"".join(_encode_varint(v) for v in vals)


class TestResultByteCompat:
    """fastjson.encode_result must be byte-identical to json.dumps over
    the legacy dict encoder for EVERY response shape."""

    def test_row_columns(self):
        assert_compat(Row([5, 17, SHARD_WIDTH + 3, 2 * SHARD_WIDTH]))

    def test_row_empty(self):
        assert_compat(Row())
        assert_compat(Row(), exclude_columns=True)

    def test_row_exclude_columns(self):
        assert_compat(Row([1, 2, 3]), exclude_columns=True)

    def test_row_keys_and_attrs(self):
        r = Row([4, 9])
        r.keys = ["alpha", "béta", "日本"]
        r.attrs = {"höhe": 3, "ok": True, "name": "zoë"}
        assert_compat(r)
        assert_compat(r, exclude_columns=True)

    def test_row_attrs_only(self):
        r = Row([4, 9])
        r.attrs = {"x": 1.5, "y": None}
        assert_compat(r)

    def test_scalars(self):
        for v in (0, 12345, True, False, None):
            assert_compat(v)

    def test_valcount(self):
        assert_compat(ValCount(val=-42, count=17))
        assert_compat(ValCount())

    def test_topn_pairs(self):
        assert_compat(PairsField([Pair(3, 9), Pair(1, 2)], "f"))
        assert_compat(
            PairsField([Pair(3, 9, key="königin"), Pair(1, 2, key="k2")], "f")
        )
        assert_compat(PairsField([], "f"))

    def test_pair_field(self):
        assert_compat(PairField(Pair(7, 3), "f"))
        assert_compat(PairField(Pair(7, 3, key="clé"), "f"))

    def test_row_ids(self):
        assert_compat(RowIDs([1, 5, 9]))
        assert_compat(RowIDs([]))
        keyed = RowIDs([1, 2])
        keyed.keys = ["a", "ü"]
        assert_compat(keyed)

    def test_group_counts(self):
        gcs = [
            GroupCount([FieldRow("f", 1), FieldRow("g", 2)], 12),
            GroupCount([FieldRow("f", 3, row_key="clé"), FieldRow("g", 4)], 0),
        ]
        assert_compat(gcs)
        assert_compat([])
        assert_compat(gcs[0])

    def test_response_envelope(self):
        frags = [
            fastjson.encode_result(r)
            for r in (Row([1, 2]), 7, ValCount(3, 4))
        ]
        want = json.dumps(
            {
                "results": [
                    legacy_encode(r) for r in (Row([1, 2]), 7, ValCount(3, 4))
                ]
            }
        ).encode() + b"\n"
        assert fastjson.response_body(frags) == want

    def test_response_envelope_attr_sets(self):
        sets = [{"id": 3, "attrs": {"k": "v"}}]
        got = fastjson.response_body([b"1"], sets)
        assert got == json.dumps(
            {"results": [1], "columnAttrSets": sets}
        ).encode() + b"\n"

    def test_generic_dumps(self):
        for obj in (
            {"error": "no such index: x", "code": "not-found"},
            {"error": "PANIC: ütf8 \n traceback", "code": "internal"},
            {"success": True},
        ):
            assert fastjson.dumps(obj) == json.dumps(obj).encode()


@pytest.fixture
def holder():
    h = Holder(None).open()
    idx = h.create_index("i")
    f = idx.create_field("f")
    g = idx.create_field("g")
    rng = np.random.default_rng(9)
    for shard in range(3):
        base = shard * SHARD_WIDTH
        for field in (f, g):
            rows = np.repeat(np.arange(4, dtype=np.uint64), 300)
            cols = rng.integers(0, SHARD_WIDTH, rows.size).astype(
                np.uint64
            ) + base
            field.import_bits(rows, cols)
    v = idx.create_field("v", options_for_int(-1000, 1000))
    cols = np.unique(rng.integers(0, 3 * SHARD_WIDTH, 400).astype(np.uint64))
    v.import_value(cols, (cols.astype(np.int64) % 700) - 350)
    yield h
    h.close()


class TestQueryBytesByteCompat:
    """api.query_bytes must equal json.dumps(api.query(...)) + newline
    for real executions — the whole-envelope end-to-end pin."""

    QUERIES = [
        "Count(Row(f=1))",
        "Row(f=1)",
        "Row(f=1)Count(Row(g=2))Row(g=3)",
        "Intersect(Row(f=1), Row(g=2))",
        "Union(Row(f=0), Row(f=1))",
        "TopN(f, n=3)",
        "Sum(field=v)Min(field=v)Max(field=v)",
        "GroupBy(Rows(f), Rows(g))",
        "Rows(f)",
        "Count(Row(f=99))",  # empty result
        "Row(f=99)",         # empty row
    ]

    @pytest.mark.parametrize("q", QUERIES)
    def test_bytes_match_dict_path(self, holder, q):
        api = API(holder, Executor(holder))
        want = (json.dumps(api.query("i", q)) + "\n").encode()
        got = api.query_bytes("i", q)
        assert got == want, q

    def test_exclude_columns(self, holder):
        api = API(holder, Executor(holder))
        kw = dict(exclude_columns=True)
        want = (json.dumps(api.query("i", "Row(f=1)", **kw)) + "\n").encode()
        assert api.query_bytes("i", "Row(f=1)", **kw) == want

    def test_keyed_index_rows(self):
        from pilosa_tpu.core.index import IndexOptions

        h = Holder(None).open()
        try:
            idx = h.create_index("k", IndexOptions(keys=True))
            idx.create_field("f")
            api = API(h, Executor(h))
            api.query("k", 'Set("côl-à", f=1)Set("col-b", f=1)')
            want = (json.dumps(api.query("k", "Row(f=1)")) + "\n").encode()
            assert api.query_bytes("k", "Row(f=1)") == want
        finally:
            h.close()

    def test_row_attrs(self, holder):
        api = API(holder, Executor(holder))
        api.query("i", 'SetRowAttrs(f, 1, city="straße", n=3)')
        want = (json.dumps(api.query("i", "Row(f=1)")) + "\n").encode()
        assert api.query_bytes("i", "Row(f=1)") == want

    def test_error_envelope_round_trips(self, holder):
        """Error bodies keep the json.dumps byte format (the _reply
        fallback encoder is json.dumps itself)."""
        from pilosa_tpu.server.http import Server

        srv = Server(API(holder, Executor(holder)), port=0).open()
        try:
            import http.client

            conn = http.client.HTTPConnection("localhost", srv.port)
            conn.request("POST", "/index/nosuch/query", "Count(Row(f=1))")
            resp = conn.getresponse()
            body = resp.read()
            assert resp.status == 400
            parsed = json.loads(body)
            assert parsed["code"]
            assert body == (json.dumps(parsed) + "\n").encode()
            conn.close()
        finally:
            srv.close()


class TestWireBytesCache:
    """Tentpole 3: a result-cache hit serves the entry's pre-encoded
    fragment — and those bytes are identical to a fresh encode."""

    def test_hit_serves_attached_wire(self, holder):
        from pilosa_tpu.exec.rescache import ResultCache

        ex = Executor(holder)
        ex.rescache = ResultCache(holder, max_bytes=1 << 20)
        api = API(holder, ex)
        q = "Count(Intersect(Row(f=1), Row(g=2)))"
        first = api.query_bytes("i", q)   # miss: encodes + attaches
        entry = next(iter(ex.rescache._entries.values()))
        assert entry.wire, "wire fragment not attached on miss"
        second = api.query_bytes("i", q)  # hit: serves cached bytes
        assert first == second
        assert ex.rescache.hits >= 1
        # The cached fragment is exactly the value's fresh encoding.
        flags = ("json", False)
        assert entry.wire[flags] == fastjson.encode_result(entry.value)

    def test_wire_bytes_charged_to_ledger(self, holder):
        from pilosa_tpu.exec.rescache import ResultCache

        ex = Executor(holder)
        cache = ResultCache(holder, max_bytes=1 << 20)
        ex.rescache = cache
        api = API(holder, ex)
        api.query_bytes("i", "Row(f=1)")
        entry = next(iter(cache._entries.values()))
        frag = next(iter(entry.wire.values()))
        # Strict ledger: resident equals the per-entry sum, and the
        # entry's accounted size includes the encoded payload.
        assert cache.resident_bytes() == sum(
            e.nbytes for e in cache._entries.values()
        )
        assert entry.nbytes > len(frag)

    def test_row_size_accounting_is_lazy(self):
        """result_nbytes must not force a lazy Row to materialize its
        columns array (ISSUE r14 satellite)."""
        from pilosa_tpu.exec.rescache import result_nbytes

        r = Row.from_segment(0, Bitmap([1, 2, 3]))
        n = result_nbytes(r)
        assert n == 112 + 8 * 3
        assert r._cols is None, "size accounting materialized columns"

    def test_oversized_wire_not_charged(self, holder):
        """A wire fragment that would push the entry past the whole
        budget is not memoized — the ledger bound holds and live
        entries are not flushed (code review r14, the commit() guard
        mirrored)."""
        from pilosa_tpu.exec.rescache import ResultCache

        ex = Executor(holder)
        # Budget just over the Row VALUE size so commit retains it but
        # value+fragment cannot fit (fragment is ~2.7x the value).
        probe = Executor(holder).execute("i", "Row(f=1)")[0]
        from pilosa_tpu.exec.rescache import result_nbytes

        budget = 300 + result_nbytes(probe) + 200
        cache = ResultCache(holder, max_bytes=budget)
        ex.rescache = cache
        api = API(holder, ex)
        api.query_bytes("i", "Count(Row(g=1))")   # small live entry
        before = len(cache._entries)
        api.query_bytes("i", "Row(f=1)")           # fragment won't fit
        entry = [e for e in cache._entries.values() if e.pql.startswith("Row")]
        assert entry and not entry[0].wire, "oversized fragment memoized"
        assert cache.resident_bytes() <= budget
        assert len(cache._entries) >= before  # small entry not flushed
        # Hits still serve (re-encoding fresh each time).
        a = api.query_bytes("i", "Row(f=1)")
        b = api.query_bytes("i", "Row(f=1)")
        assert a == b

    def test_bypass_skips_wire_cache(self, holder):
        from pilosa_tpu.exec.rescache import ResultCache

        ex = Executor(holder)
        ex.rescache = ResultCache(holder, max_bytes=1 << 20)
        api = API(holder, ex)
        q = "Count(Row(f=1))"
        a = api.query_bytes("i", q)
        b = api.query_bytes("i", q, cache_bypass=True)
        assert a == b
        assert ex.rescache.bypass >= 1


class TestRowMaterializationOracle:
    """Tentpole 1: the vectorized whole-slab materialization (lazy
    columns-backed Rows) must match the roaring oracle exactly, across
    import/import_value churn epochs and through set algebra."""

    QUERIES = [
        "Row(f=1)",
        "Intersect(Row(f=1), Row(g=2))",
        "Union(Row(f=0), Row(f=3), Row(g=1))",
        "Difference(Row(f=1), Row(g=2))",
        "Xor(Row(f=2), Row(g=3))",
        "Not(Row(f=1))",
        "Count(Intersect(Row(f=1), Row(g=2)))",
    ]

    def _oracle_row(self, row):
        """Re-derive columns from the roaring segments the lazy Row
        materializes — the two representations must agree."""
        segs = row._segs()
        parts = [
            segs[s].to_array() + np.uint64(s * SHARD_WIDTH)
            for s in sorted(segs)
        ]
        return (
            np.concatenate(parts) if parts
            else np.empty(0, dtype=np.uint64)
        )

    def test_differential_under_churn(self, holder):
        jax = pytest.importorskip("jax")  # noqa: F841 — device backend
        from pilosa_tpu.exec.tpu import TPUBackend

        idx = holder.index("i")
        ex_cpu = Executor(holder)
        ex_tpu = Executor(holder, backend=TPUBackend(holder))
        rng = np.random.default_rng(77)
        for epoch in range(3):
            for q in self.QUERIES:
                want = ex_cpu.execute("i", q)
                got = ex_tpu.execute("i", q)
                assert [result_to_json(r) for r in got] == [
                    result_to_json(r) for r in want
                ], (epoch, q)
                for r in got:
                    if isinstance(r, Row):
                        # Lazy array vs roaring-materialized agreement.
                        np.testing.assert_array_equal(
                            r.columns(), self._oracle_row(r)
                        )
            # Set algebra ON the lazy rows vs the oracle.
            a = ex_tpu.execute("i", "Row(f=1)")[0]
            b = ex_tpu.execute("i", "Row(g=2)")[0]
            ca = ex_cpu.execute("i", "Row(f=1)")[0]
            cb = ex_cpu.execute("i", "Row(g=2)")[0]
            for op in ("intersect", "union", "difference", "xor"):
                np.testing.assert_array_equal(
                    getattr(a, op)(b).columns(),
                    getattr(ca, op)(cb).columns(),
                )
            assert a.intersection_count(b) == ca.intersection_count(cb)
            assert a.count() == ca.count() and a.any() == ca.any()
            # Churn: bit imports + BSI imports start the next epoch.
            cols = np.unique(
                rng.integers(0, 3 * SHARD_WIDTH, 500).astype(np.uint64)
            )
            idx.field("f").import_bits(
                (cols % 4).astype(np.uint64), cols
            )
            vcols = np.unique(
                rng.integers(0, 3 * SHARD_WIDTH, 200).astype(np.uint64)
            )
            idx.field("v").import_value(
                vcols, (vcols.astype(np.int64) % 500) - 250
            )

    def test_from_columns_roundtrip(self):
        rng = np.random.default_rng(3)
        cols = np.unique(
            rng.integers(0, 5 * SHARD_WIDTH, 4000).astype(np.uint64)
        )
        lazy = Row.from_columns(cols.copy())
        eager = Row(cols.copy())
        assert lazy == eager
        assert lazy.count() == eager.count() == cols.size
        assert lazy.includes_column(int(cols[17]))
        assert not lazy.includes_column(int(cols[17]) + 1 if int(
            cols[17]
        ) + 1 not in set(cols[:40].tolist()) else 0) or True
        # Materialization produces the same segments as eager build.
        np.testing.assert_array_equal(
            sorted(lazy._segs()), sorted(eager._segs())
        )
        for s in lazy._segs():
            np.testing.assert_array_equal(
                lazy._segs()[s].to_array(), eager._segs()[s].to_array()
            )

    def test_duplicate_shard_list_dedupes(self, holder):
        """?shards=3,3 must union idempotently like the old per-shard
        merge loop did — not duplicate columns (code review r14)."""
        pytest.importorskip("jax")
        from pilosa_tpu.exec.tpu import TPUBackend

        be = TPUBackend(holder)
        call = parse_string("Row(f=1)").calls[0]
        want = be.bitmap_call("i", call, [1])
        got = be.bitmap_call("i", call, [1, 1])
        np.testing.assert_array_equal(got.columns(), want.columns())
        assert got.count() == want.count()
        # Unsorted shard lists still produce a sorted column array.
        rev = be.bitmap_call("i", call, [2, 0, 1])
        fwd = be.bitmap_call("i", call, [0, 1, 2])
        np.testing.assert_array_equal(rev.columns(), fwd.columns())
        cols = rev.columns()
        assert np.all(cols[:-1] < cols[1:])

    def test_unpack_slab_columns_blocked(self, monkeypatch):
        """The blocked unpack (bounded transient) is byte-identical to
        a single pass."""
        import pilosa_tpu.ops.blocks as blocks

        rng = np.random.default_rng(8)
        host = rng.integers(0, 2 ** 32, (16, 64), dtype=np.uint32)
        bases = np.arange(16, dtype=np.uint64) * np.uint64(SHARD_WIDTH)
        want = blocks.unpack_slab_columns(host, bases)
        monkeypatch.setattr(blocks, "MAX_UNPACK_BITS_BYTES", 64 * 32)
        got = blocks.unpack_slab_columns(host, bases)  # 1 row per block
        np.testing.assert_array_equal(got, want)
        assert np.all(want[:-1] < want[1:])
        empty = blocks.unpack_slab_columns(
            np.zeros((4, 64), dtype=np.uint32), bases[:4]
        )
        assert empty.size == 0

    def test_bitmap_from_sorted_array(self):
        rng = np.random.default_rng(4)
        vals = np.unique(rng.integers(0, 1 << 22, 30000).astype(np.uint64))
        bm = Bitmap.from_sorted_array(vals)
        np.testing.assert_array_equal(bm.to_array(), vals)
        assert bm.count() == vals.size
        # Dense span exercises the bitmap-container branch.
        dense = np.arange(10_000, dtype=np.uint64)
        np.testing.assert_array_equal(
            Bitmap.from_sorted_array(dense).to_array(), dense
        )
        assert Bitmap.from_sorted_array(
            np.empty(0, dtype=np.uint64)
        ).count() == 0
