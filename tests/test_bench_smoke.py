"""Tier-1 bench smoke (ISSUE r7 satellite): run bench.py end to end at a
tiny shape and assert the BENCH JSON is complete and carries the keys
the round driver consumes — an artifact-zeroing regression (a leg that
crashes, a renamed key, a partial=true artifact) fails HERE instead of
burning a full round to discover it."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SMOKE_ENV = {
    "JAX_PLATFORMS": "cpu",
    "BENCH_SHARDS": "3",
    "BENCH_ROWS": "2",
    "BENCH_DENSITY": "0.02",
    "BENCH_BATCH": "8",
    "BENCH_SECONDS": "0.3",
    "BENCH_LATENCY_N": "3",
    "BENCH_HTTP_CLIENTS": "2",
    "BENCH_HTTP_QUERIES_PER_REQ": "4",
    "BENCH_WRITE_RATES": "0,10",
    "BENCH_CHURN_SECONDS": "0.5",
    # Tiny concurrency sweep: the leg's machinery (per-N checkpoints,
    # occupancy/launch deltas) is what's smoked, not the scaling curve.
    "BENCH_CONCURRENCY": "1,4",
    # A failed background warm must degrade the wire (dense fallback),
    # never hang the smoke on the warm poll.
    "BENCH_WARM_TIMEOUT": "120",
    # Tiny ingest-under-load leg (r8): machinery smoke, not a rate.
    "BENCH_INGEST_SECONDS": "0.5",
    "BENCH_INGEST_WRITERS": "2",
    "BENCH_INGEST_READERS": "2",
    "BENCH_INGEST_BATCH": "32",
    "BENCH_INGEST_SHARDS": "2",
    # Tiny partition-heal drill (r15): two shards exercise every epoch
    # resolution arm; convergence is the contract, not a rate.
    "BENCH_PARTITION_SHARDS": "2",
    "BENCH_PARTITION_TIMEOUT": "30",
    # Tiny rolling-restart drill (r9): subprocess-cluster machinery
    # smoke; the leg self-skips (keys still present) where subprocess
    # networking is restricted.
    "BENCH_ROLLING_READERS": "2",
    "BENCH_ROLLING_SETTLE": "0.3",
    "BENCH_ROLLING_CONVERGE_TIMEOUT": "45",
    # Tiny mesh-scaling leg (r13): two curve points exercise the
    # subprocess-per-device-count machinery, the folded MULTICHIP
    # differential, and the under-churn splice counters — not a curve.
    "BENCH_MESH_DEVICES": "1,2",
    "BENCH_MESH_SHARDS": "8",
    "BENCH_MESH_SECONDS": "0.3",
    # Tiny GroupBy cardinality sweep (ISSUE 17): two levels exercise
    # the prune + tile machinery and the recompile pin, not a curve.
    "BENCH_CARD_LEVELS": "8,64",
    "BENCH_CARD_SHARDS": "2",
    "BENCH_CARD_LIVE_ROWS": "4",
}


def test_bench_smoke(tmp_path):
    pytest.importorskip(
        "pilosa_tpu.exec.tpu",
        reason="bench needs the device backend (jax.shard_map)",
        exc_type=ImportError,
    )
    env = dict(os.environ, **SMOKE_ENV)
    env["BENCH_PARTIAL_PATH"] = str(tmp_path / "BENCH_partial.json")
    out = subprocess.run(
        [sys.executable, "bench.py"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=480,
    )
    assert out.returncode == 0, (out.stdout[-2000:], out.stderr[-4000:])
    blob = json.loads(out.stdout.strip().splitlines()[-1])
    # Complete artifact, not a crash-truncated partial.
    assert blob["partial"] is False
    assert blob["value"] is not None
    # The r7 keys the driver's acceptance reads.
    assert "cold_build_seconds" in blob
    assert "cold_build_dense_seconds" in blob
    assert "churn_version_walks" in blob
    assert "minmax_churn_qps_ratio" in blob
    # The r12 zipf-cache keys the driver's acceptance reads: hit-rate
    # vs qps per concurrency point, churn-burst phases, the same-run
    # disabled comparison, and the byte-identity differential.
    assert set(blob["zipf_qps_at_clients"]) == {"1", "4"}
    assert set(blob["zipf_hit_rate_at_clients"]) == {"1", "4"}
    assert set(blob["zipf_hit_rate_phases"]) == {"pre", "burst", "post"}
    assert "zipf_qps_disabled" in blob
    assert "zipf_cache_speedup" in blob
    assert blob["zipf_differential_mismatches"] == 0
    assert blob["zipf_churn_writes"] > 0
    # The r11 concurrency-sweep keys the driver's acceptance reads.
    assert set(blob["qps_at_clients"]) == {"1", "4"}
    assert "batch_occupancy_mean_at_clients" in blob
    assert "device_launches_at_clients" in blob
    assert "client_retries" in blob and "client_aborts" in blob
    # The r14 serving-collapse keys: per-window host_reduce/serialize
    # phase deltas and payload throughput on the sweep + zipf legs,
    # plus the http leg's headline bytes/s figure.
    assert set(blob["concurrency_phase_ms"]) == {"1", "4"}
    assert set(blob["payload_bytes_per_s_at_clients"]) == {"1", "4"}
    assert set(blob["zipf_phase_ms_at_clients"]) == {"1", "4"}
    assert set(blob["zipf_payload_bytes_per_s_at_clients"]) == {"1", "4"}
    assert blob["payload_bytes_per_s"] > 0
    # The ISSUE 20 connection-plane blocks: every sweep/zipf window
    # ships queue-wait quantiles, the kernel accept-queue worst case,
    # per-state seconds, and the keep-alive reuse rate.
    assert set(blob["concurrency_conn_plane"]) == {"1", "4"}
    assert set(blob["zipf_conn_plane_at"]) == {"1", "4"}
    for win in list(blob["concurrency_conn_plane"].values()) + list(
        blob["zipf_conn_plane_at"].values()
    ):
        for key in ("queue_wait_p50_ms", "queue_wait_p99_ms",
                    "max_accept_queue_depth", "state_seconds",
                    "keepalive_reuse_rate"):
            assert key in win, win
        assert win["queue_wait_count"] > 0, win
        assert win["state_seconds"].get("executing", 0) > 0, win
    for win in blob["payload_bytes_per_s_at_clients"].values():
        assert win > 0
    # The r8 ingest-under-load keys the driver's acceptance reads.
    assert blob["ingest_rows_per_s"] > 0
    assert blob["ingest_read_qps_under_load"] > 0
    assert "ingest_read_p99_delta_ms" in blob
    assert "ingest_version_walks" in blob
    # The ISSUE 16 introspection keys: the ingest leg attributes its
    # read-p99 delta to named stall sources (server-side snapshot-stall
    # counter + per-site lock waits), and the groupby leg ships the
    # EXPLAIN tree of the 3-field sweep as ROADMAP-item-2 seed data.
    assert "ingest_snapshot_stall_seconds" in blob
    assert isinstance(blob["ingest_lock_wait_seconds"], dict)
    # The ISSUE 18 flight-recorder key: window B ships its second-by-
    # second interference timeline (a list of delta entries; at the
    # smoke's 0.5 s window it may legitimately hold < 2 samples, so
    # only the shape — not a minimum length — is pinned).
    assert isinstance(blob["ingest_timeline"], list)
    for ent in blob["ingest_timeline"]:
        assert "qps" in ent and "lockWaitS" in ent, ent
    # The ISSUE r19 plane-isolation keys: the leg ran under the paced-
    # snapshot + windowed-refresh posture, and the derating sub-window
    # raised the burn ladder and shed writers while readers held.
    assert blob["ingest_snapshot_bandwidth"] > 0
    assert blob["ingest_refresh_window_ms"] > 0
    assert "ingest_derate_sheds" in blob
    assert "ingest_derate_read_p99_ms" in blob
    assert blob["ingest_derate_level"] >= 1
    assert blob["ingest_derate_rows_per_s"] >= 0
    assert "calls" in blob["groupby_explain"], blob["groupby_explain"]
    # The ISSUE 17 tiled-GroupBy keys: the forced-sweep figure rides
    # next to the served warm figure, and the cardinality leg proves
    # launches track live_combinations/slots with zero recompiles.
    assert blob["groupby_3field_sweep_ms"] > 0
    assert blob["groupby_3field_warm_ms"] > 0
    pts = blob["groupby_cardinality_points"]
    assert [p["k_nominal"] for p in pts] == [8, 64]
    for p in pts:
        assert p["k_live"] <= p["k_nominal"]
        assert p["tiles"] == p["tiles_expected"], p
        assert p["pruned_groups"] == p["pruned_expected"], p
        assert sum(p["launches"].values()) > 0, p
    assert blob["groupby_cardinality_recompiles"] == 0
    assert "calls" in blob["groupby_cardinality_explain"]
    # The r15 partition-heal keys the driver's acceptance reads: the
    # partition was real, the cluster reconverged, zero resurrections,
    # and directed repairs were recorded for BOTH heal directions.
    assert blob["partition_heal_proven_blackholed"] is True
    assert blob["partition_heal_converged"] is True
    assert blob["partition_heal_convergence_s"] is not None
    assert blob["partition_heal_resurrected_bits"] == 0
    dr = blob["partition_heal_directed_repairs"]
    assert dr.get("remote_wins", 0) > 0 and dr.get("local_wins", 0) > 0, dr
    # The r9 rolling-restart keys: present even when the environment
    # forces a skip; when the drill ran, every restart reconverged.
    for key in ("rolling_restart_skipped", "rolling_restart_windows",
                "rolling_restart_reconverge_seconds",
                "rolling_restart_reconverge_max_s",
                "rolling_restart_availability_min",
                "rolling_restart_counters"):
        assert key in blob, key
    if blob["rolling_restart_skipped"] is None:
        assert len(blob["rolling_restart_windows"]) == 3
        assert all(w["reconverged"] for w in blob["rolling_restart_windows"])
        assert blob["rolling_restart_lost_writes"] == []
    # The r13 mesh_scaling keys the driver's acceptance reads: the
    # per-device curve, the folded MULTICHIP verdict (its historical
    # key shape preserved), and the under-churn splice proof.
    assert set(blob["mesh_qps_at_devices"]) == {"1", "2"}
    assert set(blob["mesh_sweep_ms_device_only_at_devices"]) == {"1", "2"}
    assert "mesh_sweep_monotonic" in blob
    assert "mesh_qps_scaling_vs_1" in blob
    mc = blob["multichip"]
    assert set(mc) >= {"n_devices", "rc", "ok", "skipped", "tail"}
    if not mc["skipped"]:
        assert mc["ok"] is True and mc["rc"] == 0, mc
        assert blob["mesh_differential_ok_at_devices"]["2"] is True
        sp = blob["mesh_splice"]
        # One dirty shard spliced O(slab) bytes — never a full rebuild.
        assert sp["incremental_updates"] >= 1 and sp["full_rebuilds"] == 0, sp
        assert sp["o_slab"] is True, sp
    # Every leg checkpointed along the way.
    for leg in ("build", "cold_build", "tpu_batch", "single_query",
                "minmax_churn", "http", "qps@1", "qps@4",
                "concurrency_sweep", "zipf@1", "zipf@4", "zipf_cache",
                "partition_heal", "ingest_under_load", "rolling_restart",
                "mesh@1", "mesh@2", "mesh_scaling", "groupby",
                "groupby_cardinality"):
        assert leg in blob["legs_done"], blob["legs_done"]
    # The partial artifact also landed complete on disk.
    disk = json.loads(open(env["BENCH_PARTIAL_PATH"]).read())
    assert disk["partial"] is False
