"""Asymmetric network partitions (VERDICT r4 #9; reference pumba
harness, internal/clustertests/cluster_test.go:68-92): node1's outbound
to node2 goes through a real TCP proxy that can refuse or blackhole
while every other direction stays healthy — the one failure class
SIGKILL/SIGSTOP legs cannot produce (they partition a node from
EVERYONE). Asserts: the one-sided observer degrades only its own view,
healthy peers reject its DOWN claim (SWIM corroboration), nobody flaps,
the coordinator never splits, and release heals."""

import time

import pytest

from pilosa_tpu.cluster.sync import FailureDetector
from pilosa_tpu.cluster.topology import NODE_STATE_DOWN, NODE_STATE_READY
from pilosa_tpu.shardwidth import SHARD_WIDTH

from tests.cluster_harness import FaultProxy, RewriteClient, TestCluster


def _view(cn) -> dict:
    return {n.id: n.state for n in cn.cluster.topology.nodes}


def _coord(cn):
    return next(
        (n.id for n in cn.cluster.topology.nodes if n.is_coordinator), None
    )


class TestAsymmetricPartition:
    def _setup(self, tc):
        """Wire node1's outbound to node2 through a proxy; manual-drive
        failure detectors (probe_once round-robin — no timing flake)."""
        n2 = tc[2].node.uri
        proxy = FaultProxy(n2.host, n2.port)
        rc = RewriteClient(
            {f"{n2.host}:{n2.port}": f"127.0.0.1:{proxy.port}"}, timeout=0.5
        )
        tc[1].cluster.client = rc
        tc[1].cluster.broadcaster.client = rc
        fds = [
            FailureDetector(cn.cluster, interval=999, confirm_down=3)
            for cn in tc.nodes
        ]
        return proxy, fds

    def _rounds(self, fds, k: int) -> None:
        for _ in range(k):
            for fd in fds:
                fd.probe_once()
            time.sleep(0.05)  # let async broadcasts land

    def test_one_sided_partition_no_flap_no_splitbrain(self):
        with TestCluster(3, replica_n=2) as tc:
            tc.create_index("i")
            tc.create_field("i", "f")
            cols = [s * SHARD_WIDTH + 5 for s in range(4)]
            tc.query(0, "i", " ".join(f"Set({c}, f=1)" for c in cols))
            proxy, fds = self._setup(tc)
            try:
                # Healthy: everyone READY after full probe rounds.
                self._rounds(fds, 2)
                for cn in tc.nodes:
                    assert set(_view(cn).values()) == {NODE_STATE_READY}

                # One-sided refuse: node1 -> node2 dies instantly while
                # node0<->node2 and node2 -> node1 stay healthy.
                proxy.mode = "refuse"
                self._rounds(fds, 4)  # past confirm_down=3
                assert _view(tc[1])["node2"] == NODE_STATE_DOWN
                # The observer's own cluster degrades (replica routing
                # takes over), but ONLY its view: healthy peers must
                # reject the uncorroborated DOWN claim.
                assert tc[1].cluster.state() == "DEGRADED"
                assert _view(tc[0])["node2"] == NODE_STATE_READY
                assert _view(tc[2])["node1"] == NODE_STATE_READY
                assert tc[0].cluster.state() == "NORMAL"

                # No flapping: across further rounds the views are
                # STABLE (node1 keeps its DOWN; peers keep READY).
                for _ in range(5):
                    self._rounds(fds, 1)
                    assert _view(tc[1])["node2"] == NODE_STATE_DOWN
                    assert _view(tc[0])["node2"] == NODE_STATE_READY
                    assert _view(tc[2])["node2"] == NODE_STATE_READY
                # No split-brain: node0 is the one coordinator in every
                # view, throughout.
                for cn in tc.nodes:
                    assert _coord(cn) == "node0"

                # Queries still answer everywhere (replica_n=2 routes
                # node1's scatter around the peer it cannot reach).
                for i in range(3):
                    out = tc.query(i, "i", "Count(Row(f=1))")
                    assert out["results"][0] == len(cols), i

                # Release: node1's next probe heals its view.
                proxy.mode = "pass"
                self._rounds(fds, 2)
                for cn in tc.nodes:
                    assert set(_view(cn).values()) == {NODE_STATE_READY}
                    assert _coord(cn) == "node0"
                assert tc[1].cluster.state() == "NORMAL"
            finally:
                proxy.close()

    def test_blackhole_partition_times_out_and_heals(self):
        """Blackhole (accept, never answer): the dialer pays its timeout
        instead of an instant error — same convergence, no flap."""
        with TestCluster(3, replica_n=2) as tc:
            proxy, fds = self._setup(tc)
            try:
                self._rounds(fds, 1)
                proxy.mode = "blackhole"
                self._rounds(fds, 4)
                assert _view(tc[1])["node2"] == NODE_STATE_DOWN
                assert _view(tc[0])["node2"] == NODE_STATE_READY
                for cn in tc.nodes:
                    assert _coord(cn) == "node0"
                proxy.mode = "pass"
                self._rounds(fds, 2)
                for cn in tc.nodes:
                    assert set(_view(cn).values()) == {NODE_STATE_READY}
            finally:
                proxy.close()

    def test_symmetric_down_still_converges_in_one_broadcast(self):
        """The corroboration gate must NOT slow real failures: when a
        node is dead to EVERYONE, a peer's disseminated DOWN lands on
        receivers whose own probes are failing too."""
        with TestCluster(3, replica_n=2) as tc:
            proxy, fds = self._setup(tc)
            proxy.close()  # not used here
            tc[2].server.close()  # node2 really dies
            try:
                # Each node probes once: everyone's counter starts
                # failing; then drive node1 to confirm_down.
                self._rounds(fds, 4)
                for i in (0, 1):
                    assert _view(tc[i])["node2"] == NODE_STATE_DOWN, i
            finally:
                pass
