"""Executor tests — the PQL op coverage mirrors the reference's
executor_test.go (every op, keyed variants, existence, GroupBy)."""

import numpy as np
import pytest

from pilosa_tpu.core import Holder
from pilosa_tpu.core.field import (
    options_for_bool,
    options_for_int,
    options_for_mutex,
    options_for_time,
)
from pilosa_tpu.core.index import IndexOptions
from pilosa_tpu.exec import Executor
from pilosa_tpu.exec.cpu import QueryError
from pilosa_tpu.shardwidth import SHARD_WIDTH


@pytest.fixture
def holder(tmp_path):
    h = Holder(str(tmp_path / "data")).open()
    yield h
    h.close()


@pytest.fixture
def ex(holder):
    return Executor(holder)


def setup_basic(ex):
    idx = ex.holder.create_index("i")
    idx.create_field("f")
    idx.create_field("g")
    ex.execute("i", "Set(10, f=1) Set(100, f=1) Set(10, g=2)")
    ex.execute("i", f"Set({SHARD_WIDTH * 2 + 7}, f=1)")  # shard 2
    return ex


class TestBitmapCalls:
    def test_row(self, ex):
        setup_basic(ex)
        (row,) = ex.execute("i", "Row(f=1)")
        assert row.columns().tolist() == [10, 100, SHARD_WIDTH * 2 + 7]

    def test_intersect_union_difference_xor(self, ex):
        setup_basic(ex)
        (r,) = ex.execute("i", "Intersect(Row(f=1), Row(g=2))")
        assert r.columns().tolist() == [10]
        (r,) = ex.execute("i", "Union(Row(f=1), Row(g=2))")
        assert r.columns().tolist() == [10, 100, SHARD_WIDTH * 2 + 7]
        (r,) = ex.execute("i", "Difference(Row(f=1), Row(g=2))")
        assert r.columns().tolist() == [100, SHARD_WIDTH * 2 + 7]
        (r,) = ex.execute("i", "Xor(Row(f=1), Row(g=2))")
        assert r.columns().tolist() == [100, SHARD_WIDTH * 2 + 7]

    def test_count(self, ex):
        setup_basic(ex)
        assert ex.execute("i", "Count(Row(f=1))") == [3]
        assert ex.execute("i", "Count(Intersect(Row(f=1), Row(g=2)))") == [1]

    def test_not_uses_existence(self, ex):
        setup_basic(ex)
        (r,) = ex.execute("i", "Not(Row(f=1))")
        # existence = {10, 100, shard2+7}; Not(f=1) = existence - row = {}
        assert r.columns().tolist() == []
        (r,) = ex.execute("i", "Not(Row(g=2))")
        assert r.columns().tolist() == [100, SHARD_WIDTH * 2 + 7]

    def test_not_without_existence_errors(self, holder):
        idx = holder.create_index("noex", IndexOptions(track_existence=False))
        idx.create_field("f")
        ex = Executor(holder)
        ex.execute("noex", "Set(1, f=1)")
        with pytest.raises(QueryError, match="existence"):
            ex.execute("noex", "Not(Row(f=1))")

    def test_all(self, ex):
        setup_basic(ex)
        (r,) = ex.execute("i", "All()")
        assert r.columns().tolist() == [10, 100, SHARD_WIDTH * 2 + 7]

    def test_shift(self, ex):
        setup_basic(ex)
        (r,) = ex.execute("i", "Shift(Row(g=2), n=1)")
        assert r.columns().tolist() == [11]

    def test_set_returns_changed(self, ex):
        ex.holder.create_index("i").create_field("f")
        assert ex.execute("i", "Set(1, f=1)") == [True]
        assert ex.execute("i", "Set(1, f=1)") == [False]

    def test_clear(self, ex):
        setup_basic(ex)
        assert ex.execute("i", "Clear(10, f=1)") == [True]
        assert ex.execute("i", "Clear(10, f=1)") == [False]
        (r,) = ex.execute("i", "Row(f=1)")
        assert r.columns().tolist() == [100, SHARD_WIDTH * 2 + 7]

    def test_clear_row(self, ex):
        setup_basic(ex)
        assert ex.execute("i", "ClearRow(f=1)") == [True]
        assert ex.execute("i", "Count(Row(f=1))") == [0]
        # g untouched
        assert ex.execute("i", "Count(Row(g=2))") == [1]

    def test_store(self, ex):
        setup_basic(ex)
        assert ex.execute("i", "Store(Row(f=1), stored=9)") == [True]
        (r,) = ex.execute("i", "Row(stored=9)")
        assert r.columns().tolist() == [10, 100, SHARD_WIDTH * 2 + 7]


class TestRowTimeRange:
    def test_range_query(self, holder):
        idx = holder.create_index("t")
        idx.create_field("f", options_for_time("YMDH"))
        ex = Executor(holder)
        ex.execute("t", 'Set(2, f=1, 2018-01-01T00:00)')
        ex.execute("t", 'Set(3, f=1, 2018-03-05T12:00)')
        ex.execute("t", 'Set(4, f=1, 2019-06-01T00:00)')
        (r,) = ex.execute("t", "Range(f=1, 2018-01-01T00:00, 2019-01-01T00:00)")
        assert r.columns().tolist() == [2, 3]
        (r,) = ex.execute("t", "Row(f=1, from=2018-03-01T00:00, to=2019-07-01T00:00)")
        assert r.columns().tolist() == [3, 4]
        # plain Row returns standard view (all)
        (r,) = ex.execute("t", "Row(f=1)")
        assert r.columns().tolist() == [2, 3, 4]


class TestBSI:
    def setup_bsi(self, holder):
        idx = holder.create_index("i")
        idx.create_field("v", options_for_int(-1000, 1000))
        idx.create_field("f")
        ex = Executor(holder)
        for col, val in [(1, 100), (2, -300), (3, 500), (4, 500), (5, 0)]:
            ex.execute("i", f"Set({col}, v={val})")
        return ex

    def test_sum_min_max(self, holder):
        ex = self.setup_bsi(holder)
        (vc,) = ex.execute("i", "Sum(field=v)")
        assert (vc.val, vc.count) == (800, 5)
        (vc,) = ex.execute("i", "Min(field=v)")
        assert (vc.val, vc.count) == (-300, 1)
        (vc,) = ex.execute("i", "Max(field=v)")
        assert (vc.val, vc.count) == (500, 2)

    def test_sum_with_filter(self, holder):
        ex = self.setup_bsi(holder)
        ex.execute("i", "Set(1, f=1) Set(3, f=1)")
        (vc,) = ex.execute("i", "Sum(Row(f=1), field=v)")
        assert (vc.val, vc.count) == (600, 2)

    def test_range_conditions(self, holder):
        ex = self.setup_bsi(holder)
        cases = [
            ("Row(v > 100)", [3, 4]),
            ("Row(v >= 100)", [1, 3, 4]),
            ("Row(v < 0)", [2]),
            ("Row(v <= 0)", [2, 5]),
            ("Row(v == 500)", [3, 4]),
            ("Row(v != 500)", [1, 2, 5]),
            ("Row(v >< [0, 200])", [1, 5]),
            ("Row(-300 <= v <= 100)", [1, 2, 5]),
            ("Row(v != null)", [1, 2, 3, 4, 5]),
        ]
        for q, want in cases:
            (r,) = ex.execute("i", q)
            assert r.columns().tolist() == want, q

    def test_out_of_range_conditions(self, holder):
        ex = self.setup_bsi(holder)
        (r,) = ex.execute("i", "Row(v > 100000)")
        assert r.columns().tolist() == []
        (r,) = ex.execute("i", "Row(v < 100000)")  # encompasses all -> notNull
        assert r.columns().tolist() == [1, 2, 3, 4, 5]


class TestTopN:
    def test_topn_basic(self, holder):
        idx = holder.create_index("i")
        idx.create_field("f")
        ex = Executor(holder)
        # row 1: 4 bits; row 2: 2 bits; row 3: 1 bit, spanning shards
        for col in [0, 1, 2, SHARD_WIDTH + 1]:
            ex.execute("i", f"Set({col}, f=1)")
        for col in [0, SHARD_WIDTH + 2]:
            ex.execute("i", f"Set({col}, f=2)")
        ex.execute("i", "Set(5, f=3)")
        (res,) = ex.execute("i", "TopN(f, n=2)")
        assert [(p.id, p.count) for p in res.pairs] == [(1, 4), (2, 2)]
        (res,) = ex.execute("i", "TopN(f)")
        assert [(p.id, p.count) for p in res.pairs] == [(1, 4), (2, 2), (3, 1)]

    def test_topn_with_src(self, holder):
        idx = holder.create_index("i")
        idx.create_field("f")
        idx.create_field("g")
        ex = Executor(holder)
        for col in [0, 1, 2]:
            ex.execute("i", f"Set({col}, f=1)")
        ex.execute("i", "Set(1, f=2)")
        ex.execute("i", "Set(0, g=9) Set(1, g=9)")
        (res,) = ex.execute("i", "TopN(f, Row(g=9), n=5)")
        assert [(p.id, p.count) for p in res.pairs] == [(1, 2), (2, 1)]


class TestRowsAndGroupBy:
    def setup_rows(self, holder):
        idx = holder.create_index("i")
        idx.create_field("a")
        idx.create_field("b")
        ex = Executor(holder)
        ex.execute("i", "Set(0, a=1) Set(1, a=1) Set(1, a=2) Set(2, a=3)")
        ex.execute("i", "Set(0, b=10) Set(1, b=10) Set(2, b=20)")
        return ex

    def test_rows(self, holder):
        ex = self.setup_rows(holder)
        assert list(ex.execute("i", "Rows(a)")[0]) == [1, 2, 3]
        assert list(ex.execute("i", "Rows(a, limit=2)")[0]) == [1, 2]
        assert list(ex.execute("i", "Rows(a, previous=1)")[0]) == [2, 3]
        assert list(ex.execute("i", "Rows(a, column=1)")[0]) == [1, 2]

    def test_group_by(self, holder):
        ex = self.setup_rows(holder)
        (res,) = ex.execute("i", "GroupBy(Rows(a), Rows(b))")
        got = [([fr.row_id for fr in gc.group], gc.count) for gc in res]
        assert got == [
            ([1, 10], 2),
            ([2, 10], 1),
            ([3, 20], 1),
        ]

    def test_group_by_filter(self, holder):
        ex = self.setup_rows(holder)
        (res,) = ex.execute("i", "GroupBy(Rows(a), filter=Row(b=10))")
        got = [([fr.row_id for fr in gc.group], gc.count) for gc in res]
        assert got == [([1], 2), ([2], 1)]

    def test_group_by_limit(self, holder):
        ex = self.setup_rows(holder)
        (res,) = ex.execute("i", "GroupBy(Rows(a), Rows(b), limit=2)")
        assert len(res) == 2


class TestMinMaxRow:
    def test_min_max_row(self, holder):
        holder.create_index("i").create_field("f")
        ex = Executor(holder)
        ex.execute("i", "Set(0, f=3) Set(1, f=7) Set(2, f=7)")
        (res,) = ex.execute("i", "MinRow(field=f)")
        assert (res.pair.id, res.pair.count) == (3, 1)
        (res,) = ex.execute("i", "MaxRow(field=f)")
        assert (res.pair.id, res.pair.count) == (7, 1)


class TestFieldTypes:
    def test_bool_field(self, holder):
        idx = holder.create_index("i")
        idx.create_field("b", options_for_bool())
        ex = Executor(holder)
        ex.execute("i", "Set(1, b=true) Set(2, b=false) Set(3, b=true)")
        (r,) = ex.execute("i", "Row(b=true)")
        assert r.columns().tolist() == [1, 3]
        (r,) = ex.execute("i", "Row(b=false)")
        assert r.columns().tolist() == [2]
        # flip
        ex.execute("i", "Set(1, b=false)")
        (r,) = ex.execute("i", "Row(b=true)")
        assert r.columns().tolist() == [3]

    def test_mutex_field(self, holder):
        idx = holder.create_index("i")
        idx.create_field("m", options_for_mutex())
        ex = Executor(holder)
        ex.execute("i", "Set(1, m=10) Set(1, m=20)")
        (r,) = ex.execute("i", "Row(m=10)")
        assert r.columns().tolist() == []
        (r,) = ex.execute("i", "Row(m=20)")
        assert r.columns().tolist() == [1]


class TestKeys:
    def test_keyed_index_and_field(self, holder):
        idx = holder.create_index("k", IndexOptions(keys=True))
        from pilosa_tpu.core.field import FieldOptions

        idx.create_field("f", FieldOptions(keys=True))
        ex = Executor(holder)
        ex.execute("k", 'Set("alpha", f="one") Set("beta", f="one")')
        (r,) = ex.execute("k", 'Row(f="one")')
        assert sorted(r.keys) == ["alpha", "beta"]
        (res,) = ex.execute("k", 'TopN(f, n=5)')
        assert [(p.key, p.count) for p in res.pairs] == [("one", 2)]

    def test_unkeyed_rejects_strings(self, holder):
        holder.create_index("u")
        ex = Executor(holder)
        with pytest.raises(QueryError, match="keys"):
            ex.execute("u", 'Set("alpha", f=1)')


class TestAttrs:
    def test_row_attrs(self, holder):
        holder.create_index("i").create_field("f")
        ex = Executor(holder)
        ex.execute("i", "Set(1, f=7)")
        ex.execute("i", 'SetRowAttrs(f, 7, color="blue", weight=3)')
        (r,) = ex.execute("i", "Row(f=7)")
        assert r.attrs == {"color": "blue", "weight": 3}

    def test_column_attrs(self, holder):
        idx = holder.create_index("i")
        ex = Executor(holder)
        ex.execute("i", 'SetColumnAttrs(9, happy=true)')
        assert idx.column_attr_store.attrs(9) == {"happy": True}


class TestOptions:
    def test_shards_option(self, ex):
        setup_basic(ex)
        (r,) = ex.execute("i", "Options(Row(f=1), shards=[0])")
        assert r.columns().tolist() == [10, 100]

    def test_exclude_row_attrs(self, ex):
        setup_basic(ex)
        ex.execute("i", 'SetRowAttrs(f, 1, x=1)')
        (r,) = ex.execute("i", "Options(Row(f=1), excludeRowAttrs=true)")
        assert r.attrs == {}


class TestMultiOps:
    def test_write_then_read_same_query(self, ex):
        ex.holder.create_index("i").create_field("f")
        results = ex.execute("i", "Set(1, f=1) Count(Row(f=1))")
        assert results == [True, 1]


class TestReviewRegressions:
    """Regression tests for review findings (cross-shard TopN recount,
    negative-predicate BSI routing, keyed Rows column, threaded stores,
    Shift identity)."""

    def test_topn_cross_shard_recount(self, holder):
        idx = holder.create_index("i")
        idx.create_field("t")
        ex = Executor(holder)
        # row 10: 10 bits all in shard 0; row 20: 6 + 6 across shards = 12.
        for col in range(10):
            ex.execute("i", f"Set({col}, t=10)")
        for col in range(6):
            ex.execute("i", f"Set({100 + col}, t=20)")
            ex.execute("i", f"Set({SHARD_WIDTH + col}, t=20)")
        (res,) = ex.execute("i", "TopN(t, n=1)")
        assert [(p.id, p.count) for p in res.pairs] == [(20, 12)]

    def test_bsi_negative_predicate_routing(self, holder):
        idx = holder.create_index("i")
        idx.create_field("v", options_for_int(-10, 10))
        ex = Executor(holder)
        for col, val in [(1, -2), (2, -1), (3, 0), (4, 1)]:
            ex.execute("i", f"Set({col}, v={val})")
        cases = [
            ("Row(v < 0)", [1, 2]),
            ("Row(v < -1)", [1]),
            ("Row(v <= -1)", [1, 2]),
            ("Row(v > -1)", [3, 4]),
            ("Row(v >= -1)", [2, 3, 4]),
            ("Row(v > -2)", [2, 3, 4]),
        ]
        for q, want in cases:
            (r,) = ex.execute("i", q)
            assert r.columns().tolist() == want, q

    def test_rows_column_keyed(self, holder):
        from pilosa_tpu.core.field import FieldOptions

        idx = holder.create_index("k", IndexOptions(keys=True))
        idx.create_field("f", FieldOptions(keys=True))
        ex = Executor(holder)
        ex.execute("k", 'Set("alice", f="red") Set("bob", f="blue")')
        (rows,) = ex.execute("k", 'Rows(f, column="alice")')
        assert len(rows) == 1

    def test_attr_store_cross_thread(self, holder):
        import threading

        idx = holder.create_index("i")
        idx.create_field("f")
        idx.fields["f"].row_attr_store.set_attrs(1, {"x": 1})
        seen = {}

        def reader():
            seen["attrs"] = idx.fields["f"].row_attr_store.attrs(1)

        t = threading.Thread(target=reader)
        t.start()
        t.join()
        assert seen["attrs"] == {"x": 1}

    def test_shift_identity_and_negative(self, ex):
        setup_basic(ex)
        (r,) = ex.execute("i", "Shift(Row(g=2))")
        assert r.columns().tolist() == [10]  # n missing -> unchanged
        (r,) = ex.execute("i", "Shift(Row(g=2), n=2)")
        assert r.columns().tolist() == [12]
        with pytest.raises(QueryError, match="negative"):
            ex.execute("i", "Shift(Row(g=2), n=-1)")

    def test_rows_result_keys_translated(self, holder):
        from pilosa_tpu.core.field import FieldOptions

        idx = holder.create_index("k2", IndexOptions(keys=True))
        idx.create_field("f", FieldOptions(keys=True))
        ex = Executor(holder)
        ex.execute("k2", 'Set("a", f="red") Set("b", f="blue")')
        (rows,) = ex.execute("k2", "Rows(f)")
        assert rows.to_json() == {"keys": ["red", "blue"]} or set(
            rows.to_json()["keys"]
        ) == {"red", "blue"}
