"""Round-2 cluster features: keyed translation via the coordinator primary,
anti-entropy repair, resize (grow/shrink/abort), failure detection, and
broadcast-loss recovery (reference translate.go:35, holder.go:882,
cluster.go:1196, gossip confirm-down)."""

import time

import numpy as np
import pytest

from pilosa_tpu.cluster.sync import FailureDetector, ForwardingTranslateStore, HolderSyncer
from pilosa_tpu.core.view import VIEW_STANDARD
from pilosa_tpu.shardwidth import SHARD_WIDTH
from tests.cluster_harness import TestCluster


def _frag(cn, index, field, shard):
    v = cn.holder.index(index).field(field).view(VIEW_STANDARD)
    return v.fragment(shard) if v is not None else None


class TestKeyedTranslation:
    def test_same_key_same_id_through_every_node(self):
        with TestCluster(3) as c:
            c.create_index("ki", {"keys": True})
            c.create_field("ki", "f", {"keys": True})
            # Writes through DIFFERENT nodes using the same keys.
            c.query(0, "ki", 'Set("alpha", f="x")')
            c.query(1, "ki", 'Set("beta", f="x")')
            c.query(2, "ki", 'Set("alpha", f="y")')
            # The same column key must resolve to one id everywhere.
            ids = set()
            for cn in c.nodes:
                store = cn.holder.index("ki").translate_store
                ids.add(store.translate_key("alpha", write=False))
            ids.discard(None)  # replicas that haven't pulled yet are allowed
            assert len(ids) == 1
            # Reads through every node see every write.
            for i in range(3):
                out = c.query(i, "ki", 'Row(f="x")')
                assert sorted(out["results"][0]["keys"]) == ["alpha", "beta"], i
                out = c.query(i, "ki", 'Row(f="y")')
                assert out["results"][0]["keys"] == ["alpha"], i

    def test_forwarding_store_wraps_all_keyed_stores(self):
        with TestCluster(2) as c:
            c.create_index("ki", {"keys": True})
            c.create_field("ki", "f", {"keys": True})
            for cn in c.nodes:
                idx = cn.holder.index("ki")
                assert isinstance(idx.translate_store, ForwardingTranslateStore)
                assert isinstance(idx.field("f").translate_store, ForwardingTranslateStore)

    def test_replica_tail_converges_without_reads(self):
        with TestCluster(2) as c:
            c.create_index("ki", {"keys": True})
            c.create_field("ki", "f", {})
            coord = next(cn for cn in c.nodes if cn.cluster.is_coordinator())
            other = next(cn for cn in c.nodes if not cn.cluster.is_coordinator())
            # ids assigned on the coordinator only
            coord.holder.index("ki").translate_store.translate_key("k1")
            coord.holder.index("ki").translate_store.translate_key("k2")
            assert other.holder.index("ki").translate_store.local.max_id() == 0
            c.sync_all()  # daemon pass tails the primary log
            assert other.holder.index("ki").translate_store.local.max_id() == 2


class TestAntiEntropy:
    def test_diverged_replicas_converge(self):
        with TestCluster(2, replica_n=2) as c:
            c.create_index("i")
            c.create_field("i", "f")
            c.query(0, "i", "Set(1, f=3) Set(100, f=3)")
            # Divergence: write behind the cluster's back on node0 only.
            _frag(c.nodes[0], "i", "f", 0).set_bit(3, 777)
            assert _frag(c.nodes[1], "i", "f", 0).row_count(3) == 2
            repaired = c.sync_all()
            assert repaired > 0
            # Both replicas now agree, divergent bit visible from both.
            for i in (0, 1):
                assert c.query(i, "i", "Row(f=3)")["results"][0]["columns"] == [1, 100, 777]
                assert _frag(c.nodes[i], "i", "f", 0).row_count(3) == 3
            b0 = _frag(c.nodes[0], "i", "f", 0).checksum_blocks()
            b1 = _frag(c.nodes[1], "i", "f", 0).checksum_blocks()
            assert b0 == b1

    def test_attr_stores_converge(self):
        with TestCluster(2, replica_n=2) as c:
            c.create_index("i")
            c.create_field("i", "f")
            # Attr written on node0's store only (bypassing fan-out).
            c.nodes[0].holder.index("i").field("f").row_attr_store.set_attrs(
                5, {"name": "five"}
            )
            c.sync_all()
            assert c.nodes[1].holder.index("i").field("f").row_attr_store.attrs(5) == {
                "name": "five"
            }

    def test_missed_shard_broadcast_repaired(self):
        with TestCluster(2, replica_n=2) as c:
            c.create_index("i")
            c.create_field("i", "f")
            # Simulate a missed CREATE_SHARD: set bits on node0's fragment
            # directly, shard never announced.
            f0 = c.nodes[0].holder.index("i").field("f")
            f0.create_view_if_not_exists(VIEW_STANDARD).create_fragment_if_not_exists(2)
            _frag(c.nodes[0], "i", "f", 2).set_bit(1, 2 * SHARD_WIDTH + 5)
            f0.add_available_shard(2)
            c.sync_all()
            f1 = c.nodes[1].holder.index("i").field("f")
            assert 2 in f1.available_shards().to_array().tolist()
            assert _frag(c.nodes[1], "i", "f", 2).row_count(1) == 1


class TestResize:
    def _populate(self, c, n_shards=8, row=1):
        c.create_index("i")
        c.create_field("i", "f")
        cols = list(range(0, n_shards * SHARD_WIDTH, SHARD_WIDTH // 2))
        c.nodes[0].api.import_bits(
            "i", "f", [row] * len(cols), cols
        )
        return len(cols)

    def test_add_node(self):
        with TestCluster(2) as c:
            n_bits = self._populate(c)
            want = c.query(0, "i", "Count(Row(f=1))")["results"][0]
            assert want == n_bits
            cn = c.add_node_via_resize()
            # All three nodes (incl. the joiner) answer correctly.
            for i in range(3):
                got = c.query(i, "i", "Count(Row(f=1))")["results"][0]
                assert got == want, i
            # The joiner received the fragments it now owns.
            topo = cn.cluster.topology
            owned = [
                s
                for s in range(8)
                if topo.owns_shard(cn.node.id, "i", s)
            ]
            have = [s for s in range(8) if _frag(cn, "i", "f", s) is not None]
            assert set(owned) <= set(have)
            # Old nodes dropped what they no longer own (holder cleaner).
            for old in c.nodes[:2]:
                for s in range(8):
                    if _frag(old, "i", "f", s) is not None:
                        assert topo.owns_shard(old.node.id, "i", s) or s == 0

    def test_remove_node(self):
        with TestCluster(3) as c:
            n_bits = self._populate(c)
            want = c.query(0, "i", "Count(Row(f=1))")["results"][0]
            victim = next(cn for cn in c.nodes[1:] if not cn.cluster.is_coordinator())
            c.nodes[0].cluster.resizer.remove_node(victim.node.id)
            deadline = time.time() + 10
            rest = [cn for cn in c.nodes if cn is not victim]
            while time.time() < deadline:
                if all(
                    len(cn.cluster.topology.nodes) == 2 and cn.cluster.state() == "NORMAL"
                    for cn in rest
                ):
                    break
                time.sleep(0.02)
            else:
                raise TimeoutError("remove never converged")
            for cn in rest:
                got = cn.api.query("i", "Count(Row(f=1))")["results"][0]
                assert got == want
            # The removed node flipped back to NORMAL and kept its data.
            assert victim.cluster.state() == "NORMAL"

    def test_abort_resets_state(self):
        with TestCluster(2) as c:
            c.nodes[0].cluster.set_state("RESIZING")
            c.nodes[1].cluster.set_state("RESIZING")
            c.nodes[0].cluster.resizer.abort()
            time.sleep(0.2)
            assert c.nodes[0].cluster.state() == "NORMAL"
            assert c.nodes[1].cluster.state() == "NORMAL"

    def test_add_existing_node_rejected(self):
        from pilosa_tpu.cluster.resize import ResizeError

        with TestCluster(2) as c:
            with pytest.raises(ResizeError):
                c.nodes[0].cluster.resizer.add_node(c.nodes[1].node)

    def test_stale_complete_ignored(self):
        """A MSG_RESIZE_COMPLETE carrying an old job id must not satisfy a
        later job's pending set (ADVICE r2: premature NORMAL flip routes
        queries to nodes missing data)."""
        from pilosa_tpu.cluster.broadcast import MSG_RESIZE_COMPLETE, Message

        with TestCluster(2) as c:
            rz = c.nodes[0].cluster.resizer
            rz._active_job = 7
            rz._pending_nodes = {"node0", "node1"}
            rz._new_nodes = list(c.nodes[0].cluster.topology.nodes)
            rz._notify_nodes = []
            # Stale completes (old job / aborted job) are ignored.
            rz.mark_complete(Message.make(MSG_RESIZE_COMPLETE, job=6, node="node0"))
            rz.mark_complete(Message.make(MSG_RESIZE_COMPLETE, job=None, node="node1"))
            assert rz._pending_nodes == {"node0", "node1"}
            # Matching completes drain the set and finish the job.
            rz.mark_complete(Message.make(MSG_RESIZE_COMPLETE, job=7, node="node0"))
            rz.mark_complete(Message.make(MSG_RESIZE_COMPLETE, job=7, node="node1"))
            assert rz._new_nodes is None and rz._active_job is None

    def test_failed_follow_still_completes(self):
        """A node whose instruction-following blows up mid-fetch must still
        report completion (with error) so the cluster leaves RESIZING
        (ADVICE r2: bare daemon thread death wedged the cluster)."""
        with TestCluster(2) as c:
            self._populate(c)
            cn = c.spawn_node()
            # Sabotage the joiner: schema application explodes.
            def boom(schema):
                raise RuntimeError("injected schema failure")

            cn.api.apply_schema = boom
            c.nodes[0].cluster.resizer.add_node(
                type(cn.node)(cn.node.id, cn.node.uri, False)
            )
            deadline = time.time() + 10
            while time.time() < deadline:
                if all(x.cluster.state() == "NORMAL" for x in c.nodes):
                    break
                time.sleep(0.02)
            else:
                states = [(x.node.id, x.cluster.state()) for x in c.nodes]
                raise TimeoutError(f"cluster wedged in RESIZING: {states}")

    def test_unreachable_node_aborts_job(self):
        """Instruction delivery failure rolls the cluster back to NORMAL
        instead of freezing writes forever."""
        from pilosa_tpu.cluster.resize import ResizeError
        from pilosa_tpu.cluster.topology import Node, URI

        with TestCluster(2) as c:
            dead = Node("ghost", URI(scheme="http", host="127.0.0.1", port=1), False)
            with pytest.raises(ResizeError):
                c.nodes[0].cluster.resizer.add_node(dead)
            time.sleep(0.2)
            assert c.nodes[0].cluster.state() == "NORMAL"
            assert c.nodes[1].cluster.state() == "NORMAL"
            # The failed job must not block a later, healthy one.
            cn = c.add_node_via_resize()
            assert len(cn.cluster.topology.nodes) == 3

    def test_job_timeout_auto_aborts(self):
        """A job whose completions never arrive aborts itself."""
        with TestCluster(2) as c:
            rz = c.nodes[0].cluster.resizer
            rz.job_timeout = 0.3
            cn = c.spawn_node()
            # Deliver instructions into the void: the joiner never acts.
            orig_send = c.nodes[0].cluster.broadcaster.send_to
            from pilosa_tpu.cluster import broadcast as bc

            def drop_instructions(node, msg):
                if msg.get("type") == bc.MSG_RESIZE_INSTRUCTION:
                    return  # "delivered", never followed
                return orig_send(node, msg)

            c.nodes[0].cluster.broadcaster.send_to = drop_instructions
            rz.add_node(type(cn.node)(cn.node.id, cn.node.uri, False))
            assert c.nodes[0].cluster.state() == "RESIZING"
            deadline = time.time() + 5
            while time.time() < deadline:
                if c.nodes[0].cluster.state() == "NORMAL" and rz._active_job is None:
                    break
                time.sleep(0.02)
            else:
                raise TimeoutError("job timeout never fired")


class TestFailureDetection:
    def test_down_node_marked_and_queries_survive(self):
        with TestCluster(2, replica_n=2) as c:
            c.create_index("i")
            c.create_field("i", "f")
            c.query(0, "i", "Set(1, f=1) Set(2, f=1)")
            c.nodes[1].server.close()
            det = FailureDetector(c.nodes[0].cluster, confirm_down=2)
            det.probe_once()
            peer = c.nodes[0].cluster.topology.node_by_id(c.nodes[1].node.id)
            assert peer.state == "READY"  # one strike isn't down yet
            det.probe_once()
            assert peer.state == "DOWN"
            assert c.nodes[0].cluster.state() == "DEGRADED"
            # Queries skip the dead node proactively (no timeout path).
            out = c.query(0, "i", "Count(Row(f=1))")
            assert out["results"][0] == 2

    def test_vote_down_counters_lose_no_increments_under_contention(self):
        """Regression for the shared-state finding fixed in ISSUE r13:
        the probe loop's increments race the message handler's
        vote_down RMWs on the same key; `_fails_lock` now serializes
        them, so N concurrent votes land as exactly N increments (a
        lost one used to delay a legitimate DOWN by a probe sweep)."""
        import threading

        with TestCluster(2) as c:
            det = FailureDetector(c.nodes[0].cluster, confirm_down=10_000)
            nid = c.nodes[1].node.id
            with det._fails_lock:
                det._fails[nid] = 1  # "we are failing it too"
            n_threads, per_thread = 8, 200
            barrier = threading.Barrier(n_threads)

            def vote():
                barrier.wait()
                for _ in range(per_thread):
                    det.vote_down(nid)

            threads = [
                threading.Thread(target=vote) for _ in range(n_threads)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=30)
            assert det._fails[nid] == 1 + n_threads * per_thread


class TestBroadcastRecovery:
    def test_ddl_broadcast_queued_and_flushed(self):
        with TestCluster(2) as c:
            port = c.nodes[1].server.port
            c.nodes[1].server.close()
            c.create_index("late")  # broadcast fails -> queued
            assert c.nodes[0].cluster._pending_msgs
            # Peer comes back on the same port; flush delivers the DDL.
            from pilosa_tpu.server.http import Server

            c.nodes[1].server = Server(c.nodes[1].api, host="127.0.0.1", port=port).open()
            c.nodes[0].cluster.flush_pending_broadcasts()
            assert not c.nodes[0].cluster._pending_msgs
            assert c.nodes[1].holder.index("late") is not None

    def test_remote_exec_pushes_schema_on_not_found(self):
        with TestCluster(2) as c:
            # Schema created on node0's holder only — node1 missed the DDL
            # (the ADVICE r1 scenario: peer unreachable during broadcast).
            idx = c.nodes[0].holder.create_index("i")
            f = idx.create_field("f")
            topo = c.nodes[0].cluster.topology
            # Data lands only in node0-owned shards (writes to node1 would
            # have failed while it lacked the schema).
            cols = [
                s * SHARD_WIDTH + 7
                for s in range(8)
                if topo.owns_shard(c.nodes[0].node.id, "i", s)
            ]
            remote_shards = [
                s for s in range(8) if topo.owns_shard(c.nodes[1].node.id, "i", s)
            ]
            assert cols and remote_shards, "placement degenerate; widen range"
            f.import_bits(np.full(len(cols), 1, dtype=np.uint64),
                          np.array(cols, dtype=np.uint64))
            for s in remote_shards:
                f.add_available_shard(s)  # cluster-wide set includes them
            # Query through node0: node1's shards answer "index not found",
            # node0 pushes the schema and retries instead of failing.
            out = c.query(0, "i", "Count(Row(f=1))")
            assert out["results"][0] == len(cols)
            assert c.nodes[1].holder.index("i") is not None
            assert c.nodes[1].holder.index("i").field("f") is not None


class TestDistributedPlumbing:
    """Round-3 half-wired plumbing (VERDICT r2 #5): trace linkage across
    nodes, single-RPC bulk key translation, DOWN-state dissemination."""

    def test_cross_node_trace_linkage(self):
        from pilosa_tpu.utils.tracing import global_tracer

        with TestCluster(2) as c:
            c.create_index("i")
            c.create_field("i", "f")
            c.query(0, "i", "Set(1, f=1)")
            with global_tracer.start_span("test.root") as root:
                # Direct peer RPC: the client must inject root's context.
                c.nodes[0].cluster.client.query_node(
                    c.nodes[1].node, "i", "Count(Row(f=1))", remote=True
                )
            # The peer's handler/executor spans must join root's trace
            # (the handler span finishes a beat after the response, so
            # poll briefly).
            linked = []
            for _ in range(50):
                linked = [
                    s
                    for s in global_tracer.recent(300)
                    if s["traceID"] == root.trace_id
                    and s["name"] != "test.root"
                    and s["parentID"] is not None
                ]
                if any(s["name"].startswith("http.") for s in linked):
                    break
                time.sleep(0.02)
            assert linked, "peer spans not linked to the caller's trace"
            assert any(s["name"].startswith("http.") for s in linked)
            assert any(s["name"].startswith("executor.") for s in linked)

    def test_bulk_translate_keys_is_one_rpc(self):
        with TestCluster(2) as c:
            c.create_index("ki", {"keys": True})
            non_coord = next(
                cn for cn in c.nodes if not cn.cluster.is_coordinator()
            )
            store = non_coord.holder.index("ki").translate_store
            client = non_coord.cluster.client
            calls = {"translate_keys": 0, "translate_data": 0}
            orig_tk, orig_td = client.translate_keys, client.translate_data

            def tk(*a, **k):
                calls["translate_keys"] += 1
                return orig_tk(*a, **k)

            def td(*a, **k):
                calls["translate_data"] += 1
                return orig_td(*a, **k)

            client.translate_keys, client.translate_data = tk, td
            keys = [f"user{n}" for n in range(10_000)]
            ids = store.translate_keys(keys)
            assert calls["translate_keys"] == 1
            assert calls["translate_data"] <= 2
            assert len(set(ids)) == len(keys)
            # Local replica now serves every key without an RPC.
            calls["translate_keys"] = 0
            again = store.translate_keys(keys)
            assert again == ids and calls["translate_keys"] == 0

    def test_down_state_disseminates(self):
        with TestCluster(3) as c:
            c.create_index("i")
            c.nodes[2].server.close()
            # Only node0 probes; node1 must learn DOWN via the broadcast.
            det = FailureDetector(c.nodes[0].cluster, confirm_down=1)
            det.probe_once()
            dead_id = c.nodes[2].node.id
            assert (
                c.nodes[0].cluster.topology.node_by_id(dead_id).state == "DOWN"
            )
            # Dissemination is async (fire-and-forget broadcast threads).
            peer_view = c.nodes[1].cluster.topology.node_by_id(dead_id)
            for _ in range(100):
                if peer_view.state == "DOWN":
                    break
                time.sleep(0.02)
            assert peer_view.state == "DOWN", (
                "peer did not learn DOWN from the broadcast"
            )


class TestDynamicJoin:
    """VERDICT r2 #6: a node announces itself and joins a live cluster —
    no operator resize call (reference gossip join -> listenForJoins
    cluster.go:1063-1141)."""

    def test_node_joins_without_operator_call(self):
        with TestCluster(2) as c:
            c.create_index("i")
            c.create_field("i", "f")
            for s in range(6):
                c.query(0, "i", f"Set({s * SHARD_WIDTH + 3}, f=1)")
            cn = c.spawn_node()
            assert cn.cluster.join_cluster(c.nodes[0].node, timeout=30)
            # Topology converged on every node, including the joiner.
            for node in c.nodes:
                assert len(node.cluster.topology.nodes) == 3, node.node.id
                assert node.cluster.state() == "NORMAL"
            # The joiner serves correct results (its fragments arrived).
            out = c.query(len(c.nodes) - 1, "i", "Count(Row(f=1))")
            assert out["results"][0] == 6

    def test_join_ships_node_status_to_coordinator(self):
        with TestCluster(2) as c:
            cn = c.spawn_node()
            # The joiner arrives with pre-existing schema + data.
            idx = cn.holder.create_index("pre")
            f = idx.create_field("pf")
            f.import_bits(np.array([1], dtype=np.uint64),
                          np.array([5], dtype=np.uint64))
            assert cn.cluster.join_cluster(c.nodes[0].node, timeout=30)
            # Coordinator merged the joiner's NodeStatus before resizing.
            pre = c.nodes[0].holder.index("pre")
            assert pre is not None and pre.field("pf") is not None
            assert 0 in pre.field("pf").available_shards().to_array().tolist()


class TestClusteredGroupByWindow:
    def test_offset_limit_applied_once_at_coordinator(self):
        """Remote partials must return untrimmed (capped) group lists;
        the window applies exactly once at the coordinator (r3 review:
        double-trim dropped early groups' cross-node counts)."""
        with TestCluster(2) as c:
            c.create_index("i")
            c.create_field("i", "a")
            c.create_field("i", "b")
            # Groups spread across shards owned by BOTH nodes.
            for s in range(6):
                base = s * SHARD_WIDTH
                c.query(0, "i", f"Set({base+1}, a=1) Set({base+1}, b=10)")
                c.query(0, "i", f"Set({base+2}, a=2) Set({base+2}, b=10)")
                c.query(0, "i", f"Set({base+3}, a=3) Set({base+3}, b=20)")
            from pilosa_tpu.exec.result import result_to_json

            full = result_to_json(
                c.query(0, "i", "GroupBy(Rows(a), Rows(b))")["results"][0]
            )
            assert [g["count"] for g in full] == [6, 6, 6]
            for off in (0, 1, 2):
                for lim in (1, 2, 3):
                    got = result_to_json(
                        c.query(
                            0, "i",
                            f"GroupBy(Rows(a), Rows(b), limit={lim}, offset={off})",
                        )["results"][0]
                    )
                    assert got == full[off : off + lim], (off, lim)

    def test_write_fails_when_all_replicas_down(self):
        with TestCluster(2) as c:  # replica_n=1
            c.create_index("i")
            c.create_field("i", "f")
            topo = c.nodes[0].cluster.topology
            other = c.nodes[1].node.id
            shard = next(
                s for s in range(32) if topo.owns_shard(other, "i", s)
            )
            topo.node_by_id(other).state = "DOWN"
            with pytest.raises(Exception) as ei:
                c.query(0, "i", f"Set({shard * SHARD_WIDTH + 1}, f=1)")
            assert "down" in str(ei.value)


class TestMeshPerNodeCluster:
    """The full distributed model: each node evaluates its shards on its
    OWN device mesh (ICI psum within a node), with cross-node
    scatter-gather over HTTP (the DCN plane) — 2 'hosts' x 4 virtual
    devices here (SURVEY §5: intra-pod collectives + inter-host RPC)."""

    def test_cluster_queries_on_per_node_meshes(self):
        import jax

        from pilosa_tpu.exec.tpu import TPUBackend
        from pilosa_tpu.parallel import ShardMesh

        devices = jax.devices()
        assert len(devices) >= 8

        def factory(i, holder):
            sub = devices[i * 4 : (i + 1) * 4]
            return TPUBackend(holder, mesh=ShardMesh(sub))

        with TestCluster(2, backend_factory=factory) as c:
            c.create_index("i")
            c.create_field("i", "f")
            c.create_field("i", "g")
            cols = []
            for s in range(8):
                base = s * SHARD_WIDTH
                c.query(0, "i", f"Set({base + 1}, f=1) Set({base + 2}, f=1)")
                c.query(1, "i", f"Set({base + 1}, g=2)")
                cols.append(base + 1)
            for node in (0, 1):
                out = c.query(node, "i", "Count(Row(f=1))")
                assert out["results"][0] == 16, node
                out = c.query(node, "i", "Count(Intersect(Row(f=1), Row(g=2)))")
                assert out["results"][0] == 8, node
                out = c.query(node, "i", "TopN(f, n=1)")
                top = out["results"][0]
                pairs = top.pairs if hasattr(top, "pairs") else top
                first = pairs[0]
                pid = first.id if hasattr(first, "id") else first["id"]
                pcount = first.count if hasattr(first, "count") else first["count"]
                assert (pid, pcount) == (1, 16), node
            # Multi-Count requests ride each node's batched path.
            out = c.query(0, "i", "Count(Row(f=1))Count(Row(g=2))Count(Xor(Row(f=1), Row(g=2)))")
            assert out["results"] == [16, 8, 8]

    def test_anti_entropy_heals_device_results(self):
        """Mesh-backend nodes must serve HEALED data after anti-entropy:
        repair writes go through fragment mutators, so view generations
        bump and the device stack caches refresh."""
        import jax

        from pilosa_tpu.exec.tpu import TPUBackend
        from pilosa_tpu.parallel import ShardMesh

        devices = jax.devices()
        assert len(devices) >= 8

        def factory(i, holder):
            return TPUBackend(holder, mesh=ShardMesh(devices[i * 4 : (i + 1) * 4]))

        with TestCluster(2, replica_n=2, backend_factory=factory) as c:
            c.create_index("i")
            c.create_field("i", "f")
            c.query(0, "i", "Set(1, f=3) Set(100, f=3)")
            # Prime both nodes' device caches.
            for node in (0, 1):
                assert c.query(node, "i", "Count(Row(f=3))")["results"][0] == 2
            # Diverge node0's replica behind the cluster's back.
            v = c.nodes[0].holder.index("i").field("f").view("standard")
            v.fragment(0).set_bit(3, 777)
            c.sync_all()
            # Device-backed queries on BOTH nodes see the healed bit.
            for node in (0, 1):
                out = c.query(node, "i", "Row(f=3)")
                assert out["results"][0]["columns"] == [1, 100, 777], node
                assert c.query(node, "i", "Count(Row(f=3))")["results"][0] == 3


class TestCoordinatorFailover:
    """VERDICT r3 #5: membership must survive the coordinator."""

    def test_successor_promotes_and_join_still_works(self):
        with TestCluster(3, replica_n=3) as c:
            c.create_index("i")
            c.create_field("i", "f")
            c.query(0, "i", "Set(1, f=1) Set(2, f=1)")
            assert c.nodes[0].cluster.is_coordinator()
            # Kill the coordinator's server.
            c.nodes[0].server.close()
            det1 = FailureDetector(c.nodes[1].cluster, confirm_down=1)
            det2 = FailureDetector(c.nodes[2].cluster, confirm_down=1)
            det1.probe_once()  # marks node0 DOWN; node1 (lowest READY) promotes
            assert c.nodes[1].cluster.is_coordinator()
            assert c.nodes[1].cluster.coordinator().id == "node1"
            # node2 adopts via the piggybacked view merge on its own probe
            # (the promotion broadcast is async; the merge alone suffices).
            det2.probe_once()
            det2.probe_once()
            assert c.nodes[2].cluster.coordinator().id == "node1"
            assert not c.nodes[2].cluster.local_node.is_coordinator
            # A NEW node can still join: the grow job runs on the promoted
            # coordinator and must not wait on (or fail-fast to) the dead
            # old coordinator.
            cn = c.spawn_node()
            ok = cn.cluster.join_cluster(c.nodes[1].node.uri, timeout=10.0)
            assert ok
            assert any(n.id == cn.node.id for n in c.nodes[1].cluster.topology.nodes)
            # Queries on the survivors still answer.
            out = c.query(1, "i", "Count(Row(f=1))")
            assert out["results"][0] == 2

    def test_returning_old_coordinator_demoted(self):
        with TestCluster(2, replica_n=2) as c:
            port = c.nodes[0].server.port
            c.nodes[0].server.close()
            det = FailureDetector(c.nodes[1].cluster, confirm_down=1)
            det.probe_once()
            assert c.nodes[1].cluster.is_coordinator()
            # Old coordinator comes back on its old port, still believing
            # it leads; the promoted coordinator's next probe re-asserts.
            from pilosa_tpu.server.http import Server

            c.nodes[0].server = Server(
                c.nodes[0].api, host="127.0.0.1", port=port
            ).open()
            # (node0 may still believe it leads, or the promotion
            # broadcast may already have caught it — either way the
            # probe's heal path must leave it demoted.)
            det.probe_once()  # node1 sees it READY again and re-asserts
            assert not c.nodes[0].cluster.is_coordinator()
            assert c.nodes[0].cluster.coordinator().id == "node1"

    def test_manual_set_coordinator_endpoint(self):
        with TestCluster(2) as c:
            out = c.nodes[0].api.set_coordinator("node1")
            assert out["coordinator"] == "node1"
            assert c.nodes[1].cluster.local_node.is_coordinator or any(
                n.id == "node1" and n.is_coordinator
                for n in c.nodes[0].cluster.topology.nodes
            )
