"""Query-lifecycle telemetry tests (ISSUE r6): per-phase attribution,
/debug/queries + /debug/vars, the freshness-walk counters' O(dirty)
invariant, the slow-query log, and bench.py's capture-proof retry."""

import json
import urllib.error
import urllib.request

import numpy as np
import pytest

from pilosa_tpu.core import Holder
from pilosa_tpu.exec import Executor
from pilosa_tpu.server.api import API
from pilosa_tpu.server.http import Server, _HTTPServer
from pilosa_tpu.utils.qprofile import (
    QueryProfile,
    current_profile,
    global_query_ring,
    profile_scope,
)
from pilosa_tpu.utils.stats import global_stats


def counter_sum(prefix: str) -> float:
    """Sum of every counter series whose name starts with prefix (series
    names carry tags, e.g. version_walk_total{kind="full",tier="sum"})."""
    snap = global_stats.snapshot()
    return sum(v for k, v in snap["counters"].items() if k.startswith(prefix))


@pytest.fixture
def server(tmp_path):
    holder = Holder(str(tmp_path / "data")).open()
    srv = Server(API(holder, Executor(holder)), host="localhost", port=0).open()
    yield srv
    srv.close()
    holder.close()


def req(srv, method, path, body=None, ctype="text/plain"):
    data = None
    if body is not None:
        data = body if isinstance(body, bytes) else body.encode()
    r = urllib.request.Request(
        srv.uri + path, data=data, method=method,
        headers={"Content-Type": ctype},
    )
    return json.loads(urllib.request.urlopen(r).read())


class TestQueryProfile:
    def test_phases_accumulate_and_nest(self):
        with profile_scope(index="i", query="Count(Row(f=1))") as outer:
            outer.add_phase("parse", 0.001)
            # A nested scope must reuse the outer profile.
            with profile_scope(index="other") as inner:
                assert inner is outer
                inner.add_phase("parse", 0.002)
                inner.incr("version_walk_full", 3)
            assert current_profile() is outer
        assert current_profile().__class__.__name__ == "NopProfile"
        assert outer.phases["parse"] == pytest.approx(0.003)
        assert outer.counters == {"version_walk_full": 3}
        assert outer.duration is not None

    def test_ring_records_and_histograms_export(self):
        with profile_scope(index="i", query="q", call="Count") as prof:
            prof.add_phase("host_reduce", 0.004)
        recent = global_query_ring.recent(5)
        assert recent and recent[0]["qid"] == prof.qid
        assert recent[0]["phasesMs"]["host_reduce"] == pytest.approx(4.0)
        assert recent[0]["inFlight"] is False
        snap = global_stats.snapshot()
        key = 'query_phase_seconds{call="Count",phase="host_reduce"}'
        assert key in snap["timings"]
        assert snap["timings"][key]["count"] >= 1

    def test_error_recorded(self):
        with pytest.raises(ValueError):
            with profile_scope(index="i", query="boom") as prof:
                raise ValueError("the failure")
        assert "the failure" in prof.error
        assert any(
            r["qid"] == prof.qid and "error" in r
            for r in global_query_ring.recent(10)
        )

    def test_unattributed_never_negative(self):
        p = QueryProfile()
        p.add_phase("parse", 99.0)  # more than the real elapsed time
        p.finish()
        assert p.unattributed() == 0.0


class TestDebugEndpoints:
    def test_debug_queries_live_data(self, server):
        req(server, "POST", "/index/i", b"{}", ctype="application/json")
        req(server, "POST", "/index/i/field/f", b"{}", ctype="application/json")
        req(server, "POST", "/index/i/query", "Set(10, f=1)")
        out = req(server, "POST", "/index/i/query", "Count(Row(f=1))")
        assert out == {"results": [1]}
        # The Count's profile enters `recent` when its scope exits,
        # which happens AFTER the reply bytes reached this in-process
        # client — one GIL slice later. quiesce() is the server's
        # finalization barrier for exactly that window (ISSUE r13;
        # this used to be an ad-hoc poll loop).
        assert server.quiesce(timeout=5.0)
        dbg = req(server, "GET", "/debug/queries?n=10")
        assert "inflight" in dbg and "recent" in dbg
        counts = [
            r for r in dbg["recent"]
            if r["call"] == "Count" and r["query"].startswith("Count(")
        ]
        assert counts, dbg["recent"]
        entry = counts[0]
        assert entry["index"] == "i"
        assert entry["query"].startswith("Count(")
        # The serving path must attribute real phases end to end.
        assert "parse" in entry["phasesMs"]
        assert "serialize" in entry["phasesMs"]
        assert entry["elapsedMs"] > 0

    def test_phase_histograms_on_metrics(self, server):
        req(server, "POST", "/index/i", b"{}", ctype="application/json")
        req(server, "POST", "/index/i/field/f", b"{}", ctype="application/json")
        req(server, "POST", "/index/i/query", "Count(Row(f=1))")
        text = urllib.request.urlopen(server.uri + "/metrics").read().decode()
        assert 'pilosa_query_phase_seconds_count{call="Count",phase="parse"}' in text
        assert 'phase="serialize"' in text

    def test_debug_vars_live_data(self, server):
        req(server, "GET", "/version")
        out = req(server, "GET", "/debug/vars")
        assert out["version"]
        assert out["uptimeSeconds"] >= 0
        assert any(
            k.startswith("http_requests_total") for k in out["counters"]
        ), list(out["counters"])[:5]
        # Timing series carry the monotonic count/sum pair.
        t = [k for k in out["timings"] if k.startswith("http_request_duration_seconds")]
        assert t and out["timings"][t[0]]["count"] >= 1

    def test_connection_abort_counted(self, server):
        """A handler hitting a client reset mid-response must count the
        abort instead of 500ing (VERDICT r5 #1c). Injected by making one
        route raise ConnectionResetError — the deterministic equivalent
        of the client vanishing between headers and body write."""
        handler_cls = server._httpd.RequestHandlerClass

        def aborting(self):
            raise ConnectionResetError("client went away")

        import http.client

        before = counter_sum("http_connection_aborts_total")
        handler_cls.handle_home = aborting
        try:
            # The server sends nothing back, so the client sees the
            # connection die (RemoteDisconnected / reset, depending on
            # how urllib surfaces it).
            with pytest.raises(
                (urllib.error.URLError, OSError, http.client.HTTPException)
            ):
                urllib.request.urlopen(server.uri + "/", timeout=5)
        finally:
            del handler_cls.handle_home
        assert counter_sum("http_connection_aborts_total") == before + 1

    def test_request_queue_size_raised(self, server):
        # The bench's 16 clients + writer overflowed the default 5-deep
        # listen backlog (the BENCH_r05 reset); 128 is the floor now.
        assert _HTTPServer.request_queue_size >= 128
        assert isinstance(server._httpd, _HTTPServer)


class TestSlowQueryLog:
    def test_fires_with_phase_breakdown(self, tmp_path):
        holder = Holder(str(tmp_path / "data")).open()
        try:
            ex = Executor(holder)
            lines = []

            class CaptureLogger:
                def printf(self, fmt, *args):
                    lines.append(fmt % args if args else fmt)

            ex.logger = CaptureLogger()
            ex.long_query_time = 0.0  # every query exceeds the threshold
            holder.create_index("i").create_field("f")
            ex.execute("i", "Set(3, f=2)")
            ex.execute("i", "Count(Row(f=2))")
            assert lines, "slow-query log never fired"
            assert "longQueryTime exceeded" in lines[-1]
            assert "qid=" in lines[-1]
            assert "parse=" in lines[-1]  # the phase breakdown rides along
        finally:
            holder.close()

    def test_quiet_above_threshold(self, tmp_path):
        holder = Holder(str(tmp_path / "data")).open()
        try:
            ex = Executor(holder)
            lines = []
            ex.logger = type(
                "L", (), {"printf": lambda self, fmt, *a: lines.append(fmt)}
            )()
            ex.long_query_time = 60.0
            holder.create_index("i").create_field("f")
            ex.execute("i", "Count(Row(f=1))")
            assert not lines
        finally:
            holder.close()


class TestVersionWalkCounters:
    """The freshness-walk assertion VERDICT r5 next-round #2 asked for:
    under point-write churn the journal-backed tiers must pay O(dirty)
    per-shard version reads, never a full O(shards) walk."""

    N_SHARDS = 6

    def _build(self, holder):
        from pilosa_tpu.core.field import options_for_int
        from pilosa_tpu.shardwidth import SHARD_WIDTH

        idx = holder.create_index("i")
        f = idx.create_field("v", options_for_int(-10000, 10000))
        rng = np.random.default_rng(17)
        for shard in range(self.N_SHARDS):
            cols = (
                np.unique(rng.integers(0, SHARD_WIDTH, 40, dtype=np.uint64))
                + shard * SHARD_WIDTH
            )
            f.import_value(cols, rng.integers(-9000, 9001, cols.size))
        return f

    def test_sum_epoch_walks_are_journal_backed_o_dirty(self):
        tpu = pytest.importorskip(
            "pilosa_tpu.exec.tpu",
            reason="device backend needs jax.shard_map",
            exc_type=ImportError,
        )
        from pilosa_tpu.shardwidth import SHARD_WIDTH

        holder = Holder(None).open()
        try:
            self._build(holder)
            be = tpu.TPUBackend(holder)
            ex = Executor(holder, backend=be)
            oracle = Executor(holder)

            first = ex.execute("i", "Sum(field=v)")[0]
            assert first.count > 0
            ex.execute("i", "Sum(field=v)")  # generation-keyed cache hit

            j_walks0 = counter_sum('version_walk_total{kind="journal",tier="sum"}')
            j_shards0 = counter_sum(
                'version_walk_shards_total{kind="journal",tier="sum"}'
            )
            f_shards0 = counter_sum(
                'version_walk_shards_total{kind="full",tier="sum"}'
            )
            incr0 = counter_sum("sum_incremental_updates_total")

            # Churn: EPOCHS point writes, each dirtying exactly one shard,
            # each followed by a Sum that must absorb it incrementally.
            epochs = 4
            rng = np.random.default_rng(3)
            for e in range(epochs):
                shard = e % self.N_SHARDS
                col = shard * SHARD_WIDTH + int(rng.integers(0, SHARD_WIDTH))
                ex.execute("i", f"Set({col}, v={int(rng.integers(-9000, 9001))})")
                got = ex.execute("i", "Sum(field=v)")[0]
                want = oracle.execute("i", "Sum(field=v)")[0]
                assert (got.val, got.count) == (want.val, want.count)

            j_walks = (
                counter_sum('version_walk_total{kind="journal",tier="sum"}')
                - j_walks0
            )
            j_shards = (
                counter_sum('version_walk_shards_total{kind="journal",tier="sum"}')
                - j_shards0
            )
            f_shards = (
                counter_sum('version_walk_shards_total{kind="full",tier="sum"}')
                - f_shards0
            )
            incr = counter_sum("sum_incremental_updates_total") - incr0
            assert incr == epochs, "epochs were not absorbed incrementally"
            assert j_walks == epochs
            # THE O(dirty) claim: one locked version read per dirty shard
            # per epoch — not N_SHARDS per epoch.
            assert j_shards == epochs
            # And the epoch path never fell back to a full walk.
            assert f_shards == 0
        finally:
            holder.close()

    def test_full_walk_counted_per_tier(self):
        tpu = pytest.importorskip(
            "pilosa_tpu.exec.tpu",
            reason="device backend needs jax.shard_map",
            exc_type=ImportError,
        )
        holder = Holder(None).open()
        try:
            self._build(holder)
            be = tpu.TPUBackend(holder)
            ex = Executor(holder, backend=be)
            before = counter_sum('version_walk_shards_total{kind="full",tier="sum"}')
            ex.execute("i", "Sum(field=v)")  # cold: pre-vers + confirm walks
            delta = (
                counter_sum('version_walk_shards_total{kind="full",tier="sum"}')
                - before
            )
            assert delta > 0
            assert delta % self.N_SHARDS == 0  # full walks read every shard
        finally:
            holder.close()


class TestJournalCompleteFreshness:
    """ISSUE r7 tentpole: the pair, TopN, and GroupN serving tiers must
    route epoch freshness through the journal-backed _epoch_versions —
    under point-write churn their version_walk_total{kind=full} stays
    FLAT while kind=journal pays exactly the dirty set."""

    N_SHARDS = 6
    ROWS = 4

    def _tpu(self):
        return pytest.importorskip(
            "pilosa_tpu.exec.tpu",
            reason="device backend needs jax.shard_map",
            exc_type=ImportError,
        )

    def _build(self, holder, fields=("f", "g")):
        from pilosa_tpu.shardwidth import SHARD_WIDTH

        idx = holder.create_index("i")
        rng = np.random.default_rng(23)
        for fname in fields:
            f = idx.create_field(fname)
            for shard in range(self.N_SHARDS):
                cols = (
                    np.unique(
                        rng.integers(0, SHARD_WIDTH, 300, dtype=np.uint64)
                    )
                    + shard * SHARD_WIDTH
                )
                f.import_bits(
                    rng.integers(0, self.ROWS, cols.size, dtype=np.uint64),
                    cols,
                )

    def _set_stmt(self, rng, field="f"):
        from pilosa_tpu.shardwidth import SHARD_WIDTH

        shard = int(rng.integers(0, self.N_SHARDS))
        col = shard * SHARD_WIDTH + int(rng.integers(0, SHARD_WIDTH))
        return f"Set({col}, {field}={int(rng.integers(0, self.ROWS))})"

    def _walks(self, tier):
        return {
            kind: (
                counter_sum(f'version_walk_total{{kind="{kind}",tier="{tier}"}}'),
                counter_sum(
                    f'version_walk_shards_total{{kind="{kind}",tier="{tier}"}}'
                ),
            )
            for kind in ("full", "journal")
        }

    def test_pair_churn_walks_journal_backed(self):
        tpu = self._tpu()
        from pilosa_tpu.pql import parse_string

        holder = Holder(None).open()
        try:
            self._build(holder)
            be = tpu.TPUBackend(holder)
            ex = Executor(holder, backend=be)
            oracle = Executor(holder)
            shards = list(range(self.N_SHARDS))
            queries = [
                "Count(Intersect(Row(f=1), Row(g=2)))",
                "Count(Union(Row(f=0), Row(g=3)))",
            ]
            calls = [parse_string(q).calls[0].children[0] for q in queries]
            be.count_batch("i", calls, shards)  # warm: sweep + full walks
            w0 = self._walks("pair")
            rng = np.random.default_rng(11)
            epochs = 5
            for _ in range(epochs):
                ex.execute("i", self._set_stmt(rng))
                got = be.count_batch("i", calls, shards)
                want = [oracle.execute("i", f"{q}")[0] for q in queries]
                assert got == want
            w1 = self._walks("pair")
            # Zero full walks under churn — the acceptance bar.
            assert w1["full"] == w0["full"]
            # Each epoch walks both pair sides through the journal; only
            # f's one dirtied shard pays a locked read.
            assert w1["journal"][0] - w0["journal"][0] == 2 * epochs
            assert w1["journal"][1] - w0["journal"][1] == epochs
        finally:
            holder.close()

    def test_topn_churn_walks_journal_backed(self):
        tpu = self._tpu()
        holder = Holder(None).open()
        try:
            self._build(holder, fields=("f",))
            be = tpu.TPUBackend(holder)
            ex = Executor(holder, backend=be)
            oracle = Executor(holder)
            shards = list(range(self.N_SHARDS))
            be.topn_field("i", "f", shards, 0)  # warm
            w0 = self._walks("topn")
            rng = np.random.default_rng(13)
            epochs = 5
            for _ in range(epochs):
                ex.execute("i", self._set_stmt(rng))
                got = ex.execute("i", "TopN(f, n=8)")
                want = oracle.execute("i", "TopN(f, n=8)")
                assert got == want
            w1 = self._walks("topn")
            assert w1["full"] == w0["full"]
            assert w1["journal"][0] - w0["journal"][0] == epochs
            assert w1["journal"][1] - w0["journal"][1] == epochs
        finally:
            holder.close()

    def test_groupn_churn_walks_journal_backed(self):
        tpu = self._tpu()
        holder = Holder(None).open()
        try:
            self._build(holder, fields=("f", "g", "h"))
            be = tpu.TPUBackend(holder)
            ex = Executor(holder, backend=be)
            oracle = Executor(holder)
            q = "GroupBy(Rows(f), Rows(g), Rows(h))"
            assert ex.execute("i", q) == oracle.execute("i", q)  # warm
            w0 = self._walks("groupn")
            rng = np.random.default_rng(29)
            epochs = 4
            for _ in range(epochs):
                ex.execute("i", self._set_stmt(rng))
                assert ex.execute("i", q) == oracle.execute("i", q)
            w1 = self._walks("groupn")
            assert w1["full"] == w0["full"]
            # Three fields walked per epoch; one dirtied shard total.
            assert w1["journal"][0] - w0["journal"][0] == 3 * epochs
            assert w1["journal"][1] - w0["journal"][1] == epochs
        finally:
            holder.close()

    def test_groupn_redispatch_confirm_journal_backed(self):
        """A forced groupn re-dispatch (tensor caches dropped) pays only
        the 3 unavoidable cold pre-vers full walks — the post-fetch
        confirm rides the journal (ISSUE 17 satellite: the r13 groupby
        leg showed 12 full walks = 2 executes x (3 pre + 3 confirm))."""
        tpu = self._tpu()
        holder = Holder(None).open()
        try:
            self._build(holder, fields=("f", "g", "h"))
            be = tpu.TPUBackend(holder)
            ex = Executor(holder, backend=be)
            q = "GroupBy(Rows(f), Rows(g), Rows(h))"
            ex.execute("i", q)  # warm: compile + first dispatch
            be._groupn_cache.clear()
            be._agg_cache.clear()
            w0 = self._walks("groupn")
            ex.execute("i", q)
            w1 = self._walks("groupn")
            assert w1["full"][0] - w0["full"][0] == 3
            assert w1["full"][1] - w0["full"][1] == 3 * self.N_SHARDS
            # The confirm side: journal walks with ZERO locked shard
            # reads (nothing dirtied between snapshot and fetch).
            assert w1["journal"][0] - w0["journal"][0] == 3
            assert w1["journal"][1] - w0["journal"][1] == 0
        finally:
            holder.close()

    def test_epoch_versions_differential_vs_live(self):
        """Journal-derived versions must equal the full locked walk in
        every regime: journal-covered epochs, evicted windows, and
        structural (new-fragment) events."""
        tpu = self._tpu()
        from pilosa_tpu.core.view import VIEW_STANDARD
        from pilosa_tpu.shardwidth import SHARD_WIDTH

        holder = Holder(None).open()
        try:
            self._build(holder)
            be = tpu.TPUBackend(holder)
            ex = Executor(holder, backend=be)
            f = be._field("i", "f")
            shards_t = tuple(range(self.N_SHARDS))
            rng = np.random.default_rng(31)

            def snap():
                v = f.view(VIEW_STANDARD)
                return be._live_versions(f, shards_t), v.generation

            # journal-covered: a few point writes
            vers_old, gen_old = snap()
            for _ in range(3):
                ex.execute("i", self._set_stmt(rng))
            assert be._epoch_versions(
                f, shards_t, VIEW_STANDARD, vers_old, gen_old
            ) == be._live_versions(f, shards_t)

            # evicted window: more writes than the journal retains
            from pilosa_tpu.core.view import View

            vers_old, gen_old = snap()
            for _ in range(View.JOURNAL_MAX + 8):
                ex.execute("i", self._set_stmt(rng))
            assert be._epoch_versions(
                f, shards_t, VIEW_STANDARD, vers_old, gen_old
            ) == be._live_versions(f, shards_t)

            # structural event: a write creating a NEW shard's fragment
            vers_old, gen_old = snap()
            ex.execute(
                "i", f"Set({self.N_SHARDS * SHARD_WIDTH + 7}, f=1)"
            )
            shards_t2 = tuple(range(self.N_SHARDS + 1))
            live = be._live_versions(f, shards_t2)
            assert be._epoch_versions(
                f, shards_t2, VIEW_STANDARD,
                vers_old + (None,), gen_old
            ) == live
        finally:
            holder.close()


class TestBenchCaptureProof:
    def test_post_retries_once_on_reset(self, server):
        """The r5 failure shape: ONE mid-run connection reset must cost a
        counted retry, not the whole artifact (fault injected through the
        FaultProxy fixture's one-shot RST mode)."""
        from bench import RETRIES, BenchConn
        from tests.cluster_harness import FaultProxy

        req(server, "POST", "/index/i", b"{}", ctype="application/json")
        req(server, "POST", "/index/i/field/f", b"{}", ctype="application/json")
        req(server, "POST", "/index/i/query", "Set(7, f=1)")

        proxy = FaultProxy(server.host, server.port)
        try:
            bc = BenchConn("127.0.0.1", proxy.port, "/index/i/query")
            assert bc.post("Count(Row(f=1))") == [1]
            before = RETRIES["post"]
            proxy.mode = "reset_once"
            bc.conn.close()  # force the next post onto a fresh (reset) conn
            assert bc.post("Count(Row(f=1))") == [1]
            assert RETRIES["post"] == before + 1
            # The proxy reverted: further posts are clean, no extra retry.
            assert bc.post("Count(Row(f=1))") == [1]
            assert RETRIES["post"] == before + 1
            bc.close()
        finally:
            proxy.close()

    def test_second_consecutive_failure_propagates(self, server):
        from bench import BenchConn
        from tests.cluster_harness import FaultProxy

        proxy = FaultProxy(server.host, server.port)
        try:
            proxy.mode = "refuse"  # every connection dies: systemic
            bc = BenchConn("127.0.0.1", proxy.port, "/index/i/query")
            with pytest.raises(Exception):
                bc.post("Count(Row(f=1))")
            bc.close()
        finally:
            proxy.close()

    def test_phase_means_parser(self):
        from bench import phase_means_ms

        text = (
            'pilosa_query_phase_seconds_count{call="Count",phase="parse"} 4\n'
            'pilosa_query_phase_seconds_sum{call="Count",phase="parse"} 0.002\n'
            'pilosa_query_phase_seconds_count{call="Row",phase="parse"} 6\n'
            'pilosa_query_phase_seconds_sum{call="Row",phase="parse"} 0.004\n'
            'pilosa_query_phase_seconds_count{call="Count",phase="serialize"} 4\n'
            'pilosa_query_phase_seconds_sum{call="Count",phase="serialize"} 0.008\n'
            "pilosa_other_metric 3\n"
        )
        means = phase_means_ms(text)
        assert means["parse"] == pytest.approx(0.6)  # merged across calls
        assert means["serialize"] == pytest.approx(2.0)

    def test_phase_means_baseline_diff(self):
        """The registry is cumulative: the HTTP leg's means must diff out
        earlier in-process legs' histograms (code review r6)."""
        from bench import phase_means_ms, phase_totals

        before = (
            'pilosa_query_phase_seconds_count{call="Count",phase="parse"} 10\n'
            'pilosa_query_phase_seconds_sum{call="Count",phase="parse"} 1.0\n'
        )
        after = (
            'pilosa_query_phase_seconds_count{call="Count",phase="parse"} 14\n'
            'pilosa_query_phase_seconds_sum{call="Count",phase="parse"} 1.002\n'
        )
        means = phase_means_ms(after, baseline=phase_totals(before))
        # 4 new queries costing 2 ms total -> 0.5 ms mean, not the
        # cumulative 1.002/14.
        assert means["parse"] == pytest.approx(0.5)
