"""Opt-in REAL-chip leg (VERDICT r4 #6): the dryrun query list + write
churn against the live TPU with small stacks. Pallas interpret mode (the
CPU suite) can't catch Mosaic-on-hardware behavior — VMEM limits, layout
choices — which is exactly what the device_fallback_total counter
exists for; this leg asserts the counter does NOT grow, i.e. every
device fast path really ran on the chip.

    PILOSA_TPU_TEST_TPU=1 python -m pytest -m tpu -q

Run SOLO on the bench host (never concurrently with bench.py — the
relay-attached chip and the one CPU core are both shared)."""

import numpy as np
import pytest

from pilosa_tpu.shardwidth import SHARD_WIDTH

pytestmark = pytest.mark.tpu

QUERIES = [
    "Count(Intersect(Row(f=1), Row(g=7)))",
    "Count(Union(Row(f=1), Row(f=2), Row(f=3)))",
    "Count(Not(Row(f=1)))",
    "Row(f=2)",
    "TopN(f, n=2)",
    "TopN(f, Row(g=7), n=3)",
    "Sum(field=v)",
    "Min(field=v)",
    "Max(field=v)",
    "Count(Row(v > 100))",
    "Count(Row(v >< [-100, 100]))",
    "GroupBy(Rows(f))",
    "GroupBy(Rows(f), Rows(g))",
    "GroupBy(Rows(f), Rows(g), filter=Row(f=2))",
    "GroupBy(Rows(f), Rows(g), Rows(h))",
]


@pytest.fixture(scope="module")
def live_setup(tmp_path_factory):
    import jax

    assert jax.default_backend() == "tpu", (
        f"live leg needs the real chip, got {jax.default_backend()}"
    )
    import __graft_entry__ as ge
    from pilosa_tpu.exec import Executor
    from pilosa_tpu.exec.tpu import TPUBackend

    rng = np.random.default_rng(0)
    holder = ge._build_holder(
        str(tmp_path_factory.mktemp("live")), 4, rng
    )
    be = TPUBackend(holder)
    yield holder, Executor(holder), Executor(holder, backend=be)
    holder.close()


def _fallbacks() -> int:
    from pilosa_tpu.utils.stats import global_stats

    with global_stats._lock:
        return int(
            sum(
                v
                for (name, _tags), v in global_stats._counters.items()
                if name == "device_fallback_total"
            )
        )


class TestLiveChip:
    def test_dryrun_query_list_exact_with_zero_fallbacks(self, live_setup):
        from pilosa_tpu.exec.result import result_to_json

        holder, ex_cpu, ex_dev = live_setup
        before = _fallbacks()
        for q in QUERIES:
            want = [result_to_json(r) for r in ex_cpu.execute("i", q)]
            got = [result_to_json(r) for r in ex_dev.execute("i", q)]
            assert got == want, q
        assert _fallbacks() == before, "device fast path fell back on chip"

    def test_churn_epoch_stays_exact_with_zero_fallbacks(self, live_setup):
        from pilosa_tpu.exec.result import result_to_json

        holder, ex_cpu, ex_dev = live_setup
        idx = holder.index("i")
        before = _fallbacks()
        for k in range(2):
            idx.field("f").set_bit(1, 7 + k * 131)
            idx.field("v").set_value(23 + k * 97, 400 - k)
            for q in (
                "Count(Intersect(Row(f=1), Row(g=7)))",
                "TopN(f, n=0)",
                "Sum(field=v)",
                "Min(field=v)",
                "Max(field=v)",
                "GroupBy(Rows(f), Rows(g), Rows(h))",
            ):
                want = [result_to_json(r) for r in ex_cpu.execute("i", q)]
                got = [result_to_json(r) for r in ex_dev.execute("i", q)]
                assert got == want, (k, q)
        assert _fallbacks() == before, "churn epoch fell back on chip"
