# Developer / CI entry points (ISSUE r13 satellite): lint cleanliness
# must not depend on anyone remembering to run it.

PY ?= python

.PHONY: lint lint-changed check fast-tests test bench-smoke

lint:                    ## whole-tree pilint (the CI gate)
	$(PY) -m tools.lint

lint-changed:            ## pre-commit fast path: only files changed vs git HEAD
	$(PY) -m tools.lint --changed

# The fast tier-1 subset `make check` runs on every push: the lint gate
# plus the suites pinning the lint framework itself, the config
# round-trip, the wire/PQL/roaring protocol contracts, and the serving
# front door — minutes, not the full tier-1 hour.
FAST_TESTS = tests/test_lint.py tests/test_config.py tests/test_pql.py \
             tests/test_roaring.py tests/test_server.py

check: lint fast-tests   ## lint + fast tier-1 subset (what CI runs)

fast-tests:              ## the fast subset alone (CI runs lint as its own step)
	$(PY) -m pytest -q $(FAST_TESTS)

test:                    ## full tier-1
	$(PY) -m pytest -q

# Tiny-shape bench end to end (ISSUE r13 satellite): every leg of the
# artifact — including the mesh_scaling curve, whose children force
# virtual CPU device counts themselves — runs under the same forced
# 8-device CPU platform the test suite uses, so an artifact-zeroing
# regression (crashed leg, renamed key) fails in CI instead of burning
# a capture round.
bench-smoke:             ## tiny-shape bench smoke incl. mesh_scaling keys
	env JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
		$(PY) -m pytest -q tests/test_bench_smoke.py
