# Developer / CI entry points (ISSUE r13 satellite): lint cleanliness
# must not depend on anyone remembering to run it.

PY ?= python

.PHONY: lint lint-changed check fast-tests test

lint:                    ## whole-tree pilint (the CI gate)
	$(PY) -m tools.lint

lint-changed:            ## pre-commit fast path: only files changed vs git HEAD
	$(PY) -m tools.lint --changed

# The fast tier-1 subset `make check` runs on every push: the lint gate
# plus the suites pinning the lint framework itself, the config
# round-trip, the wire/PQL/roaring protocol contracts, and the serving
# front door — minutes, not the full tier-1 hour.
FAST_TESTS = tests/test_lint.py tests/test_config.py tests/test_pql.py \
             tests/test_roaring.py tests/test_server.py

check: lint fast-tests   ## lint + fast tier-1 subset (what CI runs)

fast-tests:              ## the fast subset alone (CI runs lint as its own step)
	$(PY) -m pytest -q $(FAST_TESTS)

test:                    ## full tier-1
	$(PY) -m pytest -q
