"""VERDICT r4 #8: where do the ~18ms over relay_rtt_floor_ms go in
single_query_p50_ms? Phase-split at the bench shape."""
import os, sys, time
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np
from pilosa_tpu.core import Holder
from pilosa_tpu.exec.tpu import TPUBackend
from pilosa_tpu.pql import parse_string
import bench

h = Holder(None).open()
t0 = time.time()
bench.build_index(h)
print(f"build {time.time()-t0:.1f}s", flush=True)
be = TPUBackend(h)
shards = list(range(bench.SHARDS))
rng = np.random.default_rng(7)
queries = [f"Count(Intersect(Row(f={int(rng.integers(0,8))}), Row(g={int(rng.integers(0,8))})))" for _ in range(30)]
calls = [parse_string(q).calls[0].children[0] for q in queries]
be.count_shards("bench", calls[0], shards)  # warm
rtt = bench.measure_rtt_floor()
print(f"relay_rtt_floor {rtt*1e3:.2f} ms", flush=True)

# total single-query p50
lat = []
for c in calls:
    t0 = time.perf_counter(); be.count_shards("bench", c, shards); lat.append(time.perf_counter()-t0)
lat.sort()
print(f"single_query_p50 {lat[len(lat)//2]*1e3:.2f} ms", flush=True)

# host-side assemble alone (spec+blocks+scalars, cache-hit path)
t = []
for c in calls:
    t0 = time.perf_counter(); be._assemble("bench", c, tuple(shards)); t.append(time.perf_counter()-t0)
t.sort()
print(f"assemble p50 {t[len(t)//2]*1e3:.3f} ms", flush=True)

# dispatch+readback of the already-compiled count program on resident blocks
spec, blocks, scalars = be._assemble("bench", calls[0], tuple(shards))
prog = be._program("count", spec, True)
int(np.asarray(prog(blocks, scalars)))  # warm this spec shape
t = []
for c in calls:
    spec, blocks, scalars = be._assemble("bench", c, tuple(shards))
    fn = be._program("count", spec, True)
    t0 = time.perf_counter(); int(np.asarray(fn(blocks, scalars))); t.append(time.perf_counter()-t0)
t.sort()
print(f"dispatch+readback p50 {t[len(t)//2]*1e3:.2f} ms", flush=True)
