import os, sys, time
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from pilosa_tpu.core import Holder
from pilosa_tpu.exec.tpu import TPUBackend
import bench
h = Holder(None).open()
t0 = time.time(); bench.build_index(h); print(f"build {time.time()-t0:.1f}s", flush=True)
bench.build_bsi_field(h)
be = TPUBackend(h)
ro, churn, wr = bench.bench_minmax_churn(h, be)
print(f"minmax ro {ro:.0f} churn {churn:.0f} ratio {churn/ro:.3f} wrate {wr:.1f}", flush=True)
