import os, sys, time
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np
from pilosa_tpu.core import Holder
from pilosa_tpu.exec import Executor
from pilosa_tpu.exec.tpu import TPUBackend
from pilosa_tpu.pql import parse_string
from pilosa_tpu.utils.stats import global_stats
import bench

t0 = time.time()
h = Holder(None).open()
bench.build_index(h)
print(f"build {time.time()-t0:.1f}s", flush=True)
be = TPUBackend(h)

class L:
    def printf(self, fmt, *a): print("LOG:", fmt % a, flush=True)
be.logger = L()

shards = list(range(bench.SHARDS))
calls = [parse_string(f"Count(Intersect(Row(f={i%8}), Row(g={(i+1)%8})))").calls[0].children[0] for i in range(8)]
t0 = time.time()
be.count_batch("bench", calls, shards)
print(f"f/g warm {time.time()-t0:.1f}s", flush=True)

ex = Executor(h, backend=be)
t0 = time.time()
res = ex.execute("bench", "GroupBy(Rows(f), Rows(g), Rows(h))")
cold = time.time() - t0
print(f"cold {cold:.2f}s  results {len(res)}", flush=True)
print("fallbacks:", {k: v for k, v in global_stats._counters.items() if "fallback" in k[0]}, flush=True)
w = global_stats._counters.get(("stack_sparse_wire_bytes_total", ()), 0)
d = global_stats._counters.get(("stack_sparse_dense_bytes_total", ()), 0)
print(f"sparse wire {int(w)>>20}MB of {int(d)>>20}MB", flush=True)
be._agg_cache.clear()
t0 = time.time()
ex.execute("bench", "GroupBy(Rows(f), Rows(g), Rows(h))")
print(f"warm_ms {(time.time()-t0)*1e3:.0f}", flush=True)
