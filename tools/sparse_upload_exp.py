"""One-off experiment (VERDICT r4 #1): choose the device-side decompress
strategy for packed stack uploads on the REAL chip.

Candidates for rebuilding dense uint32[N] from packed nonzero words:
  A. dense device_put (baseline — what r4 ships)
  B. scatter: upload (positions i32[nnz], values u32[nnz]),
     out = zeros.at[pos].set(vals, unique_indices)
  C. mask+gather: upload (mask u32[N/32], values u32[nnz]),
     bits = unpack(mask); out = where(bits, vals[cumsum_exclusive(bits)], 0)

block_until_ready is NOT a trustworthy barrier over the axon relay (it
returned 0.000s for 250M-element programs), so every timing here ends
with a small device-reduction READBACK — int(sum(slice)) — which cannot
complete before the producing computation has run.

Run SOLO on the bench host (single real TPU via relay):
    PYTHONPATH=/root/repo python tools/sparse_upload_exp.py
"""

import time

import numpy as np
import jax
import jax.numpy as jnp

N = 250_000_000  # ~1 GB of uint32 — the bench h-stack scale
NNZ_FRAC = 0.17

rng = np.random.default_rng(0)
flat = np.zeros(N, dtype=np.uint32)
nnz = int(N * NNZ_FRAC)
pos = np.sort(rng.choice(N, size=nnz, replace=False)).astype(np.int32)
flat[pos] = rng.integers(1, 2**32, size=nnz, dtype=np.uint32)
vals = flat[pos]

dev = jax.devices()[0]
print("device:", dev, flush=True)

_probe = jax.jit(lambda x: jnp.sum(x[:1024].astype(jnp.uint64)))


def barrier(arrs):
    """Real completion barrier: readback of a reduction over each array."""
    tot = 0
    for a in (arrs if isinstance(arrs, (tuple, list)) else (arrs,)):
        tot += int(_probe(a.reshape(-1)))
    return tot


def timed(label, fn, n=4):
    ts = []
    out = None
    for _ in range(n):
        t0 = time.perf_counter()
        out = fn()
        barrier(out)
        ts.append(time.perf_counter() - t0)
    print(f"{label}: med {sorted(ts)[len(ts)//2]:.3f}s "
          f"(runs: {[round(t, 3) for t in ts]})", flush=True)
    return out


@jax.jit
def scatter_build(p, v):
    return jnp.zeros(N, jnp.uint32).at[p].set(v, unique_indices=True,
                                              mode="promise_in_bounds")


@jax.jit
def mask_build(mw, v):
    bits = ((mw[:, None] >> jnp.arange(32, dtype=jnp.uint32)[None, :]) & 1)
    bits = bits.reshape(-1).astype(jnp.int32)
    prefix = jnp.cumsum(bits) - bits  # exclusive
    return jnp.where(bits != 0, v[prefix], 0).astype(jnp.uint32)


mask_words = np.bitwise_or.reduce(
    ((flat.reshape(-1, 32) != 0).astype(np.uint32)
     << np.arange(32, dtype=np.uint32)[None, :]), axis=1)

# warm everything once (compiles + first transfers) before any timing
pos_d = (jax.device_put(pos, dev), jax.device_put(vals, dev))
md = (jax.device_put(mask_words, dev), jax.device_put(vals, dev))
barrier(scatter_build(*pos_d))
barrier(mask_build(*md))
print("warmup done", flush=True)

a = timed("A dense upload 1000MB ", lambda: jax.device_put(flat, dev))
pos_d = timed(f"B upload pos+vals {(pos.nbytes + vals.nbytes) >> 20}MB ",
              lambda: (jax.device_put(pos, dev), jax.device_put(vals, dev)))
b = timed("B scatter device      ", lambda: scatter_build(*pos_d))
md = timed(f"C upload mask+vals {(mask_words.nbytes + vals.nbytes) >> 20}MB ",
           lambda: (jax.device_put(mask_words, dev), jax.device_put(vals, dev)))
c = timed("C mask+gather device  ", lambda: mask_build(*md))

np.testing.assert_array_equal(np.asarray(b), flat)
np.testing.assert_array_equal(np.asarray(c), flat)
print("both decompressors bit-exact", flush=True)
