#!/usr/bin/env python3
"""Static metric-name drift check — THIN SHIM.

The implementation moved into the lint plane (ISSUE r12 checker 6):
tools/lint/checkers/metrics.py, runnable as part of
`python -m tools.lint` (rule `metric-docs`). This entry point keeps
existing invocations — CI scripts, tests/test_metrics_docs.py, operator
muscle memory — working unchanged: same module-level API
(source_metrics / doc_tokens / DYNAMIC_FAMILIES), same exit codes, same
two-way drift report.
"""

from __future__ import annotations

import sys
from pathlib import Path

# Runnable both as `python tools/check_metrics_docs.py` (sys.path[0] is
# tools/) and via importlib from the tests: anchor the repo root.
_ROOT = str(Path(__file__).resolve().parent.parent)
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)

from tools.lint.checkers.metrics import (  # noqa: E402,F401 — re-exported API
    DOC,
    DYNAMIC_FAMILIES,
    METRIC_SUFFIXES,
    SYNTHESIZED,
    doc_tokens,
    metrics_docs_drift,
    source_metrics,
)


def main() -> int:
    # One tree scan + one doc read (the checker module's DOC constant —
    # no second copy of the path), shared between the drift check and
    # the clean-path summary counts.
    src = source_metrics()
    doc_text = DOC.read_text()
    findings = metrics_docs_drift(src=src, doc_text=doc_text)
    if findings:
        for line in findings:
            print(line)
        return 1
    doc_exact, doc_wild = doc_tokens(doc_text)
    print(f"metrics docs clean: {len(src)} emitted names, "
          f"{len(doc_exact)} documented, {len(doc_wild)} wildcard families")
    return 0


if __name__ == "__main__":
    sys.exit(main())
