#!/usr/bin/env python3
"""Static metric-name drift check (ISSUE r8 satellite): every metric the
code emits must appear in docs/observability.md, and every metric-shaped
name the docs catalogue must exist in code — wired into tier-1 as a test
(tests/test_metrics_docs.py) so the catalogue can never rot.

Source side: literal first-argument names of StatsClient calls
(count/gauge/timing/histogram/timer/remove_gauge) anywhere under
pilosa_tpu/. Dynamic (f-string) names are exempt and listed in
DYNAMIC_FAMILIES with the doc spelling that covers them.

Docs side: backticked tokens in docs/observability.md whose shape is a
metric name (optionally pilosa_-prefixed, optional {tags}, optional
histogram/exporter suffix _bucket/_count/_sum/_p50/_p95/_p99/_p999 — a
histogram family's three exposition series collapse to ONE documented
name) AND that end in one of the metric suffixes below — bench JSON
keys, env knobs, and file names in the same docs do not match. A doc
token `prefix_*` is a wildcard covering every source name that starts
with `prefix_`.

Exit 0 clean; exit 1 with a report of both drift directions.
"""

from __future__ import annotations

import re
import sys
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent
SRC_DIR = ROOT / "pilosa_tpu"
DOC = ROOT / "docs" / "observability.md"

#: Metric families emitted with computed (f-string) names: the checker
#: cannot read them statically, so each must keep a doc mention of the
#: spelled-out family (asserted below so the exemption itself can't rot).
DYNAMIC_FAMILIES = {
    # executor.py: stats.count(f"query_{call.name}_total")
    "query_<Call>_total",
}

#: A doc token must end in one of these to be treated as a metric name
#: (after stripping the histogram/exporter suffixes _bucket/_count/_sum/
#: _p50/_p95/_p99/_p999, so a plain-JSON field like `device_count` does
#: not match).
METRIC_SUFFIXES = (
    "_total", "_seconds", "_bytes", "_pending", "_done",
    "_inflight", "_up", "_fds", "_threads", "_nodes", "_fields",
    "_shards", "_evictions", "_rederives", "_state",
    # Round 11: the batch_occupancy value histogram (legs/launch) and
    # the http_inflight_queries admission gauge.
    "_occupancy", "_queries",
)

_CALL_RE = re.compile(
    r"""\.(?:count|gauge|timing|histogram|timer|remove_gauge)\(\s*
        ["']([a-z][a-z0-9_.]*)["']""",
    re.VERBOSE,
)

_TOKEN_RE = re.compile(r"`([^`\n]+)`")

_EXPORT_SUFFIX_RE = re.compile(r"_(?:bucket|count|sum|p50|p95|p99|p999)$")


#: Series synthesized as literal exposition lines (no StatsClient call):
#: the /metrics/cluster scrape-health pair. Each must still appear as a
#: literal in the source, which source_metrics verifies.
SYNTHESIZED = ("cluster_scrape_up", "cluster_scrape_seconds")


def source_metrics() -> set[str]:
    names: set[str] = set()
    all_text = []
    for path in sorted(SRC_DIR.rglob("*.py")):
        text = path.read_text()
        all_text.append(text)
        for m in _CALL_RE.finditer(text):
            names.add(m.group(1).replace(".", "_").replace("-", "_"))
    blob = "\n".join(all_text)
    for name in SYNTHESIZED:
        if name in blob:
            names.add(name)
    return names


def doc_tokens() -> tuple[set[str], set[str]]:
    """(exact metric-shaped tokens, wildcard prefixes) from the doc."""
    exact: set[str] = set()
    wildcards: set[str] = set()
    for tok in _TOKEN_RE.findall(DOC.read_text()):
        tok = tok.strip()
        tok = re.sub(r"\{[^}]*\}$", "", tok)  # strip {tags}
        if tok.startswith("pilosa_"):
            tok = tok[len("pilosa_"):]
        if re.fullmatch(r"[a-z][a-z0-9_]*_\*", tok):
            wildcards.add(tok[:-2])
            continue
        if not re.fullmatch(r"[a-z][a-z0-9_]*", tok):
            continue
        base = _EXPORT_SUFFIX_RE.sub("", tok)
        if base.endswith(METRIC_SUFFIXES):
            exact.add(base)
    return exact, wildcards


def main() -> int:
    src = source_metrics()
    doc_exact, doc_wild = doc_tokens()
    doc_text = DOC.read_text()

    undocumented = sorted(
        n
        for n in src
        if n not in doc_exact
        and not any(n.startswith(w) for w in doc_wild)
    )
    phantom = sorted(
        t
        for t in doc_exact
        if t not in src
        # A documented name may be an exporter-derived spelling of a
        # real timing series (name_count/_sum/_p50/_p99 handled above)
        # or a prefix another doc line spells exactly; anything else is
        # a catalogue entry with no emitter.
    )
    missing_dynamic = sorted(f for f in DYNAMIC_FAMILIES if f not in doc_text)

    ok = True
    if undocumented:
        ok = False
        print("EMITTED BUT NOT DOCUMENTED in docs/observability.md:")
        for n in undocumented:
            print(f"  {n}")
    if phantom:
        ok = False
        print("DOCUMENTED BUT NOT EMITTED anywhere in pilosa_tpu/:")
        for n in phantom:
            print(f"  {n}")
    if missing_dynamic:
        ok = False
        print("DYNAMIC FAMILY missing its doc mention:")
        for n in missing_dynamic:
            print(f"  {n}")
    if ok:
        print(f"metrics docs clean: {len(src)} emitted names, "
              f"{len(doc_exact)} documented, {len(doc_wild)} wildcard families")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
