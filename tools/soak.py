"""Soak harness: sustained mixed read/write load against a live server.

Boots a server subprocess on a fresh data dir, seeds an index, then runs
N reader threads of batched Counts against a writer issuing Set/Clear at
a fixed rate, sampling the server's RSS each period. Fails loudly on any
non-200, and on RSS growth past --rss-slack once warm (leak detector —
the serving caches are all bounded: pair/TopN/agg tables, plan memo,
parse cache, bit-op rings, update latches).

Usage:
    python tools/soak.py --minutes 5 --readers 6 --write-rate 50
"""

import argparse
import http.client
import json
import os
import subprocess
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def rss_mb(pid: int) -> int:
    with open(f"/proc/{pid}/status") as f:
        for line in f:
            if line.startswith("VmRSS"):
                return int(line.split()[1]) // 1024
    return 0


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--minutes", type=float, default=3.0)
    ap.add_argument("--readers", type=int, default=6)
    ap.add_argument("--write-rate", type=float, default=50.0)
    ap.add_argument("--port", type=int, default=10207)
    ap.add_argument("--data-dir", default=None,
                    help="default: a fresh temp dir (a reused dir would "
                         "409 on index creation)")
    ap.add_argument("--executor", default="tpu")
    ap.add_argument("--rss-slack", type=float, default=0.15,
                    help="allowed RSS growth fraction after warmup")
    args = ap.parse_args()

    import tempfile

    import numpy as np

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    data_dir = args.data_dir or tempfile.mkdtemp(prefix="pilosa-tpu-soak-")
    env = dict(os.environ)
    env["PYTHONPATH"] = repo_root + (
        ":" + env["PYTHONPATH"] if env.get("PYTHONPATH") else ""
    )
    srv = subprocess.Popen(
        [sys.executable, "-m", "pilosa_tpu.cli", "server",
         "-d", data_dir, "--bind", f"localhost:{args.port}",
         "--executor", args.executor],
        env=env,
    )
    try:
        conn = None
        for _ in range(120):
            if srv.poll() is not None:
                raise RuntimeError(f"server exited rc={srv.returncode}")
            try:
                conn = http.client.HTTPConnection("localhost", args.port, timeout=60)
                conn.request("GET", "/status")
                conn.getresponse().read()
                break
            except OSError:
                time.sleep(0.5)
        else:
            raise RuntimeError("server did not come up in 60s")

        def post(c, body):
            c.request("POST", "/index/soak/query", body)
            r = c.getresponse()
            b = r.read().decode()
            if r.status != 200:  # not assert: must survive python -O
                raise RuntimeError(f"HTTP {r.status}: {b[:200]}")
            return json.loads(b)["results"]

        def ddl(path):
            conn.request("POST", path, "")
            r = conn.getresponse()
            b = r.read().decode()
            if r.status != 200:
                raise RuntimeError(f"{path}: HTTP {r.status}: {b[:200]}")

        ddl("/index/soak")
        ddl("/index/soak/field/f")
        ddl("/index/soak/field/g")
        # Seed BOTH queried fields, batched (500 Sets per request — one
        # Set per POST would take minutes of pure seeding round trips).
        sets = [f"Set({col}, f={col % 8})" for col in range(0, 60000, 3)]
        sets += [f"Set({col}, g={col % 8})" for col in range(0, 60000, 5)]
        for i in range(0, len(sets), 500):
            post(conn, "".join(sets[i : i + 500]))

        stop = threading.Event()
        errors: list = []
        nq = [0]
        nw = [0]

        def reader(_k):
            c = http.client.HTTPConnection("localhost", args.port, timeout=60)
            body = "".join(
                f"Count(Intersect(Row(f={r}), Row(g=2)))" for r in range(8)
            )
            try:
                while not stop.is_set():
                    post(c, body)
                    nq[0] += 8
            except Exception as e:  # noqa: BLE001 — recorded and failed below
                if not stop.is_set():
                    errors.append(("reader", repr(e)))

        def writer():
            c = http.client.HTTPConnection("localhost", args.port, timeout=60)
            rng = np.random.default_rng(3)
            period = 1.0 / args.write_rate
            nxt = time.perf_counter()
            try:
                while not stop.is_set():
                    # Deadline pacing: sleep-after-POST would fall below
                    # the requested rate by the request latency.
                    now = time.perf_counter()
                    if now < nxt:
                        time.sleep(min(period, nxt - now))
                        continue
                    nxt += period
                    col = int(rng.integers(0, 200000))
                    row = int(rng.integers(0, 8))
                    fld = ("f", "g")[int(rng.integers(0, 2))]
                    verb = "Clear" if rng.integers(0, 5) == 0 else "Set"
                    post(c, f"{verb}({col}, {fld}={row})")
                    nw[0] += 1
            except Exception as e:  # noqa: BLE001
                if not stop.is_set():
                    errors.append(("writer", repr(e)))

        threads = [
            threading.Thread(target=reader, args=(k,))
            for k in range(args.readers)
        ] + [threading.Thread(target=writer)]
        for t in threads:
            t.start()
        samples = []
        n_samples = max(3, int(args.minutes * 3))
        for m in range(n_samples):
            time.sleep(args.minutes * 60 / n_samples)
            samples.append(rss_mb(srv.pid))
            print(
                f"t={int((m + 1) * args.minutes * 60 / n_samples)}s "
                f"rss={samples[-1]}MB q={nq[0]} w={nw[0]} err={len(errors)}",
                flush=True,
            )
            if errors:
                break
        stop.set()
        for t in threads:
            t.join(timeout=30)
        if errors:
            print("FAIL:", errors[:3])
            return 1
        warm = samples[min(2, len(samples) - 1)]
        if samples[-1] > warm * (1 + args.rss_slack) + 50:
            print(f"FAIL: rss grew {warm} -> {samples[-1]} MB:", samples)
            return 1
        print(f"SOAK OK: {nq[0]} queries, {nw[0]} writes, "
              f"rss {samples[0]}->{samples[-1]}MB")
        return 0
    finally:
        srv.terminate()
        try:
            srv.wait(timeout=10)
        except subprocess.TimeoutExpired:
            srv.kill()
            srv.wait()
        if args.data_dir is None:
            # Default dirs are per-run temp dirs: don't leak them.
            import shutil

            shutil.rmtree(data_dir, ignore_errors=True)


if __name__ == "__main__":
    raise SystemExit(main())
