"""pilint core: per-file AST lint framework with validated waivers.

The Go reference gets `go vet` + `-race` for free; this is the Python
stand-in, specialized to THIS project's invariants (monotonic deadlines,
`code`-field error bodies, jit dispatch hygiene, lock ordering, crash
barriers, metric/doc drift). The framework is deliberately small:

- A SourceFile wraps one parsed module: text, AST, and its waivers.
- A Checker owns one rule id and yields Violations per file and/or once
  per run (finalize, for cross-file analyses like the lock graph).
- Waivers are `# lint: allow-<rule>(<reason>)` comments. They are data,
  not escape hatches: a waiver with no reason is itself a violation, a
  waiver naming an unknown rule is a violation, and a waiver no checker
  consumed is a violation — so the waiver inventory can never rot into
  a list of stale permissions (the failure mode of bare `# noqa`).

A waiver suppresses violations on the physical line it shares; a waiver
comment alone on its line covers the next statement line. Checkers call
SourceFile.waive(rule, start, end) with the violating node's line span,
so a waiver anywhere inside a multi-line statement counts.
"""

from __future__ import annotations

import ast
import io
import re
import tokenize
from dataclasses import dataclass, field
from pathlib import Path
from typing import Iterable, Optional

REPO_ROOT = Path(__file__).resolve().parent.parent.parent

#: Default lint tree: the shipped package. tools/tests are linted only
#: when named explicitly (fixtures are known-bad on purpose).
DEFAULT_TREE = "pilosa_tpu"

#: The waiver-ratchet ledger: the committed per-rule census of
#: `# lint: allow-<rule>(...)` waivers in the default tree. Full-tree
#: runs fail when the live census differs — adding a waiver is a
#: deliberate reviewed act (bump the ledger in the same commit), and
#: removing one ratchets the ledger down so it can never drift into a
#: standing pile of unexamined permissions.
WAIVER_LEDGER = Path(__file__).resolve().parent / "waivers.lock"

_WAIVER_RE = re.compile(
    r"allow-(?P<rule>[a-z][a-z0-9-]*)"
    r"(?:\((?P<reason>[^()]*)\))?"
)
_WAIVER_MARK = re.compile(r"#\s*lint:\s*(?P<body>.*)$")


@dataclass
class Violation:
    rule: str
    path: str  # repo-relative, for stable reports
    line: int
    message: str
    hint: str = ""

    def render(self) -> str:
        loc = f"{self.path}:{self.line}"
        out = f"{loc}: [{self.rule}] {self.message}"
        if self.hint:
            out += f"\n    fix: {self.hint}"
        return out


@dataclass
class Waiver:
    rule: str
    reason: str
    line: int        # line of the comment itself
    applies_to: int  # line the waiver covers (next stmt for own-line comments)
    used: bool = False


@dataclass
class SourceFile:
    path: Path
    rel: str
    text: str
    tree: Optional[ast.AST]
    parse_error: Optional[str] = None
    waivers: list[Waiver] = field(default_factory=list)
    #: Waiver-syntax violations found while parsing comments.
    waiver_errors: list[Violation] = field(default_factory=list)

    @classmethod
    def load(cls, path: Path, known_rules: Iterable[str]) -> "SourceFile":
        text = path.read_text()
        rel = str(path.resolve().relative_to(REPO_ROOT)) if path.resolve().is_relative_to(REPO_ROOT) else str(path)
        try:
            tree = ast.parse(text, filename=rel)
            err = None
        except SyntaxError as e:
            tree, err = None, f"syntax error: {e}"
        f = cls(path=path, rel=rel, text=text, tree=tree, parse_error=err)
        f._parse_waivers(set(known_rules))
        return f

    def _parse_waivers(self, known_rules: set[str]) -> None:
        """Collect `# lint: allow-<rule>(<reason>)` comments via the
        tokenizer (never from string literals). Validates rule names and
        the mandatory reason here, so a malformed waiver fails even when
        its rule's checker finds nothing nearby."""
        try:
            tokens = tokenize.generate_tokens(io.StringIO(self.text).readline)
            comments = [
                (t.start[0], t.string, t.line)
                for t in tokens
                if t.type == tokenize.COMMENT
            ]
        except (tokenize.TokenError, SyntaxError, IndentationError):
            return
        lines = self.text.splitlines()
        for lineno, comment, _src_line in comments:
            mark = _WAIVER_MARK.search(comment)
            if mark is None:
                continue
            body = mark.group("body")
            matches = list(_WAIVER_RE.finditer(body))
            if not matches:
                self.waiver_errors.append(Violation(
                    rule="waiver-syntax", path=self.rel, line=lineno,
                    message=f"unparseable lint waiver comment: {comment.strip()!r}",
                    hint="use `# lint: allow-<rule>(<reason>)`",
                ))
                continue
            own_line = lines[lineno - 1].lstrip().startswith("#")
            applies_to = lineno
            if own_line:
                # Comment-only line: the waiver covers the next
                # non-blank, non-comment source line.
                for nxt in range(lineno, len(lines)):
                    stripped = lines[nxt].strip()
                    if stripped and not stripped.startswith("#"):
                        applies_to = nxt + 1
                        break
            for m in matches:
                rule, reason = m.group("rule"), (m.group("reason") or "").strip()
                if rule not in known_rules:
                    self.waiver_errors.append(Violation(
                        rule="waiver-syntax", path=self.rel, line=lineno,
                        message=f"waiver names unknown rule {rule!r}",
                        hint="rule ids are the checker names in "
                             "`python -m tools.lint --list-rules`",
                    ))
                    continue
                if not reason:
                    self.waiver_errors.append(Violation(
                        rule="waiver-syntax", path=self.rel, line=lineno,
                        message=f"waiver for {rule!r} has no reason",
                        hint="say WHY: `# lint: allow-"
                             f"{rule}(<reason>)`",
                    ))
                    continue
                self.waivers.append(Waiver(
                    rule=rule, reason=reason, line=lineno,
                    applies_to=applies_to,
                ))

    def waive(self, rule: str, start: int, end: Optional[int] = None) -> bool:
        """True (and marks the waiver used) when a waiver for `rule`
        covers any line in [start, end]."""
        end = end if end is not None else start
        for w in self.waivers:
            if w.rule == rule and start <= w.applies_to <= end:
                w.used = True
                return True
        return False


class Checker:
    """One rule. Subclasses set `rule`, `doc` (one-line rationale shown
    in reports/--list-rules) and implement check_file and/or finalize."""

    rule: str = ""
    doc: str = ""
    #: Repo-relative path prefixes this checker inspects ("" = all).
    scope: tuple[str, ...] = ("",)
    #: Project-level checkers (metric/doc drift) run even when only a
    #: subset of files is linted — their subject is the whole repo.
    project_level: bool = False
    #: Cross-file checkers (the lock graph) need the whole tree to see
    #: which waivers are genuinely consumed: on subset runs their
    #: waivers are exempt from unused-waiver judging.
    cross_file: bool = False

    def in_scope(self, f: SourceFile) -> bool:
        return any(f.rel.startswith(p) for p in self.scope)

    def check_file(self, f: SourceFile) -> Iterable[Violation]:
        return ()

    def finalize(self, files: list[SourceFile]) -> Iterable[Violation]:
        """Called once after every file was offered; `files` is the
        in-scope subset. Cross-file rules report here."""
        return ()


def _git_changed_files() -> list[Path]:
    """Changed-vs-HEAD python files (staged + unstaged + untracked) —
    the --changed fast mode for pre-commit loops."""
    import subprocess

    try:
        out = subprocess.run(
            ["git", "-C", str(REPO_ROOT), "status", "--porcelain"],
            capture_output=True, text=True, timeout=30, check=True,
        ).stdout
    except (subprocess.SubprocessError, OSError):
        return []
    paths = []
    for line in out.splitlines():
        name = line[3:].split(" -> ")[-1].strip().strip('"')
        if not name.endswith(".py"):
            continue
        if not name.startswith(DEFAULT_TREE + "/"):
            # Fast mode is a SUBSET of the default gate: changed test/
            # tool files were never lint targets, and feeding them to
            # the whole-program rules (shared-state's root inventory,
            # the lock graph) manufactures roots/edges the real tree
            # doesn't have.
            continue
        p = REPO_ROOT / name
        if p.exists():
            paths.append(p)
    return paths


def collect_files(
    paths: Optional[list[str]] = None, changed: bool = False
) -> list[Path]:
    if changed:
        return sorted(_git_changed_files())
    if paths:
        out: list[Path] = []
        for raw in paths:
            p = Path(raw)
            if not p.is_absolute():
                p = REPO_ROOT / p
            if p.is_dir():
                out.extend(sorted(p.rglob("*.py")))
            else:
                out.append(p)
        return out
    return sorted((REPO_ROOT / DEFAULT_TREE).rglob("*.py"))


def read_waiver_ledger(path: Optional[Path] = None) -> Optional[dict[str, int]]:
    """rule -> allowed waiver count, or None when the ledger is absent."""
    p = path or WAIVER_LEDGER
    if not p.exists():
        return None
    out: dict[str, int] = {}
    for line in p.read_text().splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        rule, _, count = line.partition(" ")
        try:
            out[rule] = int(count)
        except ValueError:
            continue  # malformed line: surfaces as a census mismatch
    return out


def waiver_census(files: Iterable[SourceFile]) -> dict[str, int]:
    """Live per-rule waiver counts over the given files."""
    census: dict[str, int] = {}
    for f in files:
        for w in f.waivers:
            census[w.rule] = census.get(w.rule, 0) + 1
    return census


def _ratchet_violations(files: list[SourceFile]) -> list[Violation]:
    """Census-vs-ledger drift. Judged only on full default-tree runs —
    a subset run sees a partial census by construction."""
    ledger = read_waiver_ledger()
    rel = (str(WAIVER_LEDGER.relative_to(REPO_ROOT))
           if WAIVER_LEDGER.is_relative_to(REPO_ROOT)
           else str(WAIVER_LEDGER))
    if ledger is None:
        return [Violation(
            rule="waiver-ratchet", path=rel, line=1,
            message="waiver ledger missing",
            hint="create it from the live census: "
                 "`python -m tools.lint --list-waivers`",
        )]
    census = waiver_census(files)
    out = []
    for rule in sorted(set(census) | set(ledger)):
        have, allowed = census.get(rule, 0), ledger.get(rule, 0)
        if have > allowed:
            out.append(Violation(
                rule="waiver-ratchet", path=rel, line=1,
                message=f"{have} waiver(s) for {rule!r} in the tree but "
                        f"the ledger records {allowed}",
                hint="a new waiver is a reviewed decision: bump "
                     f"{rel} in the same commit (or fix instead of "
                     "waiving)",
            ))
        elif have < allowed:
            out.append(Violation(
                rule="waiver-ratchet", path=rel, line=1,
                message=f"ledger records {allowed} waiver(s) for "
                        f"{rule!r} but the tree has {have}",
                hint=f"ratchet down: lower the {rule} count in {rel}",
            ))
    return out


def run_lint(
    checkers: list[Checker],
    paths: Optional[list[str]] = None,
    changed: bool = False,
    rules: Optional[set[str]] = None,
) -> list[Violation]:
    """Run `checkers` over the selected tree; returns every violation
    (rule violations + waiver-syntax + unused waivers), sorted."""
    # Waiver validation knows EVERY registered rule, even under --rule
    # filtering — a waiver for an unselected rule is not "unknown".
    known_rules = {c.rule for c in checkers}
    if rules:
        checkers = [c for c in checkers if c.rule in rules]
    active_rules = {c.rule for c in checkers}
    files = []
    violations: list[Violation] = []
    for p in collect_files(paths, changed=changed):
        if "__pycache__" in p.parts:
            continue
        try:
            files.append(SourceFile.load(p, known_rules))
        except OSError as e:
            # A typo'd CLI path must be a reportable finding, not a
            # traceback (the promised report format covers it).
            violations.append(Violation(
                rule="parse", path=str(p), line=1,
                message=f"cannot read file: {e}",
            ))
    for f in files:
        if f.parse_error:
            violations.append(Violation(
                rule="parse", path=f.rel, line=1, message=f.parse_error,
            ))
            continue
        violations.extend(f.waiver_errors)
    parsed = [f for f in files if f.tree is not None]
    explicit_subset = bool(paths) or changed
    for checker in checkers:
        if checker.cross_file and explicit_subset:
            # Whole-program analyses are only sound on the whole
            # program: a subset's narrower name-candidate sets resolve
            # calls the full tree refuses, manufacturing roots/edges —
            # and a waiver added for a subset-only phantom would read
            # as unused on the real gate. Fixture tests drive these
            # checkers through finalize() directly.
            continue
        in_scope = [f for f in parsed if checker.in_scope(f)]
        for f in in_scope:
            violations.extend(checker.check_file(f))
        violations.extend(checker.finalize(in_scope))
    # Unused waivers: a permission nothing needed anymore is drift.
    # Judged only for rules whose checkers actually ran this invocation.
    if not explicit_subset and rules is None:
        # Waiver ratchet (full unfiltered runs only): the census of
        # suppressions must match the committed ledger exactly.
        violations.extend(_ratchet_violations(parsed))
    for f in parsed:
        for w in f.waivers:
            if w.used or w.rule not in active_rules:
                continue
            if explicit_subset and any(
                c.project_level or c.cross_file
                for c in checkers
                if c.rule == w.rule
            ):
                # Project-level/cross-file rules didn't see the whole
                # tree on a subset run: a lock-discipline waiver whose
                # consuming edge runs through an unlinted file would
                # read as falsely unused (code review r12).
                continue
            violations.append(Violation(
                rule="unused-waiver", path=f.rel, line=w.line,
                message=f"waiver for {w.rule!r} matched no violation "
                        f"(reason was: {w.reason!r})",
                hint="delete the stale waiver, or move it onto the "
                     "line it should cover",
            ))
    violations.sort(key=lambda v: (v.path, v.line, v.rule))
    return violations


# -- shared AST helpers used by several checkers ---------------------------

def call_root_name(node: ast.AST) -> Optional[str]:
    """Leftmost Name of a dotted call target: `jnp.sum(x)` -> 'jnp',
    `jax.lax.psum(...)` -> 'jax', `foo(...)` -> 'foo'."""
    while isinstance(node, ast.Attribute):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def dotted_name(node: ast.AST) -> Optional[str]:
    """Render `a.b.c` as 'a.b.c' (None for non-trivial expressions)."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def const_int(node: ast.AST) -> Optional[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return node.value
    return None
