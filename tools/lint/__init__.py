"""pilint: project-invariant static analysis for the TPU serving plane.

`python -m tools.lint` runs every checker over pilosa_tpu/ and exits
0/1 with a per-rule report (file:line, rule id, fix hint). See
docs/development.md for the rule catalogue and waiver syntax, and
tools/lint/checkers/__init__.py for how to add a checker.
"""

from tools.lint.core import (  # noqa: F401
    Checker,
    SourceFile,
    Violation,
    collect_files,
    run_lint,
)
from tools.lint.checkers import make_checkers  # noqa: F401
