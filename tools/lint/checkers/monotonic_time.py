"""monotonic-time: wall-clock reads are waiver-only.

PR 4 made every deadline/duration monotonic end to end
(utils/deadline.py); a single `time.time()` fed into that arithmetic
reintroduces the NTP-step bug class the refactor removed (a 2 s clock
slew mid-query reads as a 2 s latency spike, or an instantly-expired
deadline). Since the only legitimate wall-clock uses left are epoch
STAMPS (trace span starts for cross-node ordering, /debug display
fields), the rule is total: every `time.time()` call is a violation
unless waivered with the reason it must be wall clock.
"""

from __future__ import annotations

import ast
from typing import Iterable

from tools.lint.core import Checker, SourceFile, Violation, dotted_name


class MonotonicTimeChecker(Checker):
    rule = "monotonic-time"
    doc = ("time.time() in duration/deadline math breaks under clock "
           "steps; monotonic everywhere, wall clock only by waiver")
    # Unscoped: the default tree is pilosa_tpu/ already; explicit paths
    # (fixtures, --changed) must still be checkable.
    scope = ("",)

    def check_file(self, f: SourceFile) -> Iterable[Violation]:
        for node in ast.walk(f.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            # time_ns is the same wall clock as time.time (ISSUE r15:
            # added when the epoch plane started minting wall stamps —
            # an unwaivered time_ns would dodge the rule by suffix).
            if name not in ("time.time", "time.time_ns",
                            "datetime.datetime.now",
                            "datetime.datetime.utcnow"):
                continue
            if f.waive(self.rule, node.lineno, node.end_lineno):
                continue
            yield Violation(
                rule=self.rule, path=f.rel, line=node.lineno,
                message=f"{name}() is wall clock",
                hint="use time.monotonic() (durations/deadlines) or "
                     "time.perf_counter() (fine timing); if this is a "
                     "deliberate epoch stamp, waiver it: "
                     "# lint: allow-monotonic-time(<why wall clock>)",
            )
