"""hot-serialize: per-element result-encoding loops are waiver-only.

ISSUE r14 collapsed the three Python hot loops between device readback
and socket write (whole-slab row materialization, vectorized
integer-array-to-ASCII in utils/fastjson.py, wire-bytes cache hits).
This rule keeps them collapsed: in the device-result and serving layers
(`pilosa_tpu/exec/`, `pilosa_tpu/server/`) a `.tolist()` call — one
PyLong boxed per element — or a per-element `int(...)` conversion loop
over array data is a violation unless it carries a reasoned waiver
(legitimate: schema-sized inventories, cold debug routes, the legacy
dict encoders the byte-compat tests diff against).

Two sub-rules:
- tolist: any `.tolist()` call.
- int-loop: a list/set/generator comprehension whose element is
  `int(...)` and whose iteration source involves `.tolist()`,
  `.columns()`, or `.to_array()` — i.e. re-boxing array data one
  element at a time. Comprehensions over genuinely scalar Python
  sources (query-string splits, protobuf decode lists) do not match.
"""

from __future__ import annotations

import ast
from typing import Iterable

from tools.lint.core import Checker, SourceFile, Violation

_ARRAY_SOURCES = ("tolist", "columns", "to_array")


def _iter_touches_array(comp: ast.AST) -> bool:
    for gen in comp.generators:
        for node in ast.walk(gen.iter):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _ARRAY_SOURCES
            ):
                return True
    return False


class HotSerializeChecker(Checker):
    rule = "hot-serialize"
    doc = (".tolist() / per-element int loops in the device-result and "
           "serving layers regrow the collapsed serialize phase")
    scope = ("pilosa_tpu/exec/", "pilosa_tpu/server/")

    def check_file(self, f: SourceFile) -> Iterable[Violation]:
        for node in ast.walk(f.tree):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "tolist"
                and not node.args
            ):
                if f.waive(self.rule, node.lineno, node.end_lineno):
                    continue
                yield Violation(
                    rule=self.rule, path=f.rel, line=node.lineno,
                    message=".tolist() boxes one PyLong per element",
                    hint="keep the numpy array (utils/fastjson "
                         "encode_uints/encode_varints encode arrays "
                         "directly); waiver schema-sized or cold-path "
                         "uses: # lint: allow-hot-serialize(<why>)",
                )
            elif isinstance(
                node, (ast.ListComp, ast.SetComp, ast.GeneratorExp)
            ):
                elt = node.elt
                if not (
                    isinstance(elt, ast.Call)
                    and isinstance(elt.func, ast.Name)
                    and elt.func.id == "int"
                ):
                    continue
                if not _iter_touches_array(node):
                    continue
                if f.waive(self.rule, node.lineno, node.end_lineno):
                    continue
                yield Violation(
                    rule=self.rule, path=f.rel, line=node.lineno,
                    message="per-element int(...) loop over array data",
                    hint="operate on the array (vectorized encode / "
                         "np casts); waiver deliberate cold paths: "
                         "# lint: allow-hot-serialize(<why>)",
                )
