"""error-code: every 4xx/5xx JSON body carries a machine-readable `code`.

PR 4's contract: clients (cluster/client.py most of all — it routes
retries and breaker decisions off the parsed `code`) never string-match
error text. The HTTP layer centralizes this in `_Handler._error` (code +
Retry-After on 429/503/504) and the `_CODE_BY_STATUS` fallback map; this
checker keeps new reply sites from bypassing that funnel:

- `_reply(...)` with an error status must be inside `_error`, carry a
  non-JSON content type (protobuf query errors), or pass a dict literal
  containing a "code" key.
- Retryable statuses (429/503/504) may ONLY go out through `_error` —
  a site-local reply would silently drop Retry-After.
- `_error(...)` / `APIError(status=...)` sites using a status the
  `_CODE_BY_STATUS` map doesn't know must pass an explicit code= (the
  runtime fallback would mint an uninformative "http-NNN").
- Structural: `_error` itself must keep the Retry-After branch covering
  {429, 503, 504}.
"""

from __future__ import annotations

import ast
from typing import Iterable, Optional

from tools.lint.core import Checker, SourceFile, Violation, const_int

_RETRYABLE = {429, 503, 504}


def _kwarg(call: ast.Call, name: str) -> Optional[ast.expr]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def _enclosing_functions(tree: ast.AST) -> dict[int, str]:
    """line -> name of the innermost enclosing function, for funnel
    checks ('is this call inside _error?')."""
    spans: list[tuple[int, int, str]] = []
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            spans.append((node.lineno, node.end_lineno or node.lineno, node.name))
    out: dict[int, str] = {}
    # Innermost wins: later (smaller) spans overwrite.
    for lo, hi, name in sorted(spans, key=lambda s: (s[0], -(s[1]))):
        for ln in range(lo, hi + 1):
            out[ln] = name
    return out


def _dict_has_key(node: ast.expr, key: str) -> bool:
    return isinstance(node, ast.Dict) and any(
        isinstance(k, ast.Constant) and k.value == key for k in node.keys
    )


class ErrorCodeChecker(Checker):
    rule = "error-code"
    doc = ("4xx/5xx JSON bodies must carry a `code` field and retryable "
           "statuses must route through _error for Retry-After")
    scope = ("pilosa_tpu/server/http.py", "pilosa_tpu/server/api.py")

    def check_file(self, f: SourceFile) -> Iterable[Violation]:
        fn_of_line = _enclosing_functions(f.tree)
        code_map_keys = self._code_map_keys(f)
        for node in ast.walk(f.tree):
            if not isinstance(node, ast.Call):
                continue
            callee = node.func
            name = callee.attr if isinstance(callee, ast.Attribute) else (
                callee.id if isinstance(callee, ast.Name) else None
            )
            if name == "_reply":
                yield from self._check_reply(f, node, fn_of_line)
            elif name in ("_error", "APIError"):
                yield from self._check_coded_site(
                    f, node, name, code_map_keys
                )
        if f.rel.endswith("server/http.py"):
            yield from self._check_error_funnel(f)

    # -- _reply sites ------------------------------------------------------

    def _check_reply(self, f, node: ast.Call, fn_of_line) -> Iterable[Violation]:
        status_node = _kwarg(node, "status")
        if status_node is None and len(node.args) >= 2:
            status_node = node.args[1]
        status = const_int(status_node) if status_node is not None else None
        if status is None or status < 400:
            return
        if fn_of_line.get(node.lineno) == "_error":
            return
        ctype = _kwarg(node, "content_type")
        is_json = not (
            isinstance(ctype, ast.Constant)
            and isinstance(ctype.value, str)
            and "json" not in ctype.value
        )
        if not is_json:
            return
        # Waivers are consulted only once a violation is established —
        # a waiver on a compliant reply must surface as unused-waiver,
        # not be silently eaten (code review r12).
        if status in _RETRYABLE:
            if f.waive(self.rule, node.lineno, node.end_lineno):
                return
            yield Violation(
                rule=self.rule, path=f.rel, line=node.lineno,
                message=f"direct _reply with retryable status {status} "
                        "bypasses _error (no Retry-After header)",
                hint="raise APIError(..., status=..., code=...) or call "
                     "self._error(...) so 429/503/504 carry Retry-After",
            )
            return
        body = node.args[0] if node.args else None
        if body is None or not _dict_has_key(body, "code"):
            if f.waive(self.rule, node.lineno, node.end_lineno):
                return
            yield Violation(
                rule=self.rule, path=f.rel, line=node.lineno,
                message=f"JSON error reply (status {status}) without a "
                        "literal \"code\" field",
                hint="route through self._error()/APIError so the body "
                     "carries a machine-readable code",
            )

    # -- _error / APIError status coverage ---------------------------------

    def _code_map_keys(self, f: SourceFile) -> Optional[set[int]]:
        """Keys of the _CODE_BY_STATUS dict literal (http.py); None when
        this file doesn't define it (api.py uses http.py's — the keys are
        collected per-file, so api.py sites fall back to the shared
        canonical set below)."""
        for node in ast.walk(f.tree):
            if (
                isinstance(node, ast.Assign)
                and any(
                    isinstance(t, ast.Name) and t.id == "_CODE_BY_STATUS"
                    for t in node.targets
                )
                and isinstance(node.value, ast.Dict)
            ):
                keys = {const_int(k) for k in node.value.keys}
                keys.discard(None)
                return keys
        return None

    #: api.py raises APIError without seeing http.py's map; this mirror
    #: is asserted against the real map in finalize so it cannot drift.
    CANONICAL_STATUSES = {400, 404, 409, 413, 429, 500, 501, 502, 503, 504}

    def __init__(self):
        self._seen_map_keys: Optional[set[int]] = None

    def _check_coded_site(
        self, f, node: ast.Call, name: str, map_keys: Optional[set[int]]
    ) -> Iterable[Violation]:
        if map_keys is not None:
            self._seen_map_keys = map_keys
        known = map_keys if map_keys is not None else self.CANONICAL_STATUSES
        status_node = _kwarg(node, "status")
        status = const_int(status_node) if status_node is not None else None
        if status is None:
            return  # default 400, covered
        if _kwarg(node, "code") is not None:
            return
        if name == "_error" and len(node.args) >= 3:
            return  # positional code
        if status in known:
            return
        if f.waive(self.rule, node.lineno, node.end_lineno):
            return
        yield Violation(
            rule=self.rule, path=f.rel, line=node.lineno,
            message=f"{name} with status {status} has no explicit code= "
                    "and no _CODE_BY_STATUS fallback entry",
            hint="add code=\"...\" here, or teach _CODE_BY_STATUS the "
                 "new status",
        )

    def finalize(self, files) -> Iterable[Violation]:
        if (
            self._seen_map_keys is not None
            and self._seen_map_keys != self.CANONICAL_STATUSES
        ):
            yield Violation(
                rule=self.rule, path="pilosa_tpu/server/http.py", line=1,
                message="_CODE_BY_STATUS keys diverged from the checker's "
                        f"mirror (map: {sorted(self._seen_map_keys)})",
                hint="update CANONICAL_STATUSES in "
                     "tools/lint/checkers/error_codes.py to match",
            )
        self._seen_map_keys = None

    # -- structural: the Retry-After funnel --------------------------------

    def _check_error_funnel(self, f: SourceFile) -> Iterable[Violation]:
        err_fn = None
        for node in ast.walk(f.tree):
            if isinstance(node, ast.FunctionDef) and node.name == "_error":
                err_fn = node
                break
        if err_fn is None:
            yield Violation(
                rule=self.rule, path=f.rel, line=1,
                message="server/http.py has no _error funnel method",
                hint="keep the one place that attaches code + Retry-After",
            )
            return
        mentions_retry_after = any(
            isinstance(n, ast.Constant) and n.value == "Retry-After"
            for n in ast.walk(err_fn)
        )
        covered: set[int] = set()
        for n in ast.walk(err_fn):
            if isinstance(n, (ast.Tuple, ast.Set, ast.List)):
                vals = {const_int(e) for e in n.elts}
                vals.discard(None)
                if vals & _RETRYABLE:
                    covered |= vals
        missing = _RETRYABLE - covered
        if not mentions_retry_after or missing:
            yield Violation(
                rule=self.rule, path=f.rel, line=err_fn.lineno,
                message="_error no longer attaches Retry-After for all of "
                        f"429/503/504 (missing: {sorted(missing) or 'header'})",
                hint="retryable-by-contract statuses must tell the "
                     "client when to come back (PR 4)",
            )
