"""deadline-scope: every peer RPC rides a query/operation budget.

PR 4 built the end-to-end deadline plane (utils/deadline.py): a
monotonic budget opened at HTTP ingress, threaded thread-locally to
every layer, bounding each peer RPC's socket timeout and riding
X-Pilosa-Deadline so remote nodes abandon abandoned work. But nothing
ENFORCED it — a new daemon calling `client.status(peer)` outside any
scope silently reverts to the flat client timeout, and a hung peer pins
that thread for the full 30 s with no budget accounting.

This rule pins the invariant statically: every call path from a
concurrency root (thread targets + the thread-per-request HTTP plane,
the same inventory the shared-state rule walks) into an
`InternalClient` method must pass through a `with deadline_scope(...)`
somewhere along the way. A path that reaches the client with no scope
is flagged at the call site entering the client.

Control-plane paths with a considered reason to run un-budgeted (their
socket timeout IS the budget, or the path owns retry/backoff policy
end to end) carry a waiver at that call site naming the path:
`# lint: allow-deadline-scope(control-plane <path>: <why>)`.
"""

from __future__ import annotations

import ast
from typing import Iterable

from tools.lint.callgraph import (
    CallGraph,
    FuncInfo,
    collect_thread_roots,
    walk_own,
)
from tools.lint.core import Checker, SourceFile, Violation, dotted_name

#: The peer-RPC chokepoint class: every `_do` caller lives here.
CLIENT_CLASS = "InternalClient"


def _opens_scope(expr: ast.AST) -> bool:
    """True for a `with deadline_scope(...)` context expression."""
    if isinstance(expr, ast.Call):
        dn = dotted_name(expr.func) or ""
        return dn.split(".")[-1] == "deadline_scope"
    return False


class DeadlineScopeChecker(Checker):
    rule = "deadline-scope"
    doc = ("every call path from a thread root into InternalClient must "
           "pass a `with deadline_scope(...)` (the PR 4 budget plane)")
    # Unscoped: the default tree is pilosa_tpu/ already; explicit paths
    # (fixtures, --changed) must still be checkable.
    scope = ("",)
    cross_file = True

    def check_file(self, f: SourceFile) -> Iterable[Violation]:
        return ()  # whole-program analysis; see finalize

    def _scan_sites(self, fn: FuncInfo) -> list:
        """(callee key, line, covered) per resolved call site, where
        covered means lexically inside a deadline_scope with-block."""
        sites: list = []

        def visit(node: ast.AST, covered: bool):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                return
            if isinstance(node, ast.With):
                inner = covered or any(
                    _opens_scope(item.context_expr) for item in node.items
                )
                for item in node.items:
                    visit(item.context_expr, covered)
                for stmt in node.body:
                    visit(stmt, inner)
                return
            if isinstance(node, ast.Call):
                key = self.graph.resolve_call(node, fn)
                if key is not None:
                    sites.append((key, node.lineno, covered))
            for child in ast.iter_child_nodes(node):
                visit(child, covered)

        for stmt in getattr(fn.node, "body", []):
            visit(stmt, False)
        return sites

    def finalize(self, files: list[SourceFile]) -> Iterable[Violation]:
        if not files:
            return
        self.graph = CallGraph(files)
        self.graph.collect_calls()
        roots = collect_thread_roots(self.graph)
        if not roots:
            return

        sites: dict[str, list] = {
            fid: self._scan_sites(fn) for fid, fn in self.graph.funcs.items()
        }
        is_client = {
            fid: fn.cls == CLIENT_CLASS
            for fid, fn in self.graph.funcs.items()
        }

        # BFS per root over (func, covered) states; an edge into an
        # InternalClient method with covered=False is a finding at that
        # call site. Client-internal edges are never findings (the
        # chokepoint is the boundary, not the plumbing behind it).
        findings: dict[tuple, set] = {}  # (rel, line, callee) -> roots
        for root, entries in roots.items():
            seen: set[tuple] = set()
            stack = [(e, False) for e in entries if e in self.graph.funcs]
            while stack:
                fid, covered = stack.pop()
                if (fid, covered) in seen:
                    continue
                seen.add((fid, covered))
                fn = self.graph.funcs[fid]
                for key, line, site_cov in sites.get(fid, ()):
                    eff = covered or site_cov
                    for callee in CallGraph.callee_ids(key):
                        if callee not in self.graph.funcs:
                            continue
                        if is_client.get(callee) and not is_client.get(fid):
                            if not eff:
                                short = callee.rsplit(".", 1)[-1]
                                findings.setdefault(
                                    (fn.rel, line, short), set()
                                ).add(root)
                            continue
                        if (callee, eff) not in seen:
                            stack.append((callee, eff))

        file_of = self.graph.file_of
        for (rel, line, callee), from_roots in sorted(findings.items()):
            f = file_of.get(rel)
            if f is not None and f.waive(self.rule, line):
                continue
            root_names = ", ".join(
                sorted({r.rsplit(".", 1)[-1] if "." in r else r
                        for r in from_roots})
            )
            yield Violation(
                rule=self.rule, path=rel, line=line,
                message=f"peer RPC {callee}() reachable from thread "
                        f"root(s) {root_names} with no deadline scope on "
                        "the path",
                hint="open `with deadline_scope(Deadline(budget)):` at "
                     "the operation boundary, or waive naming the "
                     "control-plane path: # lint: allow-deadline-scope("
                     "control-plane <path>: <why>)",
            )
