"""jax-dispatch: host-sync / recompile hazards in the device layers.

The serving path is dispatch-bound (ROADMAP): one stray host sync or an
unmemoized jit in exec/ or ops/ costs more than whole query's device
work, and a shape keyed on raw occupancy recompiles per batch size —
the exact failure PR 6's `_slot_bucket` power-of-two bucketing exists to
prevent. Four sub-rules:

- item-sync: `.item()` forces a device->host readback + pipeline stall;
  read back whole arrays once via np.asarray at the readback point.
- import-jnp: jnp/jax calls at module import time run device work (and
  can initialize the backend) before the CLI chose a platform.
- jit-inline: `jax.jit(...)` immediately called, or compiled in a
  function that neither memoizes nor returns the program — XLA
  recompiles on every invocation (seconds per call on real shapes).
  The blessed patterns: builder functions that RETURN the program, and
  memo stores (`d[key] = fn` / `d.setdefault(key, fn)`) anywhere in the
  enclosing function chain.
- raw-batch-len: a `len(...)` passed straight into a `*batch*` call is
  an exact-occupancy shape; route it through `_slot_bucket(len(...))`
  (or `_pad_shards`) so compiled signatures stay O(log Q).
"""

from __future__ import annotations

import ast
from typing import Iterable, Optional

from tools.lint.core import Checker, SourceFile, Violation, call_root_name, dotted_name

_BUCKET_WRAPPERS = {"_slot_bucket", "_pad_shards", "_padded_rows"}


def _parents(tree: ast.AST) -> dict[ast.AST, ast.AST]:
    out = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            out[child] = node
    return out


def _enclosing_chain(node: ast.AST, parents) -> list[ast.AST]:
    """Enclosing FunctionDefs, innermost first."""
    chain = []
    cur = parents.get(node)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            chain.append(cur)
        cur = parents.get(cur)
    return chain


def _has_memo_store(fn: ast.AST) -> bool:
    for n in ast.walk(fn):
        if isinstance(n, ast.Assign) and any(
            isinstance(t, ast.Subscript) for t in n.targets
        ):
            return True
        if (
            isinstance(n, ast.Call)
            and isinstance(n.func, ast.Attribute)
            and n.func.attr == "setdefault"
        ):
            return True
    return False


class JaxDispatchChecker(Checker):
    rule = "jax-dispatch"
    doc = ("host syncs, import-time jnp work, unmemoized jits, and "
           "unbucketed batch shapes in the device layers")
    scope = ("pilosa_tpu/exec/", "pilosa_tpu/ops/")

    def check_file(self, f: SourceFile) -> Iterable[Violation]:
        parents = _parents(f.tree)
        yield from self._check_import_scope(f)
        for node in ast.walk(f.tree):
            if not isinstance(node, ast.Call):
                continue
            yield from self._check_item(f, node)
            yield from self._check_jit(f, node, parents)
            yield from self._check_batch_len(f, node)

    # -- .item() host sync -------------------------------------------------

    def _check_item(self, f, node: ast.Call) -> Iterable[Violation]:
        if not (
            isinstance(node.func, ast.Attribute)
            and node.func.attr == "item"
            and not node.args
        ):
            return
        if f.waive(self.rule, node.lineno, node.end_lineno):
            return
        yield Violation(
            rule=self.rule, path=f.rel, line=node.lineno,
            message=".item() is a per-element device->host sync",
            hint="read back once with np.asarray(...) at the readback "
                 "boundary, or keep the value on device",
        )

    # -- import-time jnp/jax work ------------------------------------------

    def _check_import_scope(self, f: SourceFile) -> Iterable[Violation]:
        def import_time_calls(node):
            """Calls that execute at import, at ANY nesting of module-
            level control flow (try:/if:) — but never inside a function
            or lambda body, which only runs when called (a version-gate
            `try: ... except ImportError: def compat(...)` is fine)."""
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.Lambda)):
                    continue
                if isinstance(child, ast.Call):
                    root = call_root_name(child.func)
                    name = dotted_name(child.func)
                    if root in ("jnp", "jax") and name != "jax.jit":
                        yield child
                yield from import_time_calls(child)

        for node in import_time_calls(f.tree):
            if f.waive(self.rule, node.lineno, node.end_lineno):
                continue
            yield Violation(
                rule=self.rule, path=f.rel, line=node.lineno,
                message=f"{dotted_name(node.func)}(...) runs at module "
                        "import time",
                hint="device/backend work at import races platform "
                     "selection; build lazily inside a function",
            )

    # -- unmemoized / inline jit -------------------------------------------

    def _check_jit(self, f, node: ast.Call, parents) -> Iterable[Violation]:
        if dotted_name(node.func) != "jax.jit":
            return
        parent = parents.get(node)
        # jax.jit(...)(args): compiled and invoked in one expression —
        # nothing retains the program, XLA re-traces every call.
        inline_call = isinstance(parent, ast.Call) and parent.func is node
        chain = _enclosing_chain(node, parents)
        if not chain:
            return  # module-level assignment: compiled once per process
        memoized = any(_has_memo_store(fn) for fn in chain)
        returned = self._under_return(node, parents)
        if not inline_call and (memoized or returned):
            return
        if f.waive(self.rule, node.lineno, node.end_lineno):
            return
        if inline_call:
            msg = "jax.jit(...)(...) compiled and called inline"
        else:
            msg = ("jax.jit result neither memoized nor returned by a "
                   "builder")
        yield Violation(
            rule=self.rule, path=f.rel, line=node.lineno,
            message=msg,
            hint="cache the compiled program keyed by its shape "
                 "signature (see TPUBackend._program / ops/sparse.py "
                 "_get_prog)",
        )

    @staticmethod
    def _under_return(node: ast.AST, parents) -> bool:
        cur = node
        while cur is not None:
            if isinstance(cur, ast.Return):
                return True
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return False
            cur = parents.get(cur)
        return False

    # -- raw len() into batched call sites ---------------------------------

    def _check_batch_len(self, f, node: ast.Call) -> Iterable[Violation]:
        name = (
            node.func.attr if isinstance(node.func, ast.Attribute)
            else node.func.id if isinstance(node.func, ast.Name) else ""
        )
        if "batch" not in name or name in _BUCKET_WRAPPERS:
            return
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            if (
                isinstance(arg, ast.Call)
                and isinstance(arg.func, ast.Name)
                and arg.func.id == "len"
            ):
                if f.waive(self.rule, arg.lineno, arg.end_lineno):
                    continue
                yield Violation(
                    rule=self.rule, path=f.rel, line=arg.lineno,
                    message=f"raw len(...) passed to {name}(): "
                            "exact-occupancy shape recompiles per batch "
                            "size",
                    hint="wrap in _slot_bucket(len(...)) so slot counts "
                         "pad to power-of-two buckets (PR 6)",
                )
