"""config-drift: every knob wired end to end, or it ships broken.

PRs 4-10 added ~15 config knobs by hand, and the seed shipped knobs
that PARSED but were never consumed (`max-writes-per-request`,
`log-path`, `[metric] service`) or were consumed but invisible
(`client-timeout` absent from `pilosa-tpu config`'s to_dict dump). A
knob that misses one surface fails silently: an env var that doesn't
exist reads as "the flag is broken", a missing doc row reads as "the
flag doesn't exist".

The rule: every top-level scalar field of server/config.py `Config`
must round-trip through all six surfaces —

1. TOML parse (`_apply_toml`),
2. env var (`_apply_env`, spelled `PILOSA_TPU_<FIELD>`),
3. `to_dict` (the `pilosa-tpu config` validation dump),
4. `toml_text` (the `generate-config` output),
5. cli.py wiring (something actually reads `cfg.<field>`),
6. a docs/configuration.md row (knob key + env var).

Compound fields (cluster/tls dataclasses, the slo list) are owned by
their own tests and skipped here. A deliberate exception carries a
waiver on the field's definition line.
"""

from __future__ import annotations

import ast
from typing import Iterable, Optional

from tools.lint.core import REPO_ROOT, Checker, SourceFile, Violation

CONFIG_PATH = REPO_ROOT / "pilosa_tpu" / "server" / "config.py"
CLI_PATH = REPO_ROOT / "pilosa_tpu" / "cli.py"
DOC_PATH = REPO_ROOT / "docs" / "configuration.md"

#: Scalar annotations the rule audits; everything else is compound.
_SCALAR_TYPES = {"str", "int", "float", "bool"}

#: Doc spellings for knobs that live under a TOML section instead of a
#: top-level `knob-name` key.
SPECIAL_DOC_KEYS = {
    "profile_port": "profile.port",
    "anti_entropy_interval": "[anti-entropy] interval",
    "metric_service": "[metric] service",
}

#: cli.py consumption aliases: `bind` is consumed through the derived
#: host/port properties.
_CLI_ALIASES = {"bind": ("host", "port")}

ENV_PREFIX = "PILOSA_TPU_"


def _self_attr_stores(fn: ast.AST) -> set[str]:
    out = set()
    for n in ast.walk(fn):
        if isinstance(n, ast.Assign):
            for t in n.targets:
                if (
                    isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"
                ):
                    out.add(t.attr)
    return out


def _attr_loads(fn: ast.AST, receiver: Optional[str] = None) -> set[str]:
    out = set()
    for n in ast.walk(fn):
        if (
            isinstance(n, ast.Attribute)
            and isinstance(n.value, ast.Name)
            and (receiver is None or n.value.id == receiver)
        ):
            out.add(n.attr)
    return out


def _dict_string_values(fn: ast.AST, var_name: str) -> set[str]:
    """String values of a dict literal assigned to `var_name` in fn:
    the `simple` spelling->attr map in _apply_toml."""
    out = set()
    for n in ast.walk(fn):
        if isinstance(n, ast.Assign) and isinstance(n.value, ast.Dict):
            if any(isinstance(t, ast.Name) and t.id == var_name
                   for t in n.targets):
                for v in n.value.values:
                    if isinstance(v, ast.Constant) and isinstance(v.value, str):
                        out.add(v.value)
    return out


def _env_mapping_attrs(fn: ast.AST) -> set[str]:
    """First tuple elements of the `mapping` dict in _apply_env
    (attribute names; dotted sub-config entries are skipped)."""
    out = set()
    for n in ast.walk(fn):
        if isinstance(n, ast.Assign) and isinstance(n.value, ast.Dict):
            if not any(isinstance(t, ast.Name) and t.id == "mapping"
                       for t in n.targets):
                continue
            for v in n.value.values:
                if (
                    isinstance(v, ast.Tuple)
                    and v.elts
                    and isinstance(v.elts[0], ast.Constant)
                    and isinstance(v.elts[0].value, str)
                    and "." not in v.elts[0].value
                ):
                    out.add(v.elts[0].value)
    return out


def config_drift_findings(
    config_text: str,
    cli_text: Optional[str] = None,
    doc_text: Optional[str] = None,
) -> list[tuple[str, int, str]]:
    """(field attr, config.py line, missing-surface description) per
    drifted knob. Injectable inputs so the rule is testable against a
    seeded fixture without mutating the repo (the metrics-docs
    pattern). cli/doc checks are skipped when their text is None only
    if the caller explicitly passes empty strings semantics: pass ""
    to assert against 'nothing is wired'."""
    tree = ast.parse(config_text)
    cfg_cls = next(
        (n for n in ast.walk(tree)
         if isinstance(n, ast.ClassDef) and n.name == "Config"),
        None,
    )
    if cfg_cls is None:
        return []
    fields: dict[str, int] = {}
    fns: dict[str, ast.AST] = {}
    for stmt in cfg_cls.body:
        if (
            isinstance(stmt, ast.AnnAssign)
            and isinstance(stmt.target, ast.Name)
            and isinstance(stmt.annotation, ast.Name)
            and stmt.annotation.id in _SCALAR_TYPES
        ):
            fields[stmt.target.id] = stmt.lineno
        elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fns[stmt.name] = stmt

    toml_attrs: set[str] = set()
    if "_apply_toml" in fns:
        toml_attrs |= _dict_string_values(fns["_apply_toml"], "simple")
        toml_attrs |= _self_attr_stores(fns["_apply_toml"])
    env_attrs = _env_mapping_attrs(fns["_apply_env"]) if "_apply_env" in fns else set()
    todict_attrs = _attr_loads(fns["to_dict"], "self") if "to_dict" in fns else set()
    # toml_text reads through a local alias (`c = self`): collect loads
    # on ANY simple name — only scalar field names are compared anyway.
    text_attrs = _attr_loads(fns["toml_text"]) if "toml_text" in fns else set()

    cli_attrs: set[str] = set()
    if cli_text:
        cli_attrs = _attr_loads(ast.parse(cli_text), "cfg")

    findings: list[tuple[str, int, str]] = []
    for attr, line in sorted(fields.items(), key=lambda kv: kv[1]):
        knob = attr.replace("_", "-")
        if attr not in toml_attrs:
            findings.append((attr, line, "not parseable from TOML "
                                         "(_apply_toml)"))
        if attr not in env_attrs:
            findings.append((attr, line, f"no env var ({ENV_PREFIX}"
                                         f"{attr.upper()} in _apply_env)"))
        if attr not in todict_attrs:
            findings.append((attr, line, "absent from to_dict (the "
                                         "`pilosa-tpu config` dump)"))
        if attr not in text_attrs:
            findings.append((attr, line, "absent from toml_text "
                                         "(generate-config output)"))
        if cli_text is not None:
            aliases = (attr,) + _CLI_ALIASES.get(attr, ())
            if not any(a in cli_attrs for a in aliases):
                findings.append((attr, line, "never consumed in cli.py "
                                             "(a parsed-but-dead knob)"))
        if doc_text is not None:
            doc_key = SPECIAL_DOC_KEYS.get(attr, knob)
            if doc_key not in doc_text:
                findings.append((attr, line, "no docs/configuration.md "
                                             f"row for `{doc_key}`"))
            elif (attr in env_attrs
                  and f"{ENV_PREFIX}{attr.upper()}" not in doc_text):
                findings.append((attr, line, "docs row omits the env "
                                             f"var {ENV_PREFIX}"
                                             f"{attr.upper()}"))
    return findings


class ConfigDriftChecker(Checker):
    rule = "config-drift"
    doc = ("every Config knob round-trips TOML <-> env <-> to_dict <-> "
           "toml_text <-> cli wiring <-> a docs/configuration.md row")
    scope = ("pilosa_tpu",)
    project_level = True

    def finalize(self, files: list[SourceFile]) -> Iterable[Violation]:
        try:
            config_text = CONFIG_PATH.read_text()
            cli_text = CLI_PATH.read_text()
            doc_text = DOC_PATH.read_text()
        except OSError as e:
            yield Violation(
                rule=self.rule, path="pilosa_tpu/server/config.py", line=1,
                message=f"cannot read a config-drift input: {e}",
            )
            return
        rel = str(CONFIG_PATH.relative_to(REPO_ROOT))
        cfg_file = next((f for f in files if f.rel == rel), None)
        for attr, line, missing in config_drift_findings(
            config_text, cli_text, doc_text
        ):
            if cfg_file is not None and cfg_file.waive(self.rule, line):
                continue
            yield Violation(
                rule=self.rule, path=rel, line=line,
                message=f"config knob {attr.replace('_', '-')!r}: {missing}",
                hint="wire all six surfaces (TOML/env/to_dict/toml_text/"
                     "cli/docs) or waive on the field's definition line "
                     "with the reason",
            )
