"""lock-discipline: the Python stand-in for `go vet` + `-race`.

The serving plane is thread-per-request over shared registries (stats,
tracer, HBM cache, batcher queue, breaker table); the Go reference gets
the race detector for free, we get this. Two properties are enforced
statically over the WHOLE package:

1. Lock-order safety. Every `with <lock>:` block is found (locks are
   attributes/module globals assigned `threading.Lock()/RLock()/
   Condition()`), a call graph is built with conservative name
   resolution, and "holding A, (transitively) acquires B" becomes an
   edge A->B. A cycle in that graph is an AB/BA deadlock waiting for
   the right interleaving; re-acquiring a non-reentrant Lock (directly
   or through a call chain) is a guaranteed one.

2. No blocking under a lock. While any lock is held, neither the block
   body nor anything it (transitively) calls may sleep, touch a socket
   (send/recv/accept/connect/urlopen), run a subprocess, wait on an
   Event/latch, join a thread, or dispatch to the device
   (jax.device_put / block_until_ready) — the leader/follower batcher,
   breaker registry, and histogram observe paths stay lock-cheap by
   CONSTRUCTION, and this rule keeps them that way.

Static analysis of dynamic Python is an under-approximation by nature:
attribute calls resolve to the enclosing class first, then by unique
name project-wide, then by a small-union fallback; names too generic to
resolve (dict.get, list.append, ...) are skipped. That misses exotic
dispatch — it does NOT miss the `with self._lock: self.other_method()`
patterns real deadlocks are made of. False positives get a reasoned
waiver at the `with` site.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Iterable, Optional

from tools.lint.core import Checker, SourceFile, Violation, dotted_name

#: Attribute/method names far too generic to resolve by name union —
#: resolving `d.get(...)` to some class's `get` method would invent
#: call-graph edges (and from them, phantom deadlocks).
_GENERIC_NAMES = {
    "get", "set", "pop", "popitem", "popleft", "appendleft", "items",
    "keys", "values", "append", "extend", "insert", "remove", "sort",
    "reverse", "copy", "clear", "update", "setdefault", "add",
    "discard", "count", "index", "join", "split", "rsplit", "strip",
    "lstrip", "rstrip", "startswith", "endswith", "encode", "decode",
    "format", "replace", "read", "write", "readline", "readlines",
    "close", "flush", "open", "search", "match", "fullmatch",
    "findall", "finditer", "sub", "group", "groups", "start", "end",
    "partition", "rpartition", "lower", "upper", "title", "tolist",
    "astype", "reshape", "sum", "max", "min", "any", "all", "mean",
    "nonzero", "item", "wait", "acquire", "release", "locked", "name",
    "cancel", "put", "empty", "full", "qsize", "result", "submit",
    "sleep", "is_set",
    # DB-API cursor/connection methods (sqlite in store/): never the
    # project's Executor.execute, which self-resolves above.
    "execute", "executemany", "fetchone", "fetchall", "commit",
    "rollback", "cursor",
}

#: Direct blocking operations (attribute name or dotted call).
_BLOCKING_ATTRS = {
    "recv": "socket recv", "recv_into": "socket recv",
    "sendall": "socket send", "accept": "socket accept",
    "connect": "socket connect", "makefile": "socket makefile",
    "wait": "Event/Condition wait", "select": "select",
    "block_until_ready": "device sync",
}
_BLOCKING_DOTTED = {
    "time.sleep": "time.sleep",
    "urllib.request.urlopen": "urlopen",
    "subprocess.run": "subprocess", "subprocess.Popen": "subprocess",
    "subprocess.check_output": "subprocess",
    "select.select": "select",
    "jax.device_put": "device dispatch",
}
#: .join() blocks only on thread-like receivers; "".join must not match.
_JOIN_RECEIVER_HINTS = ("thread", "proc", "pool", "prewarm", "worker")

_LOCK_CTORS = {
    "threading.Lock": "Lock",
    "threading.RLock": "RLock",
    "threading.Condition": "Condition",
}


@dataclass
class _Lock:
    lock_id: str      # module.Class.attr | module.NAME | module.func.NAME
    kind: str         # Lock | RLock | Condition
    attr: str         # attribute / variable name
    rel: str
    line: int


@dataclass
class _Func:
    func_id: str                  # module.(Class.)name(.nested)
    rel: str
    node: ast.AST
    cls: Optional[str]            # enclosing class name
    #: lock ids acquired directly anywhere in the body
    acquires: set = field(default_factory=set)
    #: (callee key, lineno, held lock ids at the call site)
    calls: list = field(default_factory=list)
    #: (lineno, description, held lock ids) for direct blocking ops
    blocking: list = field(default_factory=list)
    #: (lock_id, lineno, held-before tuple) per with-site
    with_sites: list = field(default_factory=list)


def _module_name(rel: str) -> str:
    name = rel
    for prefix in ("pilosa_tpu/",):
        if name.startswith(prefix):
            name = name[len(prefix):]
    return name[:-3].replace("/", ".") if name.endswith(".py") else name


class LockDisciplineChecker(Checker):
    rule = "lock-discipline"
    doc = ("static lock-acquisition graph: no cycles, no re-acquired "
           "non-reentrant locks, no blocking calls while a lock is held")
    # Unscoped: the default tree is pilosa_tpu/ already; explicit paths
    # (fixtures, --changed) must still be checkable.
    scope = ("",)
    cross_file = True

    def check_file(self, f: SourceFile) -> Iterable[Violation]:
        return ()  # whole-project analysis; see finalize

    # -- collection --------------------------------------------------------

    def finalize(self, files: list[SourceFile]) -> Iterable[Violation]:
        if not files:
            return
        self.locks: dict[str, _Lock] = {}          # lock_id -> _Lock
        self.attr_locks: dict[str, list[str]] = {} # attr name -> lock ids
        self.funcs: dict[str, _Func] = {}
        self.methods: dict[str, list[str]] = {}    # method name -> func ids
        self.module_funcs: dict[tuple, str] = {}   # (module, name) -> id
        self.class_methods: dict[tuple, str] = {}  # (class, name) -> id
        self.file_of: dict[str, SourceFile] = {f.rel: f for f in files}

        for f in files:
            self._collect(f)
        for fn in self.funcs.values():
            self._scan_function(fn)
        # A waivered blocking site is accepted AT ITS SOURCE: drop it
        # before the fixpoint so callers of the waivered function aren't
        # re-flagged for a risk the waiver already owns (e.g. the native
        # helper's one-time lazy compile). Only lock-held sites can
        # consume a waiver — a blocking call under NO lock was never a
        # violation, so a waiver there must surface as unused-waiver
        # instead of being silently eaten (code review r12).
        for fn in self.funcs.values():
            fn.blocking = [
                (line, desc, held) for line, desc, held in fn.blocking
                if not (held and self._waived(fn.rel, line))
            ]
        trans_acq = self._transitive_acquires()
        trans_blk = self._transitive_blocking()
        yield from self._emit(files, trans_acq, trans_blk)

    def _waived(self, rel: str, line: int) -> bool:
        f = self.file_of.get(rel)
        return f is not None and f.waive(self.rule, line)

    def _collect(self, f: SourceFile) -> None:
        mod = _module_name(f.rel)

        def add_lock(lock_id, kind, attr, line):
            self.locks[lock_id] = _Lock(lock_id, kind, attr, f.rel, line)
            self.attr_locks.setdefault(attr, []).append(lock_id)

        def visit(body, path: str, cls: Optional[str]):
            for stmt in body:
                if isinstance(stmt, ast.ClassDef):
                    visit(stmt.body, f"{path}.{stmt.name}" if path else stmt.name,
                          stmt.name)
                elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    fid = f"{mod}.{path}.{stmt.name}" if path else f"{mod}.{stmt.name}"
                    fn = _Func(func_id=fid, rel=f.rel, node=stmt, cls=cls)
                    self.funcs[fid] = fn
                    self.methods.setdefault(stmt.name, []).append(fid)
                    if cls is not None:
                        self.class_methods.setdefault(
                            (cls, stmt.name), fid
                        )
                    else:
                        self.module_funcs[(mod, stmt.name)] = fid
                    # Lock assignments + nested defs inside the function.
                    self._collect_fn_locks(stmt, fid, cls, mod, add_lock)
                    visit(
                        [s for s in stmt.body
                         if isinstance(s, (ast.FunctionDef,
                                           ast.AsyncFunctionDef,
                                           ast.ClassDef))],
                        f"{path}.{stmt.name}" if path else stmt.name,
                        cls,
                    )
                elif isinstance(stmt, ast.Assign):
                    kind = self._lock_ctor(stmt.value)
                    if kind:
                        for t in stmt.targets:
                            if isinstance(t, ast.Name):
                                add_lock(f"{mod}.{t.id}", kind, t.id,
                                         stmt.lineno)

        visit(f.tree.body, "", None)

    def _collect_fn_locks(self, fn_node, fid, cls, mod, add_lock) -> None:
        """Lock assignments in THIS function body only (nested defs get
        their own pass with their own fid, so the id reflects the scope
        the name actually lives in)."""
        def walk_own(node):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.ClassDef, ast.Lambda)):
                    continue
                yield child
                yield from walk_own(child)

        for n in walk_own(fn_node):
            if not isinstance(n, ast.Assign):
                continue
            kind = self._lock_ctor(n.value)
            if not kind:
                continue
            for t in n.targets:
                if (
                    isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"
                    and cls is not None
                ):
                    add_lock(f"{mod}.{cls}.{t.attr}", kind, t.attr, n.lineno)
                elif isinstance(t, ast.Name):
                    # function-local lock (closure rendezvous)
                    add_lock(f"{fid}.{t.id}", kind, t.id, n.lineno)

    @staticmethod
    def _lock_ctor(value: ast.AST) -> Optional[str]:
        if isinstance(value, ast.Call):
            return _LOCK_CTORS.get(dotted_name(value.func) or "")
        return None

    # -- per-function scan --------------------------------------------------

    def _resolve_lock(self, expr: ast.AST, fn: _Func) -> Optional[str]:
        """lock id for a `with <expr>:` context, or None (not a lock)."""
        if isinstance(expr, ast.Attribute):
            attr = expr.attr
            candidates = self.attr_locks.get(attr, [])
            if not candidates:
                return None
            if (
                isinstance(expr.value, ast.Name)
                and expr.value.id == "self"
                or fn.cls is not None
            ):
                # self.X — or a same-class alias like `r._lock` where r
                # is the root instance: prefer the enclosing class's X.
                for c in candidates:
                    if f".{fn.cls}.{attr}" in c:
                        return c
            if len(candidates) == 1:
                return candidates[0]
            return None  # ambiguous attribute: don't invent edges
        if isinstance(expr, ast.Name):
            # innermost function-local, then enclosing funcs, then module
            parts = fn.func_id.split(".")
            for depth in range(len(parts), 0, -1):
                cand = ".".join(parts[:depth]) + f".{expr.id}"
                if cand in self.locks:
                    return cand
            mod = _module_name(fn.rel)
            return f"{mod}.{expr.id}" if f"{mod}.{expr.id}" in self.locks else None
        return None

    def _resolve_call(self, call: ast.Call, fn: _Func) -> Optional[str]:
        """callee func id, or None when unresolvable."""
        mod = _module_name(fn.rel)
        func = call.func
        if isinstance(func, ast.Name):
            fid = self.module_funcs.get((mod, func.id))
            if fid:
                return fid
            # unique project-wide module function of that name
            cands = [
                v for (m, n), v in self.module_funcs.items() if n == func.id
            ]
            return cands[0] if len(cands) == 1 else None
        if isinstance(func, ast.Attribute):
            name = func.attr
            # self.m() resolves by the enclosing class BEFORE the
            # generic-name filter: Executor.execute is a real project
            # method even though bare `.execute(` usually means a DB
            # cursor.
            if (
                isinstance(func.value, ast.Name)
                and func.value.id == "self"
                and fn.cls is not None
            ):
                fid = self.class_methods.get((fn.cls, name))
                if fid:
                    return fid
            if name in _GENERIC_NAMES or name.startswith("__"):
                return None
            cands = self.methods.get(name, [])
            if len(cands) == 1:
                return cands[0]
            if 1 < len(cands) <= 4:
                # Small SAME-MODULE union (e.g. StatsClient +
                # NopStatsClient both define gauge): a synthetic union
                # key resolved at fixpoint time. Cross-module unions are
                # refused — merging roaring's Bitmap._put with the TPU
                # cache's _put would smear device dispatch over the
                # whole host bitmap layer and invent violations.
                mods = {self.funcs[c].rel for c in cands if c in self.funcs}
                if len(mods) == 1:
                    return "|".join(sorted(cands))
            return None
        return None

    def _blocking_desc(self, call: ast.Call) -> Optional[str]:
        dn = dotted_name(call.func)
        if dn in _BLOCKING_DOTTED:
            return _BLOCKING_DOTTED[dn]
        if isinstance(call.func, ast.Attribute):
            attr = call.func.attr
            if attr in _BLOCKING_ATTRS:
                return _BLOCKING_ATTRS[attr]
            if attr == "join":
                recv = call.func.value
                rname = (recv.attr if isinstance(recv, ast.Attribute)
                         else recv.id if isinstance(recv, ast.Name) else "")
                if any(h in rname.lower() for h in _JOIN_RECEIVER_HINTS):
                    return "thread join"
        elif isinstance(call.func, ast.Name) and call.func.id == "urlopen":
            return "urlopen"
        return None

    def _scan_function(self, fn: _Func) -> None:
        def visit(node: ast.AST, held: tuple):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                return  # closures run later, not under this lock
            if isinstance(node, ast.With):
                new = []
                for item in node.items:
                    visit(item.context_expr, held)
                    lock_id = self._resolve_lock(item.context_expr, fn)
                    if lock_id is not None:
                        fn.acquires.add(lock_id)
                        fn.with_sites.append(
                            (lock_id, item.context_expr.lineno, held)
                        )
                        new.append(lock_id)
                inner = held + tuple(new)
                for stmt in node.body:
                    visit(stmt, inner)
                return
            if isinstance(node, ast.Call):
                desc = self._blocking_desc(node)
                if desc is not None:
                    fn.blocking.append((node.lineno, desc, held))
                else:
                    callee = self._resolve_call(node, fn)
                    if callee is not None:
                        fn.calls.append((callee, node.lineno, held))
            for child in ast.iter_child_nodes(node):
                visit(child, held)

        body = getattr(fn.node, "body", [])
        for stmt in body:
            visit(stmt, ())

    # -- fixpoints ----------------------------------------------------------

    def _callee_ids(self, key: str) -> list[str]:
        return key.split("|") if "|" in key else [key]

    def _transitive_acquires(self) -> dict[str, set]:
        trans = {fid: set(fn.acquires) for fid, fn in self.funcs.items()}
        changed = True
        while changed:
            changed = False
            for fid, fn in self.funcs.items():
                for key, _ln, _held in fn.calls:
                    for callee in self._callee_ids(key):
                        got = trans.get(callee)
                        if got and not got <= trans[fid]:
                            trans[fid] |= got
                            changed = True
        return trans

    def _transitive_blocking(self) -> dict[str, Optional[str]]:
        """func id -> description of a blocking op reachable from it."""
        trans: dict[str, Optional[str]] = {}
        for fid, fn in self.funcs.items():
            trans[fid] = fn.blocking[0][1] if fn.blocking else None
        changed = True
        while changed:
            changed = False
            for fid, fn in self.funcs.items():
                if trans[fid]:
                    continue
                for key, _ln, _held in fn.calls:
                    for callee in self._callee_ids(key):
                        d = trans.get(callee)
                        if d:
                            short = callee.rsplit(".", 1)[-1]
                            trans[fid] = f"{d} (via {short})"
                            changed = True
                            break
                    if trans[fid]:
                        break
        return trans

    # -- violations ---------------------------------------------------------

    def _emit(self, files, trans_acq, trans_blk) -> Iterable[Violation]:
        edges: dict[tuple, list] = {}  # (A, B) -> [(rel, line)]
        emitted: set[tuple] = set()    # (rel, line, message) dedupe
        waived = self._waived

        def once(v: Violation):
            key = (v.path, v.line, v.message)
            if key not in emitted:
                emitted.add(key)
                yield v

        for fid, fn in self.funcs.items():
            # direct nesting edges + non-reentrant re-acquisition
            for lock_id, line, held in fn.with_sites:
                for h in held:
                    if h == lock_id:
                        if self.locks[lock_id].kind == "Lock":
                            if not waived(fn.rel, line):
                                yield from once(Violation(
                                    rule=self.rule, path=fn.rel, line=line,
                                    message="re-acquires non-reentrant "
                                            f"lock {lock_id} already held",
                                    hint="guaranteed deadlock: use RLock "
                                         "or restructure",
                                ))
                    else:
                        edges.setdefault((h, lock_id), []).append(
                            (fn.rel, line)
                        )
            # call-graph edges + blocking + re-entry through calls
            for key, line, held in fn.calls:
                if not held:
                    continue
                callee_acq = set()
                for callee in self._callee_ids(key):
                    callee_acq |= trans_acq.get(callee, set())
                for h in held:
                    for b in callee_acq:
                        if b == h:
                            if self.locks[b].kind == "Lock" and not waived(fn.rel, line):
                                yield from once(Violation(
                                    rule=self.rule, path=fn.rel, line=line,
                                    message=f"call re-enters non-reentrant "
                                            f"lock {b} through "
                                            f"{key.rsplit('.', 1)[-1]}()",
                                    hint="guaranteed deadlock: hoist the "
                                         "call out of the locked region",
                                ))
                        else:
                            edges.setdefault((h, b), []).append(
                                (fn.rel, line)
                            )
                blk = None
                for callee in self._callee_ids(key):
                    blk = blk or trans_blk.get(callee)
                if blk and not waived(fn.rel, line):
                    yield from once(Violation(
                        rule=self.rule, path=fn.rel, line=line,
                        message=f"blocking call under lock "
                                f"{held[-1]}: {blk}",
                        hint="move the blocking work outside the locked "
                             "region (collect under lock, act after)",
                    ))
            for line, desc, held in fn.blocking:
                if held and not waived(fn.rel, line):
                    yield from once(Violation(
                        rule=self.rule, path=fn.rel, line=line,
                        message=f"blocking call under lock {held[-1]}: "
                                f"{desc}",
                        hint="move the blocking work outside the locked "
                             "region",
                    ))
        yield from self._cycles(edges, waived)

    def _cycles(self, edges: dict, waived) -> Iterable[Violation]:
        graph: dict[str, set] = {}
        for (a, b), _sites in edges.items():
            graph.setdefault(a, set()).add(b)
            graph.setdefault(b, set())
        # DFS cycle detection (the lock graph is tiny).
        color: dict[str, int] = {}
        stack: list[str] = []
        found: list[list[str]] = []

        def dfs(n):
            color[n] = 1
            stack.append(n)
            for m in graph.get(n, ()):
                if color.get(m, 0) == 0:
                    dfs(m)
                elif color.get(m) == 1:
                    found.append(stack[stack.index(m):] + [m])
            stack.pop()
            color[n] = 2

        for n in sorted(graph):
            if color.get(n, 0) == 0:
                dfs(n)
        seen = set()
        for cyc in found:
            key = frozenset(cyc)
            if key in seen:
                continue
            seen.add(key)
            sites = []
            for a, b in zip(cyc, cyc[1:]):
                sites.extend(edges.get((a, b), ()))
            if sites and all(waived(rel, line) for rel, line in sites):
                continue
            rel, line = sites[0] if sites else ("pilosa_tpu", 1)
            chain = " -> ".join(cyc)
            yield Violation(
                rule=self.rule, path=rel, line=line,
                message=f"lock-order cycle: {chain}",
                hint="an AB/BA deadlock under the right interleaving; "
                     "impose one global acquisition order "
                     + "; ".join(f"{r}:{l}" for r, l in sites[:4]),
            )
