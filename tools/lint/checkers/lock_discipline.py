"""lock-discipline: the Python stand-in for `go vet` + `-race`.

The serving plane is thread-per-request over shared registries (stats,
tracer, HBM cache, batcher queue, breaker table); the Go reference gets
the race detector for free, we get this. Two properties are enforced
statically over the WHOLE package:

1. Lock-order safety. Every `with <lock>:` block is found (locks are
   attributes/module globals assigned `threading.Lock()/RLock()/
   Condition()`), a call graph is built with conservative name
   resolution, and "holding A, (transitively) acquires B" becomes an
   edge A->B. A cycle in that graph is an AB/BA deadlock waiting for
   the right interleaving; re-acquiring a non-reentrant Lock (directly
   or through a call chain) is a guaranteed one.

2. No blocking under a lock. While any lock is held, neither the block
   body nor anything it (transitively) calls may sleep, touch a socket
   (send/recv/accept/connect/urlopen), run a subprocess, wait on an
   Event/latch, join a thread, or dispatch to the device
   (jax.device_put / block_until_ready) — the leader/follower batcher,
   breaker registry, and histogram observe paths stay lock-cheap by
   CONSTRUCTION, and this rule keeps them that way.

The call graph + lock inventory live in tools/lint/callgraph.py, shared
with the shared-state and deadline-scope rules (ISSUE r13); see that
module's docstring for the resolution contract and its deliberate
under-approximation. False positives get a reasoned waiver at the
`with` site.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Iterable, Optional

from tools.lint.callgraph import CallGraph, FuncInfo, LockIndex
from tools.lint.core import Checker, SourceFile, Violation, dotted_name

#: Direct blocking operations (attribute name or dotted call).
_BLOCKING_ATTRS = {
    "recv": "socket recv", "recv_into": "socket recv",
    "sendall": "socket send", "accept": "socket accept",
    "connect": "socket connect", "makefile": "socket makefile",
    "wait": "Event/Condition wait", "select": "select",
    "block_until_ready": "device sync",
}
_BLOCKING_DOTTED = {
    "time.sleep": "time.sleep",
    "urllib.request.urlopen": "urlopen",
    "subprocess.run": "subprocess", "subprocess.Popen": "subprocess",
    "subprocess.check_output": "subprocess",
    "select.select": "select",
    "jax.device_put": "device dispatch",
}
#: .join() blocks only on thread-like receivers; "".join must not match.
_JOIN_RECEIVER_HINTS = ("thread", "proc", "pool", "prewarm", "worker")


@dataclass
class _FnState:
    """Per-function lock context collected by the scan."""

    #: lock ids acquired directly anywhere in the body
    acquires: set = field(default_factory=set)
    #: (callee key, lineno, held lock ids at the call site)
    calls: list = field(default_factory=list)
    #: (lineno, description, held lock ids) for direct blocking ops
    blocking: list = field(default_factory=list)
    #: (lock_id, lineno, held-before tuple) per with-site
    with_sites: list = field(default_factory=list)


class LockDisciplineChecker(Checker):
    rule = "lock-discipline"
    doc = ("static lock-acquisition graph: no cycles, no re-acquired "
           "non-reentrant locks, no blocking calls while a lock is held")
    # Unscoped: the default tree is pilosa_tpu/ already; explicit paths
    # (fixtures, --changed) must still be checkable.
    scope = ("",)
    cross_file = True

    def check_file(self, f: SourceFile) -> Iterable[Violation]:
        return ()  # whole-project analysis; see finalize

    # -- collection --------------------------------------------------------

    def finalize(self, files: list[SourceFile]) -> Iterable[Violation]:
        if not files:
            return
        self.graph = CallGraph(files)
        self.lock_index = LockIndex(files, self.graph)
        self.file_of = self.graph.file_of
        self.state: dict[str, _FnState] = {}
        for fid, fn in self.graph.funcs.items():
            self.state[fid] = self._scan_function(fn)
        # A waivered blocking site is accepted AT ITS SOURCE: drop it
        # before the fixpoint so callers of the waivered function aren't
        # re-flagged for a risk the waiver already owns (e.g. the native
        # helper's one-time lazy compile). Only lock-held sites can
        # consume a waiver — a blocking call under NO lock was never a
        # violation, so a waiver there must surface as unused-waiver
        # instead of being silently eaten (code review r12).
        for fid, st in self.state.items():
            rel = self.graph.funcs[fid].rel
            st.blocking = [
                (line, desc, held) for line, desc, held in st.blocking
                if not (held and self._waived(rel, line))
            ]
        trans_acq = self._transitive_acquires()
        trans_blk = self._transitive_blocking()
        yield from self._emit(trans_acq, trans_blk)

    def _waived(self, rel: str, line: int) -> bool:
        f = self.file_of.get(rel)
        return f is not None and f.waive(self.rule, line)

    # -- per-function scan --------------------------------------------------

    def _blocking_desc(self, call: ast.Call) -> Optional[str]:
        dn = dotted_name(call.func)
        if dn in _BLOCKING_DOTTED:
            return _BLOCKING_DOTTED[dn]
        if isinstance(call.func, ast.Attribute):
            attr = call.func.attr
            if attr in _BLOCKING_ATTRS:
                return _BLOCKING_ATTRS[attr]
            if attr == "join":
                recv = call.func.value
                rname = (recv.attr if isinstance(recv, ast.Attribute)
                         else recv.id if isinstance(recv, ast.Name) else "")
                if any(h in rname.lower() for h in _JOIN_RECEIVER_HINTS):
                    return "thread join"
        elif isinstance(call.func, ast.Name) and call.func.id == "urlopen":
            return "urlopen"
        return None

    def _scan_function(self, fn: FuncInfo) -> _FnState:
        st = _FnState()

        def visit(node: ast.AST, held: tuple):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                return  # closures run later, not under this lock
            if isinstance(node, ast.With):
                new = []
                for item in node.items:
                    visit(item.context_expr, held)
                    lock_id = self.lock_index.resolve(item.context_expr, fn)
                    if lock_id is not None:
                        st.acquires.add(lock_id)
                        st.with_sites.append(
                            (lock_id, item.context_expr.lineno, held)
                        )
                        new.append(lock_id)
                inner = held + tuple(new)
                for stmt in node.body:
                    visit(stmt, inner)
                return
            if isinstance(node, ast.Call):
                desc = self._blocking_desc(node)
                if desc is not None:
                    st.blocking.append((node.lineno, desc, held))
                else:
                    callee = self.graph.resolve_call(node, fn)
                    if callee is not None:
                        st.calls.append((callee, node.lineno, held))
            for child in ast.iter_child_nodes(node):
                visit(child, held)

        for stmt in getattr(fn.node, "body", []):
            visit(stmt, ())
        return st

    # -- fixpoints ----------------------------------------------------------

    def _transitive_acquires(self) -> dict[str, set]:
        trans = {fid: set(st.acquires) for fid, st in self.state.items()}
        changed = True
        while changed:
            changed = False
            for fid, st in self.state.items():
                for key, _ln, _held in st.calls:
                    for callee in CallGraph.callee_ids(key):
                        got = trans.get(callee)
                        if got and not got <= trans[fid]:
                            trans[fid] |= got
                            changed = True
        return trans

    def _transitive_blocking(self) -> dict[str, Optional[str]]:
        """func id -> description of a blocking op reachable from it."""
        trans: dict[str, Optional[str]] = {}
        for fid, st in self.state.items():
            trans[fid] = st.blocking[0][1] if st.blocking else None
        changed = True
        while changed:
            changed = False
            for fid, st in self.state.items():
                if trans[fid]:
                    continue
                for key, _ln, _held in st.calls:
                    for callee in CallGraph.callee_ids(key):
                        d = trans.get(callee)
                        if d:
                            short = callee.rsplit(".", 1)[-1]
                            trans[fid] = f"{d} (via {short})"
                            changed = True
                            break
                    if trans[fid]:
                        break
        return trans

    # -- violations ---------------------------------------------------------

    def _emit(self, trans_acq, trans_blk) -> Iterable[Violation]:
        edges: dict[tuple, list] = {}  # (A, B) -> [(rel, line)]
        emitted: set[tuple] = set()    # (rel, line, message) dedupe
        waived = self._waived
        locks = self.lock_index.locks

        def once(v: Violation):
            key = (v.path, v.line, v.message)
            if key not in emitted:
                emitted.add(key)
                yield v

        for fid, st in self.state.items():
            rel = self.graph.funcs[fid].rel
            # direct nesting edges + non-reentrant re-acquisition
            for lock_id, line, held in st.with_sites:
                for h in held:
                    if h == lock_id:
                        if locks[lock_id].kind == "Lock":
                            if not waived(rel, line):
                                yield from once(Violation(
                                    rule=self.rule, path=rel, line=line,
                                    message="re-acquires non-reentrant "
                                            f"lock {lock_id} already held",
                                    hint="guaranteed deadlock: use RLock "
                                         "or restructure",
                                ))
                    else:
                        edges.setdefault((h, lock_id), []).append(
                            (rel, line)
                        )
            # call-graph edges + blocking + re-entry through calls
            for key, line, held in st.calls:
                if not held:
                    continue
                callee_acq = set()
                for callee in CallGraph.callee_ids(key):
                    callee_acq |= trans_acq.get(callee, set())
                for h in held:
                    for b in callee_acq:
                        if b == h:
                            if locks[b].kind == "Lock" and not waived(rel, line):
                                yield from once(Violation(
                                    rule=self.rule, path=rel, line=line,
                                    message=f"call re-enters non-reentrant "
                                            f"lock {b} through "
                                            f"{key.rsplit('.', 1)[-1]}()",
                                    hint="guaranteed deadlock: hoist the "
                                         "call out of the locked region",
                                ))
                        else:
                            edges.setdefault((h, b), []).append(
                                (rel, line)
                            )
                blk = None
                for callee in CallGraph.callee_ids(key):
                    blk = blk or trans_blk.get(callee)
                if blk and not waived(rel, line):
                    yield from once(Violation(
                        rule=self.rule, path=rel, line=line,
                        message=f"blocking call under lock "
                                f"{held[-1]}: {blk}",
                        hint="move the blocking work outside the locked "
                             "region (collect under lock, act after)",
                    ))
            for line, desc, held in st.blocking:
                if held and not waived(rel, line):
                    yield from once(Violation(
                        rule=self.rule, path=rel, line=line,
                        message=f"blocking call under lock {held[-1]}: "
                                f"{desc}",
                        hint="move the blocking work outside the locked "
                             "region",
                    ))
        yield from self._cycles(edges, waived)

    def _cycles(self, edges: dict, waived) -> Iterable[Violation]:
        graph: dict[str, set] = {}
        for (a, b), _sites in edges.items():
            graph.setdefault(a, set()).add(b)
            graph.setdefault(b, set())
        # DFS cycle detection (the lock graph is tiny).
        color: dict[str, int] = {}
        stack: list[str] = []
        found: list[list[str]] = []

        def dfs(n):
            color[n] = 1
            stack.append(n)
            for m in graph.get(n, ()):
                if color.get(m, 0) == 0:
                    dfs(m)
                elif color.get(m) == 1:
                    found.append(stack[stack.index(m):] + [m])
            stack.pop()
            color[n] = 2

        for n in sorted(graph):
            if color.get(n, 0) == 0:
                dfs(n)
        seen = set()
        for cyc in found:
            key = frozenset(cyc)
            if key in seen:
                continue
            seen.add(key)
            sites = []
            for a, b in zip(cyc, cyc[1:]):
                sites.extend(edges.get((a, b), ()))
            if sites and all(waived(rel, line) for rel, line in sites):
                continue
            rel, line = sites[0] if sites else ("pilosa_tpu", 1)
            chain = " -> ".join(cyc)
            yield Violation(
                rule=self.rule, path=rel, line=line,
                message=f"lock-order cycle: {chain}",
                hint="an AB/BA deadlock under the right interleaving; "
                     "impose one global acquisition order "
                     + "; ".join(f"{r}:{l}" for r, l in sites[:4]),
            )
