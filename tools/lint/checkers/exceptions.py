"""except-exception: broad catches must re-raise, count, or be waivered.

A `except Exception` that swallows silently is how the Go reference's
"panic trap" pattern degrades in Python: the crash disappears and the
symptom surfaces three layers away as a stuck thread or a stale gauge.
The contract here (ISSUE r12 checker 5): every broad handler must either

- re-raise (any `raise` in the handler body),
- deliver the exception onward (assign the caught exception object to
  something — the batcher's leg.error rendezvous, collected error
  lists), which is a re-raise by proxy at the waiter,
- count into an `*_errors_total` / `*_failures_total` / `*_aborts_total`
  metric so the crash is on /metrics, or
- carry a waiver naming the crash barrier it implements
  (`# lint: allow-except-exception(<barrier>)`).

Bare `except:` is always a violation (it eats KeyboardInterrupt).
"""

from __future__ import annotations

import ast
from typing import Iterable

from tools.lint.core import Checker, SourceFile, Violation

_COUNTED_SUFFIXES = ("_errors_total", "_failures_total", "_aborts_total")


def _counts_error_metric(handler: ast.ExceptHandler) -> bool:
    for n in ast.walk(handler):
        if (
            isinstance(n, ast.Call)
            and isinstance(n.func, ast.Attribute)
            and n.func.attr == "count"
            and n.args
            and isinstance(n.args[0], ast.Constant)
            and isinstance(n.args[0].value, str)
            and n.args[0].value.endswith(_COUNTED_SUFFIXES)
        ):
            return True
    return False


def _reraises(handler: ast.ExceptHandler) -> bool:
    return any(isinstance(n, ast.Raise) for n in ast.walk(handler))


def _delivers(handler: ast.ExceptHandler) -> bool:
    """The caught exception object is stored somewhere (error rendezvous
    / collected per-leg error) rather than dropped."""
    name = handler.name
    if not name:
        return False
    for n in ast.walk(handler):
        if isinstance(n, ast.Assign):
            for sub in ast.walk(n.value):
                if isinstance(sub, ast.Name) and sub.id == name:
                    return True
        if isinstance(n, ast.Call):
            # e.g. failures.append({"error": str(e)}) — collected.
            for sub in ast.walk(n):
                if isinstance(sub, ast.Name) and sub.id == name:
                    return True
    return False


class ExceptDisciplineChecker(Checker):
    rule = "except-exception"
    doc = ("broad `except Exception` must re-raise, deliver/collect the "
           "error, count an *_errors_total metric, or waiver the barrier")
    # Unscoped: the default tree is pilosa_tpu/ already; explicit paths
    # (fixtures, --changed) must still be checkable.
    scope = ("",)

    def check_file(self, f: SourceFile) -> Iterable[Violation]:
        for node in ast.walk(f.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if node.type is None:
                if f.waive(self.rule, node.lineno):
                    continue
                yield Violation(
                    rule=self.rule, path=f.rel, line=node.lineno,
                    message="bare `except:` catches KeyboardInterrupt/"
                            "SystemExit",
                    hint="catch Exception at most (and then re-raise, "
                         "count, or waiver)",
                )
                continue
            if not (
                isinstance(node.type, ast.Name)
                and node.type.id in ("Exception", "BaseException")
            ):
                continue
            if _reraises(node) or _counts_error_metric(node) or _delivers(node):
                continue
            if f.waive(self.rule, node.lineno):
                continue
            yield Violation(
                rule=self.rule, path=f.rel, line=node.lineno,
                message=f"broad `except {node.type.id}` swallows the "
                        "error silently",
                hint="narrow the exception tuple, re-raise, count an "
                     "*_errors_total metric, or waiver the crash "
                     "barrier: # lint: allow-except-exception(<barrier>)",
            )
