"""shared-state: whole-program race analysis over thread roots.

The Go reference runs under `-race`; this is the static stand-in. The
package spawns threads at ~25 sites (batcher leader/follower drain,
snapshot rewriter, resize heartbeat/migration workers, broadcast
fan-out, sync daemons, monitor/profiler loops, the thread-per-request
HTTP plane). Every one is a ROOT; every function conservatively
reachable from a root runs concurrently with every function reachable
from a DIFFERENT root. Any piece of shared state — a `self.<attr>` or
a module global — written from one root while another root writes or
reads it, with no lock common to every access path, is a data race
waiting for the right interleaving.

What counts as a write:

- augmented assignment (`self.n += 1`) — a read-modify-write, never
  atomic;
- assignment whose right-hand side reads the same attribute
  (`self.n = self.n + 1`) — the same RMW spelled long-hand;
- mutation of the referenced container: subscript stores
  (`self.d[k] = v`), `del self.d[k]`, and mutating method calls
  (`self.q.append(x)`, `self.s.add(y)`, ...).

What is BLESSED (not a write):

- plain assignment of an immutable value (None/bool/number/string,
  tuple/frozenset literal or constructor): a single GIL-atomic
  STORE_ATTR publishing an immutable object — the documented
  immutable-swap idiom. Readers see the old value or the new one,
  never a torn one.
- any store inside `__init__`/`__post_init__`: construction
  happens-before the object is handed to another thread (assign-once-
  before-start).
- accesses in functions no root reaches: setup code on the main thread
  (cli wiring, daemon .start() methods) is sequenced before the threads
  exist.

Plain assignment of a MUTABLE value (`self.cache = {}`) from a root IS
recorded as a write: the store itself is atomic, but a concurrent
reader may mutate or iterate the old object while the writer swaps —
whether that is safe is exactly the judgement a reasoned
`# lint: allow-shared-state(...)` waiver should record.

A common lock means: some one lock id is held (lexically, or at every
call site leading to the function — the `always_held` intersection
fixpoint) at EVERY access to the state key, across all roots.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Iterable, Optional

from tools.lint.callgraph import (
    CallGraph,
    FuncInfo,
    LockIndex,
    collect_thread_roots,
    module_name,
    walk_own,
)
from tools.lint.core import Checker, SourceFile, Violation, dotted_name

#: Method names that mutate their receiver in place. Calling one on a
#: shared attribute is a write to that attribute's object.
MUTATING_METHODS = {
    "append", "appendleft", "add", "update", "pop", "popleft",
    "popitem", "remove", "discard", "extend", "insert", "clear",
    "setdefault", "sort", "reverse",
}

#: Constructors whose result is immutable: assigning one is an atomic
#: publish (the blessed swap idiom). `next` covers the itertools.count
#: atomic-generation idiom (`self.version = next(_counter)`).
_IMMUTABLE_CTORS = {"tuple", "frozenset", "frozendict", "bool", "int",
                    "float", "str", "bytes", "next", "len", "id"}

#: Functions whose body is construction: stores there happen before the
#: object escapes to another thread. `open` is this project's storage
#: lifecycle hook — an object is published to the holder tree only
#: AFTER open() returns (create_*_if_not_exists inserts under its lock),
#: so open-time stores are sequenced before any concurrent access.
#: These functions also act as a PUBLICATION BARRIER for reachability:
#: code they call runs during construction, so roots do not "reach"
#: shared state through them.
_CTOR_FUNCS = {"__init__", "__post_init__", "__new__", "open"}

@dataclass
class _Access:
    key: str          # module.Class.attr | module.GLOBAL
    kind: str         # store | load
    func_id: str
    rel: str
    line: int
    held: tuple       # lock ids held lexically at the site


class SharedStateChecker(Checker):
    rule = "shared-state"
    doc = ("state written from one thread root and touched from another "
           "must share a lock on every access path (static -race)")
    # Unscoped: the default tree is pilosa_tpu/ already; explicit paths
    # (fixtures, --changed) must still be checkable.
    scope = ("",)
    cross_file = True

    def check_file(self, f: SourceFile) -> Iterable[Violation]:
        return ()  # whole-program analysis; see finalize

    # -- access collection -------------------------------------------------

    def _immutable_rhs(self, value: ast.AST) -> bool:
        if isinstance(value, ast.Constant):
            return True
        if isinstance(value, ast.Tuple):
            return all(self._immutable_rhs(e) for e in value.elts)
        if isinstance(value, (ast.Compare, ast.BoolOp, ast.UnaryOp)):
            return True
        if isinstance(value, ast.IfExp):
            return self._immutable_rhs(value.body) and self._immutable_rhs(
                value.orelse
            )
        if isinstance(value, ast.Call):
            root = value.func
            name = (root.id if isinstance(root, ast.Name)
                    else root.attr if isinstance(root, ast.Attribute)
                    else "")
            return name in _IMMUTABLE_CTORS
        return False

    def _self_attr(self, node: ast.AST, fn: FuncInfo) -> Optional[str]:
        """state key for `self.<attr>`, skipping lock attributes (the
        lock-discipline rule owns those) and threading.local attributes
        (thread-confined by construction)."""
        if (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"
            and fn.cls is not None
        ):
            if node.attr in self.lock_attrs or node.attr in self.local_attrs:
                return None
            return f"{module_name(fn.rel)}.{fn.cls}.{node.attr}"
        return None

    def _scan_accesses(self, fn: FuncInfo) -> list[_Access]:
        out: list[_Access] = []
        blessed_ctor = fn.node.name in _CTOR_FUNCS
        if blessed_ctor:
            # Construction is sequenced before publication: neither its
            # stores nor its loads race anything.
            return out
        globals_declared: set[str] = set()
        for n in walk_own(fn.node):
            if isinstance(n, ast.Global):
                globals_declared.update(n.names)

        def rec(key, kind, line, held):
            out.append(_Access(key=key, kind=kind, func_id=fn.func_id,
                               rel=fn.rel, line=line, held=held))

        def mentions_attr(value: ast.AST, key: str) -> bool:
            for sub in ast.walk(value):
                if self._self_attr(sub, fn) == key:
                    return True
            return False

        def store_target(t: ast.AST, line, held, value=None):
            """One assignment target: attr store, subscript-on-attr
            store, or declared-global store. (Ctor functions never get
            here — the early return above skips their whole scan.)"""
            if isinstance(t, ast.Tuple):
                for e in t.elts:
                    store_target(e, line, held, None)
                return
            key = self._self_attr(t, fn)
            if key is not None:
                if value is not None and self._immutable_rhs(value) \
                        and not mentions_attr(value, key):
                    return  # atomic publish of an immutable value
                rec(key, "store", line, held)
                return
            if isinstance(t, (ast.Subscript, ast.Attribute)) and not isinstance(
                t, ast.Name
            ):
                # self.d[k] = v / self.obj.field = v: mutation of the
                # object a shared attribute references.
                base = t.value
                while isinstance(base, (ast.Subscript, ast.Attribute)):
                    key = self._self_attr(base, fn)
                    if key is not None:
                        rec(key, "store", line, held)
                        return
                    base = base.value
                return
            if isinstance(t, ast.Name) and t.id in globals_declared:
                mod = module_name(fn.rel)
                if value is not None and self._immutable_rhs(value):
                    return
                rec(f"{mod}.{t.id}", "store", line, held)

        def visit(node: ast.AST, held: tuple):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                return
            if isinstance(node, ast.With):
                new = []
                for item in node.items:
                    visit(item.context_expr, held)
                    lock_id = self.lock_index.resolve(item.context_expr, fn)
                    if lock_id is not None:
                        new.append(lock_id)
                inner = held + tuple(new)
                for stmt in node.body:
                    visit(stmt, inner)
                return
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    store_target(t, node.lineno, held, node.value)
                visit(node.value, held)
                return
            if isinstance(node, ast.AugAssign):
                store_target(node.target, node.lineno, held, None)
                visit(node.value, held)
                return
            if isinstance(node, ast.Delete):
                for t in node.targets:
                    store_target(t, node.lineno, held, None)
                return
            if isinstance(node, ast.Call):
                # self.q.append(x): mutation of the shared container.
                if isinstance(node.func, ast.Attribute) \
                        and node.func.attr in MUTATING_METHODS:
                    key = self._self_attr(node.func.value, fn)
                    if key is not None:
                        rec(key, "store", node.lineno, held)
            if isinstance(node, ast.Attribute) and isinstance(
                node.ctx, ast.Load
            ):
                key = self._self_attr(node, fn)
                if key is not None:
                    rec(key, "load", node.lineno, held)
            if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load) \
                    and node.id in self.mutable_globals.get(
                        module_name(fn.rel), set()):
                rec(f"{module_name(fn.rel)}.{node.id}", "load",
                    node.lineno, held)
            for child in ast.iter_child_nodes(node):
                visit(child, held)

        for stmt in getattr(fn.node, "body", []):
            visit(stmt, ())
        return out

    # -- always-held fixpoint ----------------------------------------------

    def _always_held(self, roots: dict[str, set[str]],
                     calls: dict[str, list]) -> dict[str, frozenset]:
        """lock ids held at EVERY call path into each function
        (intersection over call edges; root entries start empty-handed:
        a fresh thread inherits no locks)."""
        held: dict[str, Optional[frozenset]] = {}
        entry_ids = set().union(*roots.values()) if roots else set()
        for fid in entry_ids:
            held[fid] = frozenset()
        changed = True
        while changed:
            changed = False
            for fid, sites in calls.items():
                base = held.get(fid)
                if base is None:
                    continue
                for key, _line, site_held in sites:
                    for callee in CallGraph.callee_ids(key):
                        if callee not in self.graph.funcs:
                            continue
                        incoming = base | frozenset(site_held)
                        prev = held.get(callee)
                        nxt = incoming if prev is None else prev & incoming
                        if nxt != prev:
                            held[callee] = nxt
                            changed = True
        return {fid: h for fid, h in held.items() if h is not None}

    # -- finalize ----------------------------------------------------------

    def finalize(self, files: list[SourceFile]) -> Iterable[Violation]:
        if not files:
            return
        self.graph = CallGraph(files)
        self.graph.collect_calls()
        self.lock_index = LockIndex(files, self.graph)
        self.lock_attrs = set(self.lock_index.attr_locks)
        self.file_of = self.graph.file_of

        # threading.local() attributes are thread-confined by design.
        self.local_attrs: set[str] = set()
        for fn in self.graph.funcs.values():
            for n in walk_own(fn.node):
                if isinstance(n, ast.Assign) and isinstance(n.value, ast.Call):
                    dn = dotted_name(n.value.func) or ""
                    if dn in ("threading.local", "local"):
                        for t in n.targets:
                            if isinstance(t, ast.Attribute):
                                self.local_attrs.add(t.attr)

        # Mutable module globals: names some function re-binds via
        # `global` (everything else at module scope is config/constants).
        self.mutable_globals: dict[str, set[str]] = {}
        for fn in self.graph.funcs.values():
            for n in walk_own(fn.node):
                if isinstance(n, ast.Global):
                    self.mutable_globals.setdefault(
                        module_name(fn.rel), set()
                    ).update(n.names)

        roots = collect_thread_roots(self.graph)
        if not roots:
            return
        reach = {name: self._reachable(entries)
                 for name, entries in roots.items()}

        # Per-function lock-context call sites (for always_held) and
        # accesses.
        calls: dict[str, list] = {}
        accesses: dict[str, list[_Access]] = {}
        touched = set().union(*reach.values())
        for fid in touched:
            fn = self.graph.funcs[fid]
            calls[fid] = self._scan_calls_with_locks(fn)
            accesses[fid] = self._scan_accesses(fn)
        always = self._always_held(roots, calls)

        # Group accesses by state key, tagged with every root that
        # reaches the access's function.
        by_key: dict[str, list[tuple[str, _Access, frozenset]]] = {}
        for root, fids in reach.items():
            for fid in fids:
                for acc in accesses.get(fid, ()):
                    eff = frozenset(acc.held) | always.get(fid, frozenset())
                    by_key.setdefault(acc.key, []).append((root, acc, eff))

        for key in sorted(by_key):
            recs = by_key[key]
            store_roots = {r for r, a, _e in recs if a.kind == "store"}
            all_roots = {r for r, _a, _e in recs}
            if not store_roots:
                continue
            # racing = a store in one root plus any access in another.
            if len(store_roots) < 2 and not (
                store_roots and len(all_roots) > 1
            ):
                continue
            common = None
            for _r, _a, eff in recs:
                common = eff if common is None else common & eff
            if common:
                continue  # one lock guards every access path
            # Deterministic primary site: first store by (rel, line).
            stores = sorted(
                (a for _r, a, _e in recs if a.kind == "store"),
                key=lambda a: (a.rel, a.line),
            )
            primary = stores[0]
            f = self.file_of.get(primary.rel)
            if f is not None and f.waive(self.rule, primary.line):
                continue
            others = sorted(
                {(r, a.rel, a.line) for r, a, _e in recs
                 if (a.rel, a.line) != (primary.rel, primary.line)},
            )[:3]
            root_names = ", ".join(
                sorted({r.rsplit(".", 1)[-1] if "." in r else r
                        for r in all_roots})
            )
            detail = "; ".join(
                f"{r.rsplit('.', 1)[-1] if '.' in r else r} at {rel}:{line}"
                for r, rel, line in others
            )
            yield Violation(
                rule=self.rule, path=primary.rel, line=primary.line,
                message=f"shared state {key} written here and touched "
                        f"from other thread roots ({root_names}) with no "
                        f"common lock ({detail})",
                hint="guard every access with one lock, or publish via "
                     "immutable swap; if the interleaving is provably "
                     "safe, waive with the reason: "
                     "# lint: allow-shared-state(<why>)",
            )

    def _reachable(self, entries: set[str]) -> set[str]:
        """graph.reachable with the publication barrier: construction
        functions (_CTOR_FUNCS) do not propagate concurrency — the code
        they call runs before the object is handed to another thread.
        (A thread whose TARGET is a ctor func still propagates.)"""
        seen: set[str] = set()
        stack = [e for e in entries if e in self.graph.funcs]
        while stack:
            fid = stack.pop()
            if fid in seen:
                continue
            seen.add(fid)
            fn = self.graph.funcs[fid]
            if fn.node.name in _CTOR_FUNCS and fid not in entries:
                continue
            for key, _ln in fn.calls:
                for callee in CallGraph.callee_ids(key):
                    if callee in self.graph.funcs and callee not in seen:
                        stack.append(callee)
        return seen

    def _scan_calls_with_locks(self, fn: FuncInfo) -> list:
        """(callee key, line, held lock ids) per call site — the lock-
        aware variant of FuncInfo.calls, for the always_held fixpoint."""
        sites: list = []

        def visit(node: ast.AST, held: tuple):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                return
            if isinstance(node, ast.With):
                new = []
                for item in node.items:
                    visit(item.context_expr, held)
                    lock_id = self.lock_index.resolve(item.context_expr, fn)
                    if lock_id is not None:
                        new.append(lock_id)
                inner = held + tuple(new)
                for stmt in node.body:
                    visit(stmt, inner)
                return
            if isinstance(node, ast.Call):
                key = self.graph.resolve_call(node, fn)
                if key is not None:
                    sites.append((key, node.lineno, held))
            for child in ast.iter_child_nodes(node):
                visit(child, held)

        for stmt in getattr(fn.node, "body", []):
            visit(stmt, ())
        return sites
