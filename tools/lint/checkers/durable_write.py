"""durable-write: holder-data-dir writes use crash-safe idioms only.

ISSUE r8's recovery contract (core/fragment.py open) only holds if every
byte under the data dir got there one of two ways:

- **tmp file + os.replace** — whole-file rewrites (snapshots, .meta,
  .cache, .available.shards) land atomically: a crash leaves either the
  old complete file or the new complete file, never a torn prefix the
  next open refuses.
- **unbuffered append** (`open(..., "a?b", buffering=0)`) — the WAL
  idiom (`_WalFile`/`OpWriter`): each checksummed record hits the OS in
  order, and a crash mid-append produces exactly the torn-tail shape
  replay recovery truncates away.

Anything else — a truncating write with no rename, a buffered append —
is a write a crash can tear into a state recovery was never specified
for. The rule is structural, per enclosing function: a write-mode
`open()` must share its function with an `os.replace(...)` call, and an
append-mode `open()` must pass `buffering=0` (or share the function
with an `os.replace`, for the snapshot's tail splice into the temp
file). Reads are ignored. Scope: the packages that write under the
holder data dir (core/, roaring/, store/).
"""

from __future__ import annotations

import ast
from typing import Iterable, Optional

from tools.lint.core import Checker, SourceFile, Violation, dotted_name


def _open_mode(call: ast.Call) -> Optional[str]:
    """The literal mode of a builtin open() call, or None when it is not
    an open() / the mode is not a string constant (default 'r')."""
    if not (isinstance(call.func, ast.Name) and call.func.id == "open"):
        return None
    mode_node: Optional[ast.expr] = None
    if len(call.args) >= 2:
        mode_node = call.args[1]
    else:
        for kw in call.keywords:
            if kw.arg == "mode":
                mode_node = kw.value
    if mode_node is None:
        return "r"
    if isinstance(mode_node, ast.Constant) and isinstance(mode_node.value, str):
        return mode_node.value
    return None  # computed mode: out of static reach


def _has_unbuffered(call: ast.Call) -> bool:
    if len(call.args) >= 3:
        a = call.args[2]
        return isinstance(a, ast.Constant) and a.value == 0
    for kw in call.keywords:
        if kw.arg == "buffering":
            return isinstance(kw.value, ast.Constant) and kw.value.value == 0
    return False


class DurableWriteChecker(Checker):
    rule = "durable-write"
    doc = ("data-dir writes must be tmp-file + os.replace (atomic "
           "rewrite) or unbuffered append (the OpWriter WAL idiom)")
    #: The holder-data-dir writers. Other packages (bench artifacts,
    #: profiler dumps) are not under the recovery contract. cluster/
    #: joined the scope with the persisted-topology file (ISSUE r9):
    #: .topology lives in the data dir and a torn write there would
    #: break the very restart it exists to survive.
    scope = (
        "pilosa_tpu/core/",
        "pilosa_tpu/roaring/",
        "pilosa_tpu/store/",
        "pilosa_tpu/cluster/",
        "tests/lint_fixtures/",  # so the seeded fixture stays checkable
    )

    def check_file(self, f: SourceFile) -> Iterable[Violation]:
        for fn in ast.walk(f.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            has_replace = any(
                isinstance(n, ast.Call)
                and dotted_name(n.func) == "os.replace"
                for n in ast.walk(fn)
            )
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                mode = _open_mode(node)
                if mode is None or not set(mode) & set("wxa+"):
                    continue
                if "a" in mode and "+" not in mode and (
                    _has_unbuffered(node) or has_replace
                ):
                    continue  # WAL append / snapshot tail splice
                if "a" not in mode and has_replace:
                    continue  # tmp + os.replace rewrite
                if f.waive(self.rule, node.lineno, node.end_lineno):
                    continue
                yield Violation(
                    rule=self.rule, path=f.rel, line=node.lineno,
                    message=(
                        f"open(..., {mode!r}) under the holder data dir "
                        "without a crash-safe idiom"
                    ),
                    hint=(
                        "write a tmp file and os.replace() it in the same "
                        "function (atomic rewrite), or append unbuffered "
                        "(buffering=0) through an attached OpWriter; if "
                        "this write is genuinely outside the recovery "
                        "contract: # lint: allow-durable-write(<why>)"
                    ),
                )
