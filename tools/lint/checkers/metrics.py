"""metric-docs + metric-tags: the metric plane's two static rails.

metric-docs is the PR 3 drift check (tools/check_metrics_docs.py,
now a thin shim over this module): every metric the code emits must be
catalogued in docs/observability.md and every catalogued name must have
an emitter. Project-level — it reads the whole source tree and the doc.

metric-tags is the cardinality rule: tag KEYS must come from the
documented vocabulary below (a new key is a conscious schema decision,
not a typo), and tag VALUES must never be raw request content — a query
string or peer URL as a tag value mints an unbounded series per distinct
request and OOMs the in-memory registry (the classic cardinality bomb).
"""

from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Iterable, Optional

from tools.lint.core import REPO_ROOT, Checker, SourceFile, Violation

SRC_DIR = REPO_ROOT / "pilosa_tpu"
DOC = REPO_ROOT / "docs" / "observability.md"

# -- metric-docs scan (shared with the tools/check_metrics_docs.py shim) ---

#: Metric families emitted with computed (f-string) names: the checker
#: cannot read them statically, so each must keep a doc mention of the
#: spelled-out family (asserted below so the exemption itself can't rot).
DYNAMIC_FAMILIES = {
    # executor.py: stats.count(f"query_{call.name}_total")
    "query_<Call>_total",
}

#: A doc token must end in one of these to be treated as a metric name
#: (after stripping histogram/exporter suffixes, so a plain JSON field
#: like `device_count` does not match).
METRIC_SUFFIXES = (
    "_total", "_seconds", "_bytes", "_pending", "_done",
    "_inflight", "_up", "_fds", "_threads", "_nodes", "_fields",
    "_shards", "_evictions", "_rederives", "_state",
    "_occupancy", "_queries", "_ops", "_entries",
    "_programs", "_live", "_heat", "_depth",
)

_CALL_RE = re.compile(
    r"""\.(?:count|gauge|timing|histogram|timer|remove_gauge)\(\s*
        ["']([a-z][a-z0-9_.]*)["']""",
    re.VERBOSE,
)

_TOKEN_RE = re.compile(r"`([^`\n]+)`")

_EXPORT_SUFFIX_RE = re.compile(r"_(?:bucket|count|sum|p50|p95|p99|p999)$")

#: Series synthesized as literal exposition lines (no StatsClient call):
#: the /metrics/cluster scrape-health pair. Each must still appear as a
#: literal in the source, which source_metrics verifies.
SYNTHESIZED = ("cluster_scrape_up", "cluster_scrape_seconds")


def source_metrics(src_dir: Optional[Path] = None) -> set[str]:
    names: set[str] = set()
    all_text = []
    for path in sorted((src_dir or SRC_DIR).rglob("*.py")):
        text = path.read_text()
        all_text.append(text)
        for m in _CALL_RE.finditer(text):
            names.add(m.group(1).replace(".", "_").replace("-", "_"))
    blob = "\n".join(all_text)
    for name in SYNTHESIZED:
        if name in blob:
            names.add(name)
    return names


def doc_tokens(doc_text: Optional[str] = None) -> tuple[set[str], set[str]]:
    """(exact metric-shaped tokens, wildcard prefixes) from the doc."""
    exact: set[str] = set()
    wildcards: set[str] = set()
    for tok in _TOKEN_RE.findall(
        doc_text if doc_text is not None else DOC.read_text()
    ):
        tok = tok.strip()
        tok = re.sub(r"\{[^}]*\}$", "", tok)  # strip {tags}
        if tok.startswith("pilosa_"):
            tok = tok[len("pilosa_"):]
        if re.fullmatch(r"[a-z][a-z0-9_]*_\*", tok):
            wildcards.add(tok[:-2])
            continue
        if not re.fullmatch(r"[a-z][a-z0-9_]*", tok):
            continue
        base = _EXPORT_SUFFIX_RE.sub("", tok)
        if base.endswith(METRIC_SUFFIXES):
            exact.add(base)
    return exact, wildcards


def metrics_docs_drift(
    src: Optional[set[str]] = None, doc_text: Optional[str] = None
) -> list[str]:
    """Human-readable drift findings (empty = clean). Injectable inputs
    so the rule itself is testable without mutating the repo."""
    src = src if src is not None else source_metrics()
    doc_exact, doc_wild = doc_tokens(doc_text)
    text = doc_text if doc_text is not None else DOC.read_text()
    out = []
    for n in sorted(src):
        if n not in doc_exact and not any(n.startswith(w) for w in doc_wild):
            out.append(f"emitted but not documented: {n}")
    for t in sorted(doc_exact):
        if t not in src:
            out.append(f"documented but not emitted: {t}")
    for fam in sorted(DYNAMIC_FAMILIES):
        if fam not in text:
            out.append(f"dynamic family missing its doc mention: {fam}")
    return out


class MetricDocsChecker(Checker):
    rule = "metric-docs"
    doc = ("every emitted metric documented in docs/observability.md, "
           "every documented metric emitted (PR 3's drift check)")
    scope = ("pilosa_tpu",)
    project_level = True

    def finalize(self, files) -> Iterable[Violation]:
        for finding in metrics_docs_drift():
            yield Violation(
                rule=self.rule, path="docs/observability.md", line=1,
                message=finding,
                hint="add the catalogue entry or remove the dead name "
                     "(python tools/check_metrics_docs.py for the "
                     "two-way report)",
            )


# -- metric-tags: tag-key vocabulary + value-cardinality rule --------------

#: The documented tag-key vocabulary (docs/development.md "Metric
#: discipline"). Keys are bounded enumerations by construction:
ALLOWED_TAG_KEYS = {
    "route",   # HTTP route handler name (route table is finite)
    "method",  # HTTP verb / client op name
    "call",    # PQL call name (parser vocabulary)
    "phase",   # query lifecycle phase (qprofile.PHASES)
    "kind",    # leg/launch kind (batcher LEG_KINDS + program kinds)
    "index",   # index name (operator-created, bounded by schema)
    "field",   # field name (operator-created, bounded by schema)
    "peer",    # peer host:port (bounded by cluster size)
    "node",    # node id (bounded by cluster size)
    "tier",    # container representation tier (dense/array/run)
    "class",   # error class (4xx/5xx/transport/decode)
    "state",   # cluster state enum + connection lifecycle state
               # (server/connplane.py STATES — 8 literals)
    "role",    # thread role (utils/threads.py vocabulary: one literal
               # per spawn site + main/unknown — bounded by
               # construction, NEVER a thread name or peer address)
    "to",      # state-transition target enum
    "won",     # hedge winner (hedge/primary)
    "direction",  # directed-repair resolution (remote_wins/local_wins)
    "reason",  # bounded failure-reason enum (device fallback, import shed)
    "outcome", # recovery outcome enum (replayed/truncated/corrupt)
    "le",      # histogram bucket bound (static BUCKET_BOUNDS)
    "site",    # instrumented-lock site name (utils/locks call sites)
    "program", # device-program ledger kind (program kinds are finite)
    "shape",   # canonical-PQL shape fingerprint (pql/ast.py shape_key:
               # structure only — call vocabulary x schema field names;
               # literals never survive into the key)
}

#: Variable names that smell like raw request content. A tag VALUE
#: rendered from one of these is an unbounded-cardinality series.
FORBIDDEN_VALUE_NAMES = {
    "query", "pql", "sql", "url", "uri", "path", "body", "text",
    "raw", "msg", "message", "detail", "payload", "line",
}


class TagCardinalityChecker(Checker):
    rule = "metric-tags"
    doc = ("with_tags keys must come from the documented vocabulary; "
           "values must never be raw query strings / URLs / bodies")
    # Unscoped: the default tree is pilosa_tpu/ already; explicit paths
    # (fixtures, --changed) must still be checkable.
    scope = ("",)

    def check_file(self, f: SourceFile) -> Iterable[Violation]:
        for node in ast.walk(f.tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "with_tags"
            ):
                continue
            for arg in node.args:
                yield from self._check_tag(f, node, arg)

    def _check_tag(self, f, call, arg) -> Iterable[Violation]:
        key = None
        value_names: list[str] = []
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            key = arg.value.split(":", 1)[0]
        elif isinstance(arg, ast.JoinedStr) and arg.values:
            head = arg.values[0]
            if isinstance(head, ast.Constant) and isinstance(head.value, str):
                key = head.value.split(":", 1)[0]
            for part in arg.values:
                if isinstance(part, ast.FormattedValue) and isinstance(
                    part.value, ast.Name
                ):
                    value_names.append(part.value.id)
        else:
            return  # *tags forwarding / non-literal: out of static reach
        if key is None or not re.fullmatch(r"[a-z][a-z0-9_]*", key or ""):
            if f.waive(self.rule, arg.lineno, arg.end_lineno):
                return
            yield Violation(
                rule=self.rule, path=f.rel, line=arg.lineno,
                message="tag without a literal `key:` prefix",
                hint='tags are "key:value" with a key from the '
                     "documented vocabulary",
            )
            return
        if key not in ALLOWED_TAG_KEYS:
            if not f.waive(self.rule, arg.lineno, arg.end_lineno):
                yield Violation(
                    rule=self.rule, path=f.rel, line=arg.lineno,
                    message=f"unknown tag key {key!r}",
                    hint="new tag keys are a schema decision: add to "
                         "ALLOWED_TAG_KEYS (tools/lint/checkers/"
                         "metrics.py) with a boundedness rationale and "
                         "document it in docs/development.md",
                )
            return
        for vn in value_names:
            if vn.lower() in FORBIDDEN_VALUE_NAMES:
                if f.waive(self.rule, arg.lineno, arg.end_lineno):
                    continue
                yield Violation(
                    rule=self.rule, path=f.rel, line=arg.lineno,
                    message=f"tag value interpolates {vn!r} — raw "
                            "request content is unbounded cardinality",
                    hint="tag a bounded enum (route/op/class) instead; "
                         "the raw value belongs in logs/traces",
                )
