"""Checker registry: one module per rule family, assembled here.

Adding a checker: subclass tools.lint.core.Checker in a new module,
set `rule`/`doc`/`scope`, implement check_file (per-module AST walk)
and/or finalize (cross-file), and register an instance in
make_checkers() below — it is the ONE registry every entry point (CLI,
run_lint, tests) constructs from. Ship a known-bad fixture under
tests/lint_fixtures/ and assert it fires in tests/test_lint.py — a
checker that cannot demonstrate a catch is dead weight (see
docs/development.md "Lint plane").
"""

from __future__ import annotations

from tools.lint.checkers.config_drift import ConfigDriftChecker
from tools.lint.checkers.deadline_scope import DeadlineScopeChecker
from tools.lint.checkers.durable_write import DurableWriteChecker
from tools.lint.checkers.error_codes import ErrorCodeChecker
from tools.lint.checkers.exceptions import ExceptDisciplineChecker
from tools.lint.checkers.hot_serialize import HotSerializeChecker
from tools.lint.checkers.jax_dispatch import JaxDispatchChecker
from tools.lint.checkers.lock_discipline import LockDisciplineChecker
from tools.lint.checkers.metrics import MetricDocsChecker, TagCardinalityChecker
from tools.lint.checkers.monotonic_time import MonotonicTimeChecker
from tools.lint.checkers.shared_state import SharedStateChecker


def make_checkers():
    """Fresh checker instances (some carry per-run state)."""
    return [
        MonotonicTimeChecker(),
        ErrorCodeChecker(),
        JaxDispatchChecker(),
        HotSerializeChecker(),
        LockDisciplineChecker(),
        SharedStateChecker(),
        DeadlineScopeChecker(),
        ExceptDisciplineChecker(),
        MetricDocsChecker(),
        TagCardinalityChecker(),
        DurableWriteChecker(),
        ConfigDriftChecker(),
    ]
