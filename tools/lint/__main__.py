"""CLI: python -m tools.lint [paths...] [--changed] [--rule ID]...

Exit 0 when the tree is clean, 1 with a per-rule report otherwise —
wired into tier-1 by tests/test_lint.py exactly like the metric drift
check, so a new violation fails the build.
"""

from __future__ import annotations

import argparse
import sys
import time
from collections import Counter

from tools.lint.checkers import make_checkers
from tools.lint.core import run_lint


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.lint",
        description="pilosa-tpu project-invariant static analysis",
    )
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to lint (default: pilosa_tpu/)")
    ap.add_argument("--changed", action="store_true",
                    help="fast mode: only files changed vs git HEAD")
    ap.add_argument("--rule", action="append", default=[],
                    help="run only this rule id (repeatable)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalogue and exit")
    ap.add_argument("--list-waivers", action="store_true",
                    help="print the live full-tree waiver census (the "
                         "ratchet ledger's source of truth) and exit")
    args = ap.parse_args(argv)

    checkers = make_checkers()
    if args.list_waivers:
        from tools.lint.core import (
            SourceFile, collect_files, waiver_census,
        )

        if args.paths or args.changed:
            # The census is the ratchet ledger's source of truth: a
            # partial count pasted into waivers.lock would fail every
            # subsequent full run with spurious ratchet-down findings.
            print("--list-waivers always censuses the full default "
                  "tree; ignoring paths/--changed")
        known = {c.rule for c in checkers}
        files = [
            SourceFile.load(p, known)
            for p in collect_files()
            if "__pycache__" not in p.parts
        ]
        census = waiver_census(files)
        for rule in sorted(census):
            print(f"{rule} {census[rule]}")
        for f in files:
            for w in sorted(f.waivers, key=lambda w: w.line):
                print(f"  {f.rel}:{w.line} [{w.rule}] {w.reason}")
        return 0
    if args.list_rules:
        width = max(len(c.rule) for c in checkers)
        for c in checkers:
            print(f"{c.rule:<{width}}  {c.doc}")
        print(f"{'waiver-syntax':<{width}}  "
              "malformed / reasonless / unknown-rule waiver comments")
        print(f"{'unused-waiver':<{width}}  "
              "waivers that no longer match any violation")
        return 0

    t0 = time.monotonic()
    violations = run_lint(
        checkers,
        paths=args.paths or None,
        changed=args.changed,
        rules=set(args.rule) or None,
    )
    dt = time.monotonic() - t0
    if not violations:
        print(f"lint clean ({len(checkers)} checkers, {dt:.2f}s)")
        return 0
    for v in violations:
        print(v.render())
    by_rule = Counter(v.rule for v in violations)
    summary = ", ".join(f"{r}={n}" for r, n in sorted(by_rule.items()))
    print(f"\n{len(violations)} violation(s): {summary} ({dt:.2f}s)")
    return 1


if __name__ == "__main__":
    sys.exit(main())
