"""Shared whole-program call graph + lock index for the concurrency
checkers (lock-discipline, shared-state, deadline-scope).

Extracted from lock_discipline.py (PR 7) when shared-state and
deadline-scope arrived (ISSUE r13): all three rules need the same
conservatively-resolved call graph, the same function inventory, and
(for the first two) the same lock inventory — one resolver means one
set of precision bugs instead of three drifting copies.

Resolution is deliberately an under-approximation of dynamic Python:

- `self.m()` resolves to the enclosing class's method first;
- a bare name resolves to the same module's function, else to the
  unique project-wide function of that name;
- an attribute call resolves to the unique project-wide method of that
  name, or to a small SAME-MODULE union (<= 4 candidates) rendered as a
  `a|b` union key — cross-module unions are refused (merging roaring's
  `_put` with the TPU cache's `_put` would invent call edges and, from
  them, phantom findings);
- names too generic to mean anything (`get`, `append`, `execute`, ...)
  are skipped entirely.

That misses exotic dispatch; it does NOT miss the direct-call patterns
real deadlocks and races are made of.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Iterable, Optional

from tools.lint.core import SourceFile, dotted_name

#: Attribute/method names far too generic to resolve by name union —
#: resolving `d.get(...)` to some class's `get` method would invent
#: call-graph edges (and from them, phantom deadlocks/races).
GENERIC_NAMES = {
    "get", "set", "pop", "popitem", "popleft", "appendleft", "items",
    "keys", "values", "append", "extend", "insert", "remove", "sort",
    "reverse", "copy", "clear", "update", "setdefault", "add",
    "discard", "count", "index", "join", "split", "rsplit", "strip",
    "lstrip", "rstrip", "startswith", "endswith", "encode", "decode",
    "format", "replace", "read", "write", "readline", "readlines",
    "close", "flush", "open", "search", "match", "fullmatch",
    "findall", "finditer", "sub", "group", "groups", "start", "end",
    "partition", "rpartition", "lower", "upper", "title", "tolist",
    "astype", "reshape", "sum", "max", "min", "any", "all", "mean",
    "nonzero", "item", "wait", "acquire", "release", "locked", "name",
    "cancel", "put", "empty", "full", "qsize", "result", "submit",
    "sleep", "is_set",
    # DB-API cursor/connection methods (sqlite in store/): never the
    # project's Executor.execute, which self-resolves above.
    "execute", "executemany", "fetchone", "fetchall", "commit",
    "rollback", "cursor",
}

LOCK_CTORS = {
    "threading.Lock": "Lock",
    "threading.RLock": "RLock",
    "threading.Condition": "Condition",
    # utils/locks.py stall-attributed wrappers: same exclusion semantics
    # as the bare locks, so the discipline/race analyses keep covering
    # the instrumented sites (fragment, WAL append, snapshot mutex,
    # batcher drain, rescache, HBM ledger).
    "InstrumentedLock": "Lock",
    "InstrumentedRLock": "RLock",
    "locks.InstrumentedLock": "Lock",
    "locks.InstrumentedRLock": "RLock",
}


def module_name(rel: str) -> str:
    """Repo-relative path -> short module id used in func/lock ids."""
    name = rel
    for prefix in ("pilosa_tpu/",):
        if name.startswith(prefix):
            name = name[len(prefix):]
    return name[:-3].replace("/", ".") if name.endswith(".py") else name


@dataclass
class FuncInfo:
    func_id: str                  # module.(Class.)name(.nested)
    rel: str
    node: ast.AST
    cls: Optional[str]            # enclosing class name
    #: (callee key, lineno) for every conservatively-resolved call —
    #: populated by CallGraph.collect_calls(); checkers that need more
    #: context at the call site (held locks, deadline cover) rescan the
    #: body themselves via walk_own/iter_own_calls.
    calls: list = field(default_factory=list)


def walk_own(node: ast.AST) -> Iterable[ast.AST]:
    """Yield the nodes that execute as part of THIS function's body:
    nested function/class/lambda bodies are skipped (they run later,
    under their own FuncInfo)."""
    for child in ast.iter_child_nodes(node):
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef, ast.Lambda)):
            continue
        yield child
        yield from walk_own(child)


class CallGraph:
    """Function inventory + conservative call resolution over a set of
    parsed SourceFiles."""

    def __init__(self, files: list[SourceFile]):
        self.funcs: dict[str, FuncInfo] = {}
        self.methods: dict[str, list[str]] = {}    # method name -> func ids
        self.module_funcs: dict[tuple, str] = {}   # (module, name) -> id
        self.class_methods: dict[tuple, str] = {}  # (class, name) -> id
        self.file_of: dict[str, SourceFile] = {f.rel: f for f in files}
        for f in files:
            self._collect(f)

    # -- collection --------------------------------------------------------

    def _collect(self, f: SourceFile) -> None:
        mod = module_name(f.rel)

        def visit(body, path: str, cls: Optional[str]):
            for stmt in body:
                if isinstance(stmt, ast.ClassDef):
                    visit(stmt.body,
                          f"{path}.{stmt.name}" if path else stmt.name,
                          stmt.name)
                elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    fid = (f"{mod}.{path}.{stmt.name}" if path
                           else f"{mod}.{stmt.name}")
                    fn = FuncInfo(func_id=fid, rel=f.rel, node=stmt, cls=cls)
                    self.funcs[fid] = fn
                    self.methods.setdefault(stmt.name, []).append(fid)
                    if cls is not None:
                        self.class_methods.setdefault((cls, stmt.name), fid)
                    else:
                        self.module_funcs[(mod, stmt.name)] = fid
                    visit(
                        [s for s in stmt.body
                         if isinstance(s, (ast.FunctionDef,
                                           ast.AsyncFunctionDef,
                                           ast.ClassDef))],
                        f"{path}.{stmt.name}" if path else stmt.name,
                        cls,
                    )

        visit(f.tree.body, "", None)

    # -- resolution --------------------------------------------------------

    def resolve_call(self, call: ast.Call, fn: FuncInfo) -> Optional[str]:
        """Callee func id (possibly an `a|b` union key), or None."""
        return self.resolve_ref(call.func, fn)

    def resolve_ref(self, func: ast.AST, fn: FuncInfo) -> Optional[str]:
        """Resolve a function REFERENCE (a call target, a Thread
        target=, a pool.submit first argument) to a func id."""
        mod = module_name(fn.rel)
        if isinstance(func, ast.Name):
            fid = self.module_funcs.get((mod, func.id))
            if fid:
                return fid
            # nested def in an enclosing function of this module
            parts = fn.func_id.split(".")
            for depth in range(len(parts), 0, -1):
                cand = ".".join(parts[:depth]) + f".{func.id}"
                if cand in self.funcs:
                    return cand
            # unique project-wide module function of that name
            cands = [
                v for (m, n), v in self.module_funcs.items() if n == func.id
            ]
            return cands[0] if len(cands) == 1 else None
        if isinstance(func, ast.Attribute):
            name = func.attr
            # self.m() resolves by the enclosing class BEFORE the
            # generic-name filter: Executor.execute is a real project
            # method even though bare `.execute(` usually means a DB
            # cursor.
            if (
                isinstance(func.value, ast.Name)
                and func.value.id == "self"
                and fn.cls is not None
            ):
                fid = self.class_methods.get((fn.cls, name))
                if fid:
                    return fid
            if name in GENERIC_NAMES or name.startswith("__"):
                return None
            cands = self.methods.get(name, [])
            if len(cands) == 1:
                return cands[0]
            if 1 < len(cands) <= 4:
                # Small SAME-MODULE union (e.g. StatsClient +
                # NopStatsClient both define gauge): a synthetic union
                # key resolved at fixpoint time. Cross-module unions are
                # refused — merging roaring's Bitmap._put with the TPU
                # cache's _put would smear device dispatch over the
                # whole host bitmap layer and invent violations.
                mods = {self.funcs[c].rel for c in cands if c in self.funcs}
                if len(mods) == 1:
                    return "|".join(sorted(cands))
            return None
        return None

    @staticmethod
    def callee_ids(key: str) -> list[str]:
        return key.split("|") if "|" in key else [key]

    def collect_calls(self) -> None:
        """Populate FuncInfo.calls for every function (context-free
        edges: no lock/deadline state — checkers that need that rescan
        with their own state machine)."""
        for fn in self.funcs.values():
            for n in walk_own(fn.node):
                if isinstance(n, ast.Call):
                    key = self.resolve_call(n, fn)
                    if key is not None:
                        fn.calls.append((key, n.lineno))

    def reachable(self, roots: Iterable[str]) -> set[str]:
        """Transitive closure over FuncInfo.calls (collect_calls first)."""
        seen: set[str] = set()
        stack = [r for r in roots if r in self.funcs]
        while stack:
            fid = stack.pop()
            if fid in seen:
                continue
            seen.add(fid)
            fn = self.funcs.get(fid)
            if fn is None:
                continue
            for key, _ln in fn.calls:
                for callee in self.callee_ids(key):
                    if callee in self.funcs and callee not in seen:
                        stack.append(callee)
        return seen


#: Receiver-name hints for `<pool>.submit(f)` / `<pool>.map(f)` thread
#: dispatch (plain `.map` on anything else is not a thread root).
POOL_HINTS = ("pool", "executor", "workers")


def thread_targets(graph: CallGraph, call: ast.Call,
                   fn: FuncInfo) -> list[str]:
    """Resolved func ids a Call hands to another thread, or []:
    `threading.Thread(target=...)` under any alias, the
    `spawn(role, target, ...)` named-thread helper (utils/threads.py,
    ISSUE 20 — every converted Thread site must stay a thread root or
    the shared-state/lock-discipline analyses go blind to it), and
    `<pool>.submit(f, ...)` / `<pool>.map(f, it)` executor dispatch."""
    out: list[str] = []
    func = call.func
    dn = dotted_name(func) or ""
    if dn.endswith("Thread") and not dn.endswith("ThreadPoolExecutor"):
        for kw in call.keywords:
            if kw.arg == "target":
                ref = graph.resolve_ref(kw.value, fn)
                if ref:
                    out.extend(CallGraph.callee_ids(ref))
    elif dn == "spawn" or dn.endswith(".spawn"):
        # spawn(role, target) — target is positional arg 1 or a
        # `target=` keyword; same resolution as Thread(target=...).
        target = call.args[1] if len(call.args) > 1 else None
        for kw in call.keywords:
            if kw.arg == "target":
                target = kw.value
        if target is not None:
            ref = graph.resolve_ref(target, fn)
            if ref:
                out.extend(CallGraph.callee_ids(ref))
    elif isinstance(func, ast.Attribute) and func.attr in ("submit", "map"):
        recv = func.value
        rname = (recv.attr if isinstance(recv, ast.Attribute)
                 else recv.id if isinstance(recv, ast.Name) else "")
        if any(h in rname.lower() for h in POOL_HINTS) and call.args:
            ref = graph.resolve_ref(call.args[0], fn)
            if ref:
                out.extend(CallGraph.callee_ids(ref))
    return out


def collect_thread_roots(graph: CallGraph) -> dict[str, set[str]]:
    """root name -> entry func ids, across the whole graph. Thread
    targets are one root each; the HTTP handler class's methods are one
    synthetic 'http-request' root (the stdlib server spawns one thread
    per request into do_*, so every routed handler method runs on such
    a thread)."""
    roots: dict[str, set[str]] = {}
    for fn in graph.funcs.values():
        for n in walk_own(fn.node):
            if isinstance(n, ast.Call):
                for target in thread_targets(graph, n, fn):
                    roots.setdefault(target, set()).add(target)
    handler_classes = {
        cls for (cls, name) in graph.class_methods if name == "do_GET"
    }
    request_entries = {
        fid for (cls, name), fid in graph.class_methods.items()
        if cls in handler_classes
    }
    if request_entries:
        roots["http-request"] = request_entries
    return roots


@dataclass
class LockDef:
    lock_id: str      # module.Class.attr | module.NAME | module.func.NAME
    kind: str         # Lock | RLock | Condition
    attr: str         # attribute / variable name
    rel: str
    line: int


class LockIndex:
    """Every `threading.Lock()/RLock()/Condition()` assignment in the
    tree, resolvable from a `with <expr>:` context expression."""

    def __init__(self, files: list[SourceFile], graph: CallGraph):
        self.locks: dict[str, LockDef] = {}
        self.attr_locks: dict[str, list[str]] = {}  # attr name -> lock ids
        for f in files:
            self._collect_module(f)
        for fn in graph.funcs.values():
            self._collect_fn(fn)

    def _add(self, lock_id: str, kind: str, attr: str, rel: str,
             line: int) -> None:
        self.locks[lock_id] = LockDef(lock_id, kind, attr, rel, line)
        self.attr_locks.setdefault(attr, []).append(lock_id)

    @staticmethod
    def lock_ctor(value: ast.AST) -> Optional[str]:
        if isinstance(value, ast.Call):
            return LOCK_CTORS.get(dotted_name(value.func) or "")
        return None

    def _collect_module(self, f: SourceFile) -> None:
        """Module-level and class-level Name-target lock assignments."""
        mod = module_name(f.rel)

        def visit(body):
            for stmt in body:
                if isinstance(stmt, ast.ClassDef):
                    visit(stmt.body)
                elif isinstance(stmt, ast.Assign):
                    kind = self.lock_ctor(stmt.value)
                    if kind:
                        for t in stmt.targets:
                            if isinstance(t, ast.Name):
                                self._add(f"{mod}.{t.id}", kind, t.id,
                                          f.rel, stmt.lineno)

        visit(f.tree.body)

    def _collect_fn(self, fn: FuncInfo) -> None:
        """self.X = Lock() and function-local lock assignments inside
        one function body (nested defs get their own FuncInfo pass)."""
        mod = module_name(fn.rel)
        for n in walk_own(fn.node):
            if not isinstance(n, ast.Assign):
                continue
            kind = self.lock_ctor(n.value)
            if not kind:
                continue
            for t in n.targets:
                if (
                    isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"
                    and fn.cls is not None
                ):
                    self._add(f"{mod}.{fn.cls}.{t.attr}", kind, t.attr,
                              fn.rel, n.lineno)
                elif isinstance(t, ast.Name):
                    # function-local lock (closure rendezvous)
                    self._add(f"{fn.func_id}.{t.id}", kind, t.id,
                              fn.rel, n.lineno)

    def resolve(self, expr: ast.AST, fn: FuncInfo) -> Optional[str]:
        """lock id for a `with <expr>:` context, or None (not a lock)."""
        if isinstance(expr, ast.Attribute):
            attr = expr.attr
            candidates = self.attr_locks.get(attr, [])
            if not candidates:
                return None
            if (
                isinstance(expr.value, ast.Name)
                and expr.value.id == "self"
                or fn.cls is not None
            ):
                # self.X — or a same-class alias like `r._lock` where r
                # is the root instance: prefer the enclosing class's X.
                for c in candidates:
                    if f".{fn.cls}.{attr}" in c:
                        return c
            if len(candidates) == 1:
                return candidates[0]
            return None  # ambiguous attribute: don't invent edges
        if isinstance(expr, ast.Name):
            # innermost function-local, then enclosing funcs, then module
            parts = fn.func_id.split(".")
            for depth in range(len(parts), 0, -1):
                cand = ".".join(parts[:depth]) + f".{expr.id}"
                if cand in self.locks:
                    return cand
            mod = module_name(fn.rel)
            cand = f"{mod}.{expr.id}"
            return cand if cand in self.locks else None
        return None
