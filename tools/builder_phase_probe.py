import os, sys, time
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np, jax
from pilosa_tpu.ops import sparse

dev = None  # default device
shape = (954, 8, 32768)
n = int(np.prod(shape))
rng = np.random.default_rng(0)
flat = np.zeros(n, dtype=np.uint32)
nnz = n // 6
pos = rng.choice(n, size=nnz, replace=False)
flat[pos] = rng.integers(1, 2**32, size=nnz, dtype=np.uint32)
print("synthetic h-like stack: 1GB, nnz 16.7%", flush=True)

t0 = time.time()
ts = sparse.warm_chunk_programs(jax.devices()[0])
ts.join()
print(f"chunk program warm (4 buckets) {time.time()-t0:.1f}s", flush=True)

b = sparse.ChunkedStackBuilder(None, shape)
t0 = time.time()
step = sparse.CHUNK_WORDS
for i in range(0, n, step):
    b.feed(flat[i:i+step])
t_feed = time.time() - t0
print(f"feed (compress+device_put) {t_feed:.1f}s", flush=True)
t0 = time.time()
out = b.finish()
t_fin = time.time() - t0
print(f"finish (decomp+place chain) {t_fin:.1f}s", flush=True)
t0 = time.time()
s = int(np.asarray(out[0, 0, :4]).sum())
print(f"readback probe {time.time()-t0:.1f}s", flush=True)
np.testing.assert_array_equal(np.asarray(out).reshape(-1)[:1<<20], flat[:1<<20])
print("prefix bit-exact", flush=True)
