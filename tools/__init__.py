"""Developer tooling for the pilosa-tpu repo (lint plane, probes, bench
helpers). Package-shaped so `python -m tools.lint` works from the repo
root."""
