"""Headline benchmark: PQL Intersect+Count throughput at the north-star
shape (954 shards = 1.0B columns, BASELINE.json), TPU vs the numpy oracle.

Measured paths:

- batched throughput: Q same-shape Count(Intersect(Row,Row)) queries fused
  into ONE device dispatch over stacked HBM blocks (the serving shape;
  per-dispatch blocking sync through this environment's relay-attached
  chip costs ~78 ms regardless of work, so batching is the only honest
  throughput measurement — single-query latency is reported separately).
- single-query p50/p99 latency: one unbatched dispatch per query.
- TopN latency: exact popcount-per-row + sort over the whole field.

Baseline: the same queries through the CPU oracle backend — **vectorized
numpy roaring, NOT the Go reference**. The reference publishes no absolute
numbers and no Go toolchain exists in this image (BASELINE.md); vs_baseline
is therefore labeled vs_numpy_oracle. Rough calibration: the Go engine's
per-container AND+popcount loops are typically 3-10x faster than this
numpy oracle on equal hardware, so divide vs_baseline by ~10 for a
conservative Go-relative estimate.

Roofline context: each query touches 2 rows x SHARDS x 128 KiB = ~250 MB
of HBM at the 954-shard shape; hbm_gbps reports the achieved read rate so
the "fast" claim is bandwidth-grounded (VERDICT r1 #6).

Prints ONE JSON line {metric, value, unit, vs_baseline, ...}.

Env knobs: BENCH_SHARDS (default 954 = 1B cols), BENCH_ROWS (8),
BENCH_DENSITY (0.05), BENCH_BATCH (256), BENCH_SECONDS (10),
BENCH_LATENCY_N (30).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

from pilosa_tpu.core import Holder
from pilosa_tpu.exec import Executor
from pilosa_tpu.exec.tpu import TPUBackend
from pilosa_tpu.pql import parse_string
from pilosa_tpu.shardwidth import SHARD_WIDTH

SHARDS = int(os.environ.get("BENCH_SHARDS", "954"))  # 954*2^20 > 1e9 columns
ROWS = int(os.environ.get("BENCH_ROWS", "8"))
DENSITY = float(os.environ.get("BENCH_DENSITY", "0.05"))
BATCH = int(os.environ.get("BENCH_BATCH", "256"))
SECONDS = float(os.environ.get("BENCH_SECONDS", "10"))
LATENCY_N = int(os.environ.get("BENCH_LATENCY_N", "30"))

WORDS = SHARD_WIDTH // 32


def build_index(h: Holder):
    idx = h.create_index("bench")
    rng = np.random.default_rng(42)
    n_bits = int(SHARD_WIDTH * DENSITY)
    for fname in ("f", "g"):
        field = idx.create_field(fname)
        for shard in range(SHARDS):
            base = shard * SHARD_WIDTH
            rows = np.repeat(np.arange(ROWS, dtype=np.uint64), n_bits)
            cols = rng.integers(0, SHARD_WIDTH, ROWS * n_bits, dtype=np.uint64) + base
            field.import_bits(rows, cols)
    return idx


def bench_tpu(holder, queries) -> tuple[float, list[int]]:
    be = TPUBackend(holder)
    shards = list(range(SHARDS))
    calls = [parse_string(q).calls[0].children[0] for q in queries]
    # warmup: compile + upload blocks
    first = be.count_batch("bench", calls[:BATCH], shards)
    n_done = 0
    t0 = time.time()
    while time.time() - t0 < SECONDS:
        be.count_batch("bench", calls[:BATCH], shards)
        n_done += BATCH
    dt = time.time() - t0
    return n_done / dt, first, be


def bench_tpu_single(be, queries) -> tuple[float, float]:
    """Unbatched: one dispatch + one scalar readback per query."""
    shards = list(range(SHARDS))
    calls = [parse_string(q).calls[0].children[0] for q in queries[:LATENCY_N]]
    be.count_shards("bench", calls[0], shards)  # warm
    lat = []
    for c in calls:
        t0 = time.perf_counter()
        be.count_shards("bench", c, shards)
        lat.append(time.perf_counter() - t0)
    lat.sort()
    return lat[len(lat) // 2], lat[min(len(lat) - 1, int(len(lat) * 0.99))]


def bench_topn(be) -> float:
    """Exact TopN over the whole field: p50 of LATENCY_N runs."""
    shards = list(range(SHARDS))
    be.topn_field("bench", "f", shards, 10)  # warm
    lat = []
    for _ in range(max(5, LATENCY_N // 3)):
        t0 = time.perf_counter()
        be.topn_field("bench", "f", shards, 10)
        lat.append(time.perf_counter() - t0)
    lat.sort()
    return lat[len(lat) // 2]


def bench_cpu(holder, parsed_queries) -> float:
    """Same pre-parsed queries through the numpy-oracle executor."""
    ex = Executor(holder)
    n_done = 0
    t0 = time.time()
    # At the 1B-column shape a single oracle query takes seconds; run at
    # least 3 so the rate is a measurement, not one sample.
    while time.time() - t0 < SECONDS or n_done < 3:
        ex.execute("bench", parsed_queries[n_done % len(parsed_queries)])
        n_done += 1
    dt = time.time() - t0
    return n_done / dt


def main():
    h = Holder(None)  # in-memory: bench measures query path, not disk
    h.open()
    t_build = time.time()
    build_index(h)
    t_build = time.time() - t_build

    rng = np.random.default_rng(7)
    queries = [
        f"Count(Intersect(Row(f={int(rng.integers(0, ROWS))}), Row(g={int(rng.integers(0, ROWS))})))"
        for _ in range(BATCH)
    ]
    parsed = [parse_string(q) for q in queries]

    cpu_qps = bench_cpu(h, parsed)
    tpu_qps, tpu_first, be = bench_tpu(h, queries)
    p50, p99 = bench_tpu_single(be, queries)
    topn_p50 = bench_topn(be)

    # Correctness cross-check: TPU batch results must equal the CPU oracle.
    ex = Executor(h)
    for i in sorted({0, BATCH // 2, BATCH - 1}):
        want = ex.execute("bench", queries[i])[0]
        assert tpu_first[i] == want, (i, tpu_first[i], want)

    # HBM roofline: bytes of row data each query's AND+popcount touches.
    bytes_per_query = 2 * SHARDS * WORDS * 4
    hbm_gbps = tpu_qps * bytes_per_query / 1e9

    print(
        json.dumps(
            {
                "metric": "intersect_count_qps",
                "value": round(tpu_qps, 1),
                "unit": "queries/s",
                "vs_baseline": round(tpu_qps / cpu_qps, 2) if cpu_qps else None,
                "baseline": "numpy_oracle_cpu (NOT Go/roaring; see BASELINE.md)",
                "baseline_qps": round(cpu_qps, 2),
                "single_query_p50_ms": round(p50 * 1e3, 2),
                "single_query_p99_ms": round(p99 * 1e3, 2),
                "topn_p50_ms": round(topn_p50 * 1e3, 2),
                "hbm_read_gbps": round(hbm_gbps, 1),
                "bytes_touched_per_query": bytes_per_query,
                "build_seconds": round(t_build, 1),
                "config": {
                    "shards": SHARDS,
                    "columns": SHARDS * SHARD_WIDTH,
                    "rows_per_field": ROWS,
                    "density": DENSITY,
                    "batch": BATCH,
                },
            }
        )
    )


if __name__ == "__main__":
    main()
